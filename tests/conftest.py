"""Test configuration: force CPU JAX with 8 virtual devices.

This is the CI analog of the reference's portable fallback path
(roaring/assembly_generic.go) — everything must pass without a TPU.  The
8 virtual CPU devices let the sharded/mesh tests (parallel/) exercise real
GSPMD partitioning and collectives.

Must run before jax is imported anywhere.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("PILOSA_TPU_TEST_REAL_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # A sitecustomize hook (remote-TPU plugin) may have imported jax before
    # this conftest ran, in which case jax has already latched
    # JAX_PLATFORMS from the outer environment and the env var above is
    # too late.  Force the config directly — backends are created lazily,
    # so as long as no computation ran yet this reliably pins CPU (and
    # keeps the suite off a possibly-unreachable remote TPU tunnel).
    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", "cpu")
        # XLA_FLAGS was latched at that import too — restore the 8-device
        # virtual CPU mesh or the parallel/ suite silently skips.
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running sweep excluded from tier-1 (-m 'not slow')",
    )


def pytest_addoption(parser):
    parser.addoption(
        "--run-tpu",
        action="store_true",
        default=False,
        help="run tests that require a real TPU (use with PILOSA_TPU_TEST_REAL_TPU=1)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# -- native library build (native/Makefile) ---------------------------------
#
# Tier-1 builds native/libpilosa_native.so BEFORE the suite runs so every
# test exercises the same lanes CI ships (native.load() auto-builds via
# the Makefile on first use).  Without a compiler the Python fallbacks
# serve and the native-only tests (test_writelane) skip with a reason.

@pytest.fixture(scope="session", autouse=True)
def _native_library_build():
    import shutil

    from pilosa_tpu import native

    if native.load() is None and not os.environ.get("PILOSA_TPU_NO_NATIVE"):
        missing = [t for t in ("make", "g++") if shutil.which(t) is None]
        reason = (
            f"toolchain missing: {', '.join(missing)}" if missing
            else "make -C native failed"
        )
        sys.stderr.write(
            f"\n[conftest] native library unavailable ({reason}); "
            "Python fallbacks serve, native-only tests skip\n"
        )
    yield


# -- runtime lock checker (pilosa_tpu/analysis/lockcheck.py) ----------------
#
# The tier-1 concurrency/replica/qos/writelane/ingest/qcache suites run
# with the lock checker ON: every named lock created during these tests
# feeds the cross-thread acquisition-order graph, blocking calls under a
# lock are caught, declared guarded fields (`_guarded_by_`) refine
# per-field candidate locksets (the Eraser-style race detector), and a
# test that recorded any violation FAILS with the checker's report.
# Subprocess group workers inherit PILOSA_TPU_LOCK_CHECK=1 via the env
# and self-enable at import (violations print to their stderr at exit).

_LOCKCHECK_MODULES = ("test_concurrency", "test_replica", "test_qos",
                      "test_writelane", "test_ingest", "test_qcache",
                      "test_freethread")


def _lockcheck_wanted(item) -> bool:
    name = item.module.__name__ if item.module else ""
    return any(name.startswith(m) for m in _LOCKCHECK_MODULES)


@pytest.fixture(autouse=True)
def _lockcheck_gate(request):
    item = request.node
    try:
        wanted = _lockcheck_wanted(item)
    except Exception:
        wanted = False
    if not wanted:
        yield
        return
    from pilosa_tpu.analysis import lockcheck

    os.environ[lockcheck.ENV_VAR] = "1"  # spawned group workers inherit
    lockcheck.enable()
    lockcheck.reset()
    try:
        yield
    finally:
        os.environ.pop(lockcheck.ENV_VAR, None)
        violations = lockcheck.take_violations()
        lockcheck.disable()
        if violations:
            pytest.fail(
                f"lock checker recorded {len(violations)} violation(s):\n\n"
                + "\n\n".join(v.describe() for v in violations),
                pytrace=False,
            )


# -- replica-protocol trace conformance (pilosa_tpu/analysis/spec.py) -------
#
# The fault-seam e2e suite (test_replica_recovery) runs with the
# protocol event collector installed: every router/WAL/catch-up/resync
# transition emits an event record (zero cost when the collector is
# off), and at test teardown the recorded trace is validated against
# the executable write-protocol model — sequence monotonicity, quorum
# commits, tombstone/apply exclusion, per-epoch applied-mark
# monotonic-max, compaction floors, read-your-writes.  A reordering bug
# the assertions missed still fails the test with the exact protocol
# violation.  (Subprocess group events are invisible — the trace covers
# the in-process router side, which owns every invariant checked.)

_SPEC_TRACE_MODULES = ("test_replica_recovery", "test_replica_shard")


@pytest.fixture(autouse=True)
def _spec_trace_gate(request):
    item = request.node
    try:
        name = item.module.__name__ if item.module else ""
    except Exception:
        name = ""
    if not any(name.startswith(m) for m in _SPEC_TRACE_MODULES):
        yield
        return
    from pilosa_tpu.analysis import spec

    events = spec.install_collector()
    try:
        yield
    finally:
        spec.uninstall_collector()
        problems = spec.check_trace(events)
        if problems:
            pytest.fail(
                "replica-protocol trace conformance: "
                f"{len(problems)} violation(s) over {len(events)} event(s):\n"
                + "\n".join("  " + p for p in problems),
                pytrace=False,
            )
