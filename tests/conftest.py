"""Test configuration: force CPU JAX with 8 virtual devices.

This is the CI analog of the reference's portable fallback path
(roaring/assembly_generic.go) — everything must pass without a TPU.  The
8 virtual CPU devices let the sharded/mesh tests (parallel/) exercise real
GSPMD partitioning and collectives.

Must run before jax is imported anywhere.
"""

import os

if not os.environ.get("PILOSA_TPU_TEST_REAL_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--run-tpu",
        action="store_true",
        default=False,
        help="run tests that require a real TPU (use with PILOSA_TPU_TEST_REAL_TPU=1)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)
