"""Device-first bulk build + Arrow egress (pilosa_tpu/bulk).

Pins the build kernels (composite-key sort, CSR word lane, jax/numpy
parity on ragged shapes), the fragment overlay commit (dense planes and
sparse word OR, edge cases: empty input, slice growth mid-batch,
overlap with existing storage), the lazy materialization ledger (debt
on commit, pay-on-touch, budgeted drain, close-with-debt persistence),
the seeded differential contract (bulk-built fragments digest-identical
to streamed), and both front doors end to end (HTTP server and the
lockstep service) including the Arrow export -> re-ingest round trip.

Arrow-dependent tests carry the reason-logged skip contract: a host
without pyarrow skips them by name (the packed-PI64 lanes still run),
it does not fail tier-1.
"""

import json
import tempfile
import threading
import time
import urllib.request
import zlib

import numpy as np
import pytest

from pilosa_tpu import ingest
from pilosa_tpu.bulk import build as bulk_build
from pilosa_tpu.bulk import ingress
from pilosa_tpu.bulk.build import (
    WORDS_PER_PLANE,
    build_planes_numpy,
    build_words_numpy,
    group_pairs,
    plane_positions,
)
from pilosa_tpu.bulk.lazy import LEDGER, MaterializationLedger
from pilosa_tpu.config import Config
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.ops import bitwise as bw
from pilosa_tpu.pilosa import SLICE_WIDTH
from pilosa_tpu.qos import CLASS_WRITE, classify_request
from pilosa_tpu.server.client import Client
from pilosa_tpu.server.server import Server

requires_pyarrow = pytest.mark.skipif(
    not ingest.arrow_available(),
    reason="pyarrow unavailable on this host: arrow bulk/egress lanes "
    "skipped (packed-PI64 lanes still covered)",
)


# -- reference ---------------------------------------------------------------

def _reference_planes(rows, cols):
    """Brute-force ground truth: {(slice, row): set(local cols)}."""
    ref: dict = {}
    for r, c in zip(np.asarray(rows).tolist(), np.asarray(cols).tolist()):
        ref.setdefault((c // SLICE_WIDTH, r), set()).add(c % SLICE_WIDTH)
    return ref


def _planes_to_sets(slice_ids, row_ids, planes):
    out = {}
    for s, r, plane in zip(slice_ids.tolist(), row_ids.tolist(), planes):
        out[(s, r)] = set(plane_positions(plane).tolist())
    return out


# -- build kernels -----------------------------------------------------------

def test_group_pairs_empty():
    s, r, gid, local = group_pairs([], [])
    assert len(s) == len(r) == len(gid) == len(local) == 0


def test_group_pairs_orders_and_segments():
    rows = np.array([5, 1, 5, 1, 5], dtype=np.uint64)
    cols = np.array([3, SLICE_WIDTH + 1, 3, 2, 1], dtype=np.uint64)
    s, r, gid, local = group_pairs(rows, cols)
    # groups sorted by (slice, row); within a group locals nondecreasing
    assert list(zip(s.tolist(), r.tolist())) == [(0, 1), (0, 5), (1, 1)]
    assert gid.tolist() == sorted(gid.tolist())
    for g in set(gid.tolist()):
        ll = local[gid == g]
        assert ll.tolist() == sorted(ll.tolist())


def test_group_pairs_bigid_fallback_matches_fastpath():
    """Slice/row ids past the 44-bit composite budget take the lexsort
    lane; both lanes produce the identical group table on data that
    fits either."""
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 100, size=2000).astype(np.uint64)
    cols = rng.integers(0, 8 * SLICE_WIDTH, size=2000).astype(np.uint64)
    fast = group_pairs(rows, cols)
    # Force the fallback by planting one huge row id, then restricting
    # the comparison to the shared groups' shape via the reference.
    big_rows = np.concatenate([rows, np.array([1 << 50], dtype=np.uint64)])
    big_cols = np.concatenate([cols, np.array([3], dtype=np.uint64)])
    slow = group_pairs(big_rows, big_cols)
    ref = _reference_planes(big_rows, big_cols)
    assert len(slow[0]) == len(ref)
    # and the fast lane alone matches ITS reference exactly
    assert _planes_to_sets(*build_planes_numpy(rows, cols)) == \
        _reference_planes(rows, cols)
    assert len(fast[0]) == len(_reference_planes(rows, cols))


def test_build_planes_numpy_matches_reference():
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 16, size=5000).astype(np.uint64)
    cols = rng.integers(0, 3 * SLICE_WIDTH, size=5000).astype(np.uint64)
    s, r, planes = build_planes_numpy(rows, cols)
    assert _planes_to_sets(s, r, planes) == _reference_planes(rows, cols)


def test_build_words_matches_dense_planes():
    """The sparse CSR lane is the SAME build as the dense lane, in
    nonzero-word form: reassembling its words reproduces the planes."""
    rng = np.random.default_rng(4)
    rows = rng.integers(0, 8, size=4000).astype(np.uint64)
    cols = rng.integers(0, 2 * SLICE_WIDTH, size=4000).astype(np.uint64)
    ds, dr, planes = build_planes_numpy(rows, cols)
    ws, wr, counts, widx, wvals = build_words_numpy(rows, cols)
    assert ds.tolist() == ws.tolist() and dr.tolist() == wr.tolist()
    assert int(counts.sum()) == len(widx) == len(wvals)
    offs = np.concatenate([[0], np.cumsum(counts)])
    for g in range(len(ws)):
        lo, hi = offs[g], offs[g + 1]
        rebuilt = np.zeros(WORDS_PER_PLANE, dtype=np.uint32)
        rebuilt[widx[lo:hi]] = wvals[lo:hi]
        assert np.array_equal(rebuilt, planes[g])
        # word indices unique + ascending within the group (the
        # fancy-indexed OR in bulk_or_words depends on it)
        assert np.all(np.diff(widx[lo:hi]) > 0)


def test_build_words_empty():
    s, r, counts, widx, wvals = build_words_numpy([], [])
    assert len(s) == len(r) == len(counts) == len(widx) == len(wvals) == 0


def test_build_jax_matches_numpy_ragged_last_slice():
    """Device lane parity on a ragged shape: the last slice holds a
    single pair, duplicates included (the jax dedup makes scatter-add
    equal scatter-or)."""
    jax = pytest.importorskip("jax")  # noqa: F841
    rng = np.random.default_rng(5)
    rows = rng.integers(0, 6, size=3000).astype(np.uint64)
    cols = rng.integers(0, 2 * SLICE_WIDTH, size=3000).astype(np.uint64)
    rows = np.concatenate([rows, rows[:100],  # duplicates
                           np.array([2], dtype=np.uint64)])
    cols = np.concatenate([cols, cols[:100],
                           np.array([5 * SLICE_WIDTH + 17], dtype=np.uint64)])
    ns, nr, nplanes = build_planes_numpy(rows, cols)
    js, jr, jplanes = bulk_build.build_planes_jax(rows, cols)
    assert ns.tolist() == js.tolist() and nr.tolist() == jr.tolist()
    assert np.array_equal(nplanes, jplanes)


def test_plane_positions_matches_roaring_bit_order():
    from pilosa_tpu.roaring import Bitmap

    pos = np.array([0, 1, 31, 32, 63, 1000, SLICE_WIDTH - 1], dtype=np.uint64)
    plane = np.zeros(WORDS_PER_PLANE, dtype=np.uint32)
    for p in pos.tolist():
        plane[p // 32] |= np.uint32(1) << np.uint32(p % 32)
    assert plane_positions(plane).tolist() == pos.tolist()
    b = Bitmap()
    b.add_many(pos)
    assert plane_positions(plane, base=0).tolist() == list(b)


def test_count_words_matches_reference():
    rng = np.random.default_rng(6)
    x = rng.integers(0, 1 << 32, size=999, dtype=np.uint64).astype(np.uint32)
    assert bw.count_words(x) == bw.np_count(x)
    assert bw.count_words(np.zeros(0, dtype=np.uint32)) == 0


# -- fragment overlay commit -------------------------------------------------

@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0,
                 cache_type="ranked")
    f.open()
    yield f
    if f._open:
        f.close()


def _commit_words(f, rows, cols):
    s, r, counts, widx, wvals = build_words_numpy(rows, cols)
    offs = np.concatenate([[0], np.cumsum(counts)])
    assert set(s.tolist()) <= {0}
    return f.bulk_or_words(r, counts, widx, wvals)


def test_bulk_or_words_serves_merged_and_materializes_on_touch(frag):
    frag.set_bit(3, 10)  # pre-existing roaring bit overlapping the bulk rows
    rows = np.array([3, 3, 4], dtype=np.uint64)
    cols = np.array([10, 11, 99], dtype=np.uint64)
    _commit_words(frag, rows, cols)
    # merged read-your-writes before any materialization
    assert frag.row_count(3) == 2  # {10, 11}: overlap deduplicated
    assert frag.row_count(4) == 1
    assert frag._bulk_planes  # still lazy
    # roaring-shaped touch pays the debt and converges
    assert frag.contains(3, 11)
    csum = frag.checksum()
    assert not frag._bulk_planes
    # equal to the same bits set directly
    g = Fragment(frag.path + ".b", "i", "f2", "standard", 0)
    g.open()
    g.set_bit(3, 10), g.set_bit(3, 11), g.set_bit(4, 99)
    assert g.checksum() == csum
    g.close()


def test_bulk_or_words_validates_csr():
    with tempfile.TemporaryDirectory() as d:
        f = Fragment(d + "/0", "i", "f", "standard", 0)
        f.open()
        try:
            with pytest.raises(ValueError):
                f.bulk_or_words(np.array([1]), np.array([1, 2]),
                                np.array([0]), np.array([1], dtype=np.uint32))
            with pytest.raises(ValueError):
                f.bulk_or_words(np.array([1]), np.array([2]),  # sum != len
                                np.array([0]), np.array([1], dtype=np.uint32))
            with pytest.raises(ValueError):
                f.bulk_or_words(np.array([1]), np.array([1]),
                                np.array([WORDS_PER_PLANE]),  # out of range
                                np.array([1], dtype=np.uint32))
        finally:
            f.close()


def test_apply_bulk_empty_and_slice_growth(tmp_path):
    """Edge cases via the full ingress path: a zero-pair chunk commits
    nothing, and a later chunk touching NEW slices grows the fragment
    set mid-batch."""
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path / "d"))
    h.open()
    try:
        idx = h.create_index("i")
        fr = idx.create_frame("f", FrameOptions())
        assert ingress.apply_bulk(fr, [], []) == 0
        std = fr.view("standard")
        assert std is None or not std.fragments
        # chunk 1: slice 0 only
        ingress.apply_bulk(fr, np.array([1, 2], dtype=np.uint64),
                           np.array([5, 6], dtype=np.uint64))
        assert sorted(fr.view("standard").fragments) == [0]
        # chunk 2: grows to slice 2 (slice 1 stays absent — sparse)
        ingress.apply_bulk(fr, np.array([1], dtype=np.uint64),
                           np.array([2 * SLICE_WIDTH + 7], dtype=np.uint64))
        assert sorted(fr.view("standard").fragments) == [0, 2]
        assert fr.view("standard").fragment(2).row_count(1) == 1
    finally:
        h.close()


def test_close_with_debt_persists(tmp_path):
    """A fragment closed while carrying overlay debt materializes on
    close: reopening serves the bulk bits from storage."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    _commit_words(f, np.array([7, 7], dtype=np.uint64),
                  np.array([100, 200], dtype=np.uint64))
    assert f._bulk_planes
    f.close()
    g = Fragment(f.path, "i", "f", "standard", 0)
    g.open()
    try:
        assert g.contains(7, 100) and g.contains(7, 200)
    finally:
        g.close()


# -- lazy ledger -------------------------------------------------------------

def test_ledger_tracks_debt_and_budget_drain(frag):
    """Budget semantics on the process ledger (fragments report their
    own materialization back to it, so the drain must run against the
    same registry the commit noted debt in)."""
    _commit_words(frag, np.array([1], dtype=np.uint64),
                  np.array([5], dtype=np.uint64))
    assert LEDGER.pending_count() >= 1
    assert LEDGER.materialize_some(0) == 0  # <=0 budget: fully lazy
    assert frag._bulk_planes
    assert LEDGER.materialize_some(5000) >= 1
    assert not frag._bulk_planes
    assert LEDGER.pending_count() == 0
    # debt already paid: the drain is a no-op
    assert LEDGER.materialize_some(5000) == 0


def test_ledger_weakref_never_pins_fragments():
    led = MaterializationLedger()

    class _F:  # minimal stand-in with the materialize hook
        def materialize_bulk(self):
            pass

    f = _F()
    led.note_pending(f)
    assert led.pending_count() == 1
    del f
    import gc

    gc.collect()
    assert led.pending_count() == 0


def test_global_ledger_pays_on_touch(frag):
    before = LEDGER.pending_count()
    _commit_words(frag, np.array([2], dtype=np.uint64),
                  np.array([9], dtype=np.uint64))
    assert LEDGER.pending_count() == before + 1
    frag.checksum()  # storage-shaped touch
    assert LEDGER.pending_count() == before


# -- seeded differential: bulk-built == streamed -----------------------------

@pytest.mark.parametrize("inverse", [False, True])
def test_bulk_differential_digest_vs_streamed(tmp_path, inverse):
    """The tentpole contract: the SAME seeded pairs through the bulk
    build and through the streamed set_bits door produce digest-
    identical fragments, standard and inverse views both."""
    from pilosa_tpu.core.holder import Holder

    rng = np.random.default_rng(11)
    rows = rng.integers(0, 40, size=20000).astype(np.uint64)
    cols = rng.integers(0, 3 * SLICE_WIDTH, size=20000).astype(np.uint64)
    h = Holder(str(tmp_path / "d"))
    h.open()
    try:
        idx = h.create_index("i")
        fb = idx.create_frame("b", FrameOptions(inverse_enabled=inverse))
        fs = idx.create_frame("s", FrameOptions(inverse_enabled=inverse))
        # bulk door applies in chunks (exercises overlay accumulation)
        for i in range(0, len(rows), 4096):
            ingress.apply_bulk(fb, rows[i:i + 4096], cols[i:i + 4096])
        ingress.complete_bulk(fb)
        ingest.apply_columnar(fs, rows, cols)
        ingest.recalc_frame_caches(fs)
        views = ["standard"] + (["inverse"] if inverse else [])
        for vname in views:
            vb, vs = fb.view(vname), fs.view(vname)
            assert sorted(vb.fragments) == sorted(vs.fragments)
            for s in vb.fragments:
                assert vb.fragment(s).checksum() == vs.fragment(s).checksum(), (
                    f"{vname}/{s} diverged"
                )
    finally:
        h.close()


# -- HTTP front door ---------------------------------------------------------

def test_bulk_route_classifies_as_write():
    assert classify_request("POST", "/index/i/frame/f/bulk", b"") == CLASS_WRITE


@pytest.fixture
def srv():
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(data_dir=d, host="127.0.0.1:0", engine="numpy",
                     stats="expvar", qcache_enabled=False)
        s = Server(cfg)
        s.open()
        try:
            c = Client(s.host)
            c.create_index("i")
            c.create_frame("i", "f")
            yield s, c
        finally:
            s.close()


def test_bulk_end_to_end_http(srv):
    s, c = srv
    rng = np.random.default_rng(12)
    rows = rng.integers(0, 30, size=20000).astype(np.uint64)
    cols = rng.integers(0, 2 * SLICE_WIDTH, size=20000).astype(np.uint64)
    out = c.bulk_stream("i", "f", rows, cols, chunk_pairs=4096)
    assert out["done"] and out["ops"] == 20000
    # served reads merge the overlay; TopN fresh at completion
    r = c.execute_query("i", 'Count(Bitmap(rowID=7, frame="f"))')
    assert r["results"][0]["n"] == len(np.unique(cols[rows == 7]))
    uniq = {int(x): len(np.unique(cols[rows == x])) for x in np.unique(rows)}
    top = c.execute_query("i", 'TopN(frame="f", n=1)')["results"][0]["pairs"]
    assert top[0]["count"] == max(uniq.values())
    # streamed twin digest parity through the OTHER door
    c.create_frame("i", "g")
    assert c.ingest_stream("i", "g", rows, cols, chunk_pairs=4096)["done"]
    idx = s.holder.index("i")
    for sl in sorted(idx.frame("g").view("standard").fragments):
        assert idx.frame("f").view("standard").fragment(sl).checksum() == \
            idx.frame("g").view("standard").fragment(sl).checksum()
    # bulk.* counters registered and moving (fragment-level counters
    # carry index/frame tags, so match on the flat dump)
    v = json.loads(
        urllib.request.urlopen(f"http://{s.host}/debug/vars").read()
    )
    assert v["bulk.pairs"] >= 20000
    flat = json.dumps(v)
    assert "bulk.commit_rows" in flat and "bulk.build" in flat


@requires_pyarrow
def test_arrow_export_reingest_roundtrip(srv):
    s, c = srv
    rng = np.random.default_rng(13)
    rows = rng.integers(0, 20, size=5000).astype(np.uint64)
    cols = rng.integers(0, SLICE_WIDTH, size=5000).astype(np.uint64)
    assert c.bulk_stream("i", "f", rows, cols)["done"]
    a = c.export_arrow("i", "f", "standard", 0)
    c.create_frame("i", "rt")
    crc = zlib.crc32(a)
    status, out = c.ingest_chunk("i", "rt", 0, len(a), crc, a, ccrc=crc,
                                 door="bulk", arrow=True)
    assert status == 200 and out["done"]
    b = c.export_arrow("i", "rt", "standard", 0)
    assert a == b  # deterministic egress: byte-identical round trip
    r2, c2 = ingest.decode_arrow(a)
    ref = sorted(zip(rows.tolist(), cols.tolist()))
    got = sorted(set(zip(r2.tolist(), c2.tolist())))
    assert got == sorted(set(ref))


@requires_pyarrow
def test_arrow_ingest_hardening_http(srv):
    """Producer-variety arrow chunks through the HTTP bulk door: extra
    columns and dictionary-encoded ids apply; schema mistakes answer
    pointed 400s."""
    import io

    import pyarrow as pa

    _, c = srv
    rows = np.array([1, 1, 2], dtype=np.uint64)
    cols = np.array([10, 11, 12], dtype=np.uint64)
    t = pa.table({
        "row": pa.array(rows.tolist(), type=pa.int32()).dictionary_encode(),
        "col": cols,
        "extra": ["a", "b", "c"],
    })
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    body = sink.getvalue()
    crc = zlib.crc32(body)
    status, out = c.ingest_chunk("i", "f", 0, len(body), crc, body, ccrc=crc,
                                 door="bulk", arrow=True)
    assert status == 200 and out["done"]
    assert c.execute_query("i", 'Count(Bitmap(rowID=1, frame="f"))')[
        "results"][0]["n"] == 2
    # missing required column: pointed 400 naming it
    t2 = pa.table({"row": rows})
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, t2.schema) as w:
        w.write_table(t2)
    body = sink.getvalue()
    from pilosa_tpu.server.client import ClientError

    with pytest.raises(ClientError) as ei:
        c.ingest_chunk("i", "f", 0, len(body), zlib.crc32(body), body,
                       ccrc=zlib.crc32(body), door="bulk", arrow=True)
    assert ei.value.status == 400 and "col" in str(ei.value)


# -- lockstep front door -----------------------------------------------------

def _lockstep_svc(tmp_path):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    svc = LockstepService(
        h, control_addr=("127.0.0.1", 0), http_addr=("127.0.0.1", 0)
    )
    threading.Thread(target=svc.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 10
    while svc._httpd is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc._httpd is not None
    return h, svc, f"http://{svc.http_addr[0]}:{svc.http_addr[1]}"


def _post(base, path, data, timeout=30):
    rq = urllib.request.Request(base + path, data=data, method="POST")
    with urllib.request.urlopen(rq, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_lockstep_front_end_bulk(tmp_path):
    """The lockstep front end serves the bulk wire: rank 0 decodes each
    chunk once and replays the pairs through the replicated total order;
    every rank runs the build kernel; the completion recalc rides its
    own reserved entry — reads right after are fresh and digest-equal
    to the streamed door."""
    h, svc, base = _lockstep_svc(tmp_path)
    try:
        rng = np.random.default_rng(14)
        rows = rng.integers(0, 12, size=6000).astype(np.uint64)
        cols = rng.integers(0, 2 * SLICE_WIDTH, size=6000).astype(np.uint64)
        frames = [
            ingest.encode_packed(rows[i:i + 2048], cols[i:i + 2048])
            for i in range(0, len(rows), 2048)
        ]
        total = sum(len(f) for f in frames)
        crc = 0
        for fb in frames:
            crc = zlib.crc32(fb, crc)
        off = 0
        for fb in frames:
            out = _post(
                base,
                f"/index/i/frame/f/bulk?off={off}&total={total}"
                f"&crc={crc}&ccrc={zlib.crc32(fb)}", fb,
            )
            off += len(fb)
            assert out["staged"] == off
        assert out["done"]
        got = _post(base, "/index/i/query",
                    b'Count(Bitmap(rowID=3, frame="f"))')["results"][0]
        assert got == len(np.unique(cols[rows == 3]))
        # digest + TopN parity vs the streamed door on the same service
        # (TopN is per-fragment-approximate by design, so the streamed
        # twin — not brute-force ground truth — is the correctness bar)
        h.index("i").create_frame("g", FrameOptions())
        off = 0
        for fb in frames:
            out = _post(
                base,
                f"/index/i/frame/g/ingest?off={off}&total={total}"
                f"&crc={crc}&ccrc={zlib.crc32(fb)}", fb,
            )
            off += len(fb)
        assert out["done"]
        top_b = _post(base, "/index/i/query",
                      b'TopN(frame="f", n=3)')["results"][0]
        top_g = _post(base, "/index/i/query",
                      b'TopN(frame="g", n=3)')["results"][0]
        assert top_b == top_g and top_b[0]["count"] > 0
        idx = h.index("i")
        for sl in sorted(idx.frame("g").view("standard").fragments):
            assert idx.frame("f").view("standard").fragment(sl).checksum() \
                == idx.frame("g").view("standard").fragment(sl).checksum()
    finally:
        svc.shutdown()
        h.close()


@requires_pyarrow
def test_lockstep_front_end_bulk_arrow(tmp_path):
    """Arrow chunks through the lockstep bulk door: rank 0's decode is
    the only pyarrow touch — replicated replay carries decoded pairs."""
    import io

    import pyarrow as pa

    h, svc, base = _lockstep_svc(tmp_path)
    try:
        rows = np.array([1, 2, 2], dtype=np.uint64)
        cols = np.array([7, 8, 9], dtype=np.uint64)
        t = pa.table({"row": rows, "col": cols, "noise": [0.1, 0.2, 0.3]})
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        body = sink.getvalue()
        crc = zlib.crc32(body)
        rq = urllib.request.Request(
            base + f"/index/i/frame/f/bulk?off=0&total={len(body)}"
            f"&crc={crc}&ccrc={crc}",
            data=body, method="POST",
            headers={"Content-Type": ingest.ARROW_CONTENT_TYPE},
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["done"]
        got = _post(base, "/index/i/query",
                    b'Count(Bitmap(rowID=2, frame="f"))')["results"][0]
        assert got == 2
    finally:
        svc.shutdown()
        h.close()
