"""Partitioned replica groups: the 2-D (slice-shard x replica) router.

The shard map partitions the slice space into contiguous ranges, each
with its own replica set and its own write sequence space.  Pinned
here:

- ShardMap validation and the cover contract: exact (union over shards
  == the requested set), minimal (only owning shards appear, each slice
  exactly once), consistent with shard_of — the same partition contract
  the executor's ``cluster.slices_by_node`` placement obeys (property
  tests over seeded-random maps; hypothesis drives them when the
  container ships it).
- Read routing: a ``slices=``-scoped query touching K shards costs
  exactly K forwards (replica.routed counters); unscoped queries fan to
  every shard and merge; per-shard reads carry the owning shard's group.
- Write routing: a PQL body routes by ``columnID // SLICE_WIDTH`` to
  the one owning shard's sequencer; a body spanning shards SPLITS into
  per-shard sub-batches with results reassembled in call order; two
  shards' sequencers are different lock instances (lockcheck runs over
  this whole module — the conftest gate).
- Observability: /replica/status and /debug/fleet carry the shard map,
  the ownership epoch, and per-(shard, group) lag.
- Live resharding: POST /replica/reshard splits a shard with zero
  failed writes under concurrent load — pre-stream, epoch-fenced flip,
  moved range cleared off the old owners (this module is also in the
  spec-trace conformance gate, so the reshard epoch/ordering events are
  model-checked live).
"""

import json
import random
import tempfile
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.config import Config
from pilosa_tpu.pilosa import SLICE_WIDTH
from pilosa_tpu.replica import GROUP_HEADER, ReplicaRouter
from pilosa_tpu.replica.shards import (
    DEFAULT_SHARD_SPAN,
    Shard,
    ShardMap,
    ShardMapError,
    parse_shard_map,
    single_shard_map,
    uniform_shard_map,
)
from pilosa_tpu.stats import ExpvarStatsClient

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # container without hypothesis: seeded-random loops
    HAVE_HYPOTHESIS = False


# -- shard-map construction & validation -------------------------------------


def test_single_shard_map_is_the_degenerate_default():
    m = single_shard_map(["g0=h:1", "g1=h:2"])
    assert len(m) == 1
    s = m.shards[0]
    assert (s.name, s.lo, s.hi) == ("s0", 0, None)
    assert s.owns(0) and s.owns(10**9)
    assert m.shard_of(0) is s and m.shard_of(5_000_000) is s


def test_uniform_shard_map_shapes():
    m = uniform_shard_map(["a=h:1", "b=h:2", "c=h:3", "d=h:4"], 2, span=100)
    assert [(s.name, s.lo, s.hi) for s in m] == [("s0", 0, 100), ("s1", 100, None)]
    assert m.shards[0].group_specs == ["a=h:1", "b=h:2"]
    assert m.shards[1].group_specs == ["c=h:3", "d=h:4"]
    assert uniform_shard_map(["a=h:1"], 1).shards[0].hi is None
    assert DEFAULT_SHARD_SPAN == 256


def test_uniform_shard_map_rejects_uneven_split():
    with pytest.raises(ShardMapError, match="evenly"):
        uniform_shard_map(["a=h:1", "b=h:2", "c=h:3"], 2)
    with pytest.raises(ShardMapError):
        uniform_shard_map([], 1)
    with pytest.raises(ShardMapError):
        uniform_shard_map(["a=h:1"], 0)
    with pytest.raises(ShardMapError):
        uniform_shard_map(["a=h:1"], 1, span=0)


def test_parse_shard_map_explicit():
    m = parse_shard_map("s0=0-4:g0=h:1,g1=h:2; s1=4-:g2=h:3")
    assert [(s.name, s.lo, s.hi) for s in m] == [("s0", 0, 4), ("s1", 4, None)]
    assert m.shards[0].group_specs == ["g0=h:1", "g1=h:2"]
    assert m.shard_of(3).name == "s0" and m.shard_of(4).name == "s1"
    # Names default positionally when omitted.
    m2 = parse_shard_map("0-2:g0=h:1;2-:g1=h:2")
    assert [s.name for s in m2] == ["s0", "s1"]


@pytest.mark.parametrize("spec,msg", [
    ("s0=1-:g0=h:1", "start at slice 0"),                  # not at 0
    ("s0=0-4:g0=h:1;s1=5-:g1=h:2", "gap"),                 # hole at 4
    ("s0=0-4:g0=h:1;s1=3-:g1=h:2", "overlap"),             # 3 covered twice
    ("s0=0-4:g0=h:1;s1=4-8:g1=h:2", "open-ended"),         # no tail
    ("s0=0-:g0=h:1;s1=4-:g1=h:2", "not last"),             # open-ended mid
    ("s0=0-4:;s1=4-:g1=h:2", "no groups"),                 # empty replica set
    ("s0=0-4:g0=h:1;s0=4-:g1=h:2", "duplicate shard"),     # shard name reuse
    ("s0=0-4:gX=h:1;s1=4-:gX=h:2", "duplicate group"),     # group name reuse
    ("s0=04:g0=h:1", "lo-hi"),                             # no dash
    ("s0=a-b:g0=h:1", "bad range"),                        # non-int bounds
    ("", "at least one shard"),                            # empty map
])
def test_shard_map_validation_errors(spec, msg):
    with pytest.raises(ShardMapError, match=msg):
        parse_shard_map(spec)


def test_shard_of_rejects_negative_slice():
    m = single_shard_map(["g0=h:1"])
    with pytest.raises(ShardMapError):
        m.shard_of(-1)


# -- the cover contract (property tests) -------------------------------------


def _random_map(rng: random.Random) -> ShardMap:
    """A random valid map: 1..6 contiguous ranges, last open-ended."""
    n = rng.randint(1, 6)
    bounds = sorted(rng.sample(range(1, 500), n - 1)) if n > 1 else []
    los = [0] + bounds
    his = bounds + [None]
    return ShardMap([
        Shard(f"s{i}", lo, hi, [f"g{i}=h:{i + 1}"])
        for i, (lo, hi) in enumerate(zip(los, his))
    ])


def _check_cover_contract(m: ShardMap, slices: list):
    cover = m.cover(slices)
    # EXACT: the union over shards is exactly the requested set.
    union = [s for part in cover.values() for s in part]
    assert sorted(union) == sorted(set(slices))
    # MINIMAL: each slice appears exactly once, under its one owner, and
    # every listed shard owns at least one requested slice.
    assert len(union) == len(set(union))
    by_name = {s.name: s for s in m}
    for name, part in cover.items():
        assert part, f"shard {name} listed with no slices"
        for s in part:
            assert by_name[name].owns(s)
            assert m.shard_of(s).name == name  # shard_of agreement
    # K-shard cost: the fan-out breadth is the number of distinct owners.
    assert len(cover) == len({m.shard_of(s).name for s in set(slices)})


if HAVE_HYPOTHESIS:

    @settings(max_examples=200, deadline=None)
    @given(
        seed=st.integers(0, 2**32 - 1),
        slices=st.lists(st.integers(0, 600), max_size=64),
    )
    def test_cover_is_exact_and_minimal(seed, slices):
        _check_cover_contract(_random_map(random.Random(seed)), slices)

else:

    def test_cover_is_exact_and_minimal():
        rng = random.Random(0xC0FFEE)
        for _ in range(300):
            m = _random_map(rng)
            slices = [rng.randrange(600) for _ in range(rng.randint(0, 64))]
            _check_cover_contract(m, slices)
        _check_cover_contract(_random_map(rng), [])


def test_cover_agrees_with_cluster_placement_contract():
    """The router's cover and the executor's ``slices_by_node`` obey the
    SAME partition contract — each requested slice lands on exactly one
    owner and the union is exactly the request — so a query fanned by
    either layer scans every slice once."""
    from pilosa_tpu.cluster import Cluster, Node

    cluster = Cluster(nodes=[Node(f"h{i}:1") for i in range(3)])
    m = parse_shard_map("s0=0-7:g0=h:1;s1=7-40:g1=h:2;s2=40-:g2=h:3")
    rng = random.Random(7)
    for _ in range(50):
        slices = sorted({rng.randrange(120) for _ in range(rng.randint(1, 40))})
        shard_parts = [tuple(v) for v in m.cover(slices).values()]
        node_parts = [
            tuple(v) for v in cluster.slices_by_node("i", slices).values()
        ]
        for parts in (shard_parts, node_parts):
            flat = sorted(s for p in parts for s in p)
            assert flat == slices, parts


# -- config / CLI plumbing ----------------------------------------------------


def test_config_shard_keys(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        "[replica]\n"
        "shards = 2\n"
        'shard-map = "s0=0-4:g0=h:1;s1=4-:g1=h:2"\n'
        "shard-span = 64\n"
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.replica_shards == 2
    assert cfg.replica_shard_map.startswith("s0=0-4")
    assert cfg.replica_shard_span == 64
    cfg.apply_env({
        "PILOSA_TPU_REPLICA_SHARDS": "4",
        "PILOSA_TPU_REPLICA_SHARD_MAP": "s0=0-:g0=h:1",
        "PILOSA_TPU_REPLICA_SHARD_SPAN": "128",
    })
    assert cfg.replica_shards == 4
    assert cfg.replica_shard_map == "s0=0-:g0=h:1"
    assert cfg.replica_shard_span == 128
    d = Config()
    assert d.replica_shards == 1
    assert d.replica_shard_map == ""
    assert d.replica_shard_span == DEFAULT_SHARD_SPAN


def test_router_from_config_builds_shard_axis():
    from pilosa_tpu.replica import router_from_config

    # shards = N auto-splits the flat group list.
    cfg = Config(replica_groups=["a=127.0.0.1:1", "b=127.0.0.1:2"])
    cfg.replica_shards = 2
    cfg.replica_shard_span = 8
    r = router_from_config(cfg)
    assert [(sh.name, sh.lo, sh.hi) for sh in r.shards] == [
        ("s0", 0, 8), ("s1", 8, None)
    ]
    assert [g.name for g in r.groups] == ["a", "b"]
    r.close()
    # An explicit shard-map wins over shards=N.
    cfg2 = Config()
    cfg2.replica_shards = 9  # would be invalid — must be ignored
    cfg2.replica_shard_map = "s0=0-4:x=127.0.0.1:1;rest=4-:y=127.0.0.1:2"
    r2 = router_from_config(cfg2)
    assert [sh.name for sh in r2.shards] == ["s0", "rest"]
    r2.close()
    # Default stays the single-sequencer router.
    cfg3 = Config(replica_groups=["127.0.0.1:1"])
    r3 = router_from_config(cfg3)
    assert len(r3.shards) == 1 and r3.shards[0].hi is None
    r3.close()


def test_cli_shard_flags_validate(capsys):
    from pilosa_tpu.cli.main import build_parser

    p = build_parser()
    # A malformed --shard-map refuses before binding anything.
    args = p.parse_args([
        "replica-router", "--port", "0", "--test-exit",
        "--shard-map", "s0=0-4:g0=127.0.0.1:1;s1=9-:g1=127.0.0.1:2",
    ])
    assert args.fn(args) == 1
    assert "bad --shard-map" in capsys.readouterr().err
    # An uneven --shards split refuses too.
    args = p.parse_args([
        "replica-router", "--port", "0", "--test-exit",
        "--groups", "a=127.0.0.1:1,b=127.0.0.1:2,c=127.0.0.1:3",
        "--shards", "2",
    ])
    assert args.fn(args) == 1
    assert "bad --shards split" in capsys.readouterr().err


def test_cli_shard_map_supplies_groups(capsys):
    from pilosa_tpu.cli.main import build_parser

    p = build_parser()
    args = p.parse_args([
        "replica-router", "--port", "0", "--test-exit",
        "--shard-map", "s0=0-4:g0=127.0.0.1:1;s1=4-:g1=127.0.0.1:2",
    ])
    assert args.fn(args) == 0
    out = capsys.readouterr().out
    assert "2 shards" in out and "g0=" in out and "g1=" in out


# -- the 2-shard e2e rig ------------------------------------------------------


class _ShardRig:
    """N in-process group servers behind a sharded router: server i is
    the lone replica of shard i (quorum 1 per shard) unless ``spare``
    holds some back for reshard targets."""

    def __init__(self, tmp, boundaries=(4,), n_servers=2, spare=0,
                 shard_map=None, **router_kw):
        from pilosa_tpu.server.server import Server

        self.servers = []
        for i in range(n_servers):
            cfg = Config(
                data_dir=f"{tmp}/g{i}", host="127.0.0.1:0", engine="numpy",
                stats="expvar", qcache_enabled=False, replica_group=f"g{i}",
            )
            srv = Server(cfg)
            srv.open()
            self.servers.append(srv)
        routed = self.servers[:len(self.servers) - spare]
        if shard_map is None:
            los = [0] + list(boundaries)
            his = list(boundaries) + [None]
            assert len(los) == len(routed)
            shard_map = ShardMap([
                Shard(f"s{i}", lo, hi, [f"g{i}={srv.host}"])
                for i, (lo, hi, srv) in enumerate(zip(los, his, routed))
            ])
        self.stats = ExpvarStatsClient()
        self.router = ReplicaRouter(
            shard_map=shard_map, probe_interval_s=0.1, stats=self.stats,
            **router_kw,
        ).serve()
        self.base = f"http://127.0.0.1:{self.router.port}"

    def req(self, method, path, body=None, headers=None, timeout=30):
        rq = urllib.request.Request(self.base + path, data=body, method=method)
        for k, v in (headers or {}).items():
            rq.add_header(k, v)
        try:
            with urllib.request.urlopen(rq, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def query(self, q, qs="", headers=None):
        return self.req("POST", f"/index/i/query{qs}", q.encode(), headers)

    def direct_count(self, i, row=1):
        rq = urllib.request.Request(
            f"http://{self.servers[i].host}/index/i/query",
            data=f'Count(Bitmap(rowID={row}, frame="f"))'.encode(),
            method="POST",
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            return json.loads(resp.read())["results"][0]

    def seed(self):
        assert self.req("POST", "/index/i", b"{}")[0] == 200
        assert self.req("POST", "/index/i/frame/f", b"{}")[0] == 200

    def close(self):
        self.router.close()
        for s in self.servers:
            s.close()


@pytest.fixture
def rig2():
    with tempfile.TemporaryDirectory() as tmp:
        r = _ShardRig(tmp)
        try:
            yield r
        finally:
            r.close()


def _col(slice_i: int, off: int = 0) -> int:
    return slice_i * SLICE_WIDTH + off


def test_two_shard_write_routing_and_merged_reads(rig2):
    """Schema fans everywhere; a data write lands ONLY on its slice's
    owning shard; an unscoped read fans to every shard and sums."""
    rig2.seed()
    for i in range(2):  # schema reached both shards' groups
        rq = urllib.request.Request(f"http://{rig2.servers[i].host}/schema")
        schema = json.loads(urllib.request.urlopen(rq, timeout=10).read())
        assert [x["name"] for x in schema["indexes"]] == ["i"]
    # Three bits in shard s0's range, two in s1's.
    for c in (0, 1, _col(2)):
        st, body, hdrs = rig2.query(f'SetBit(rowID=1, frame="f", columnID={c})')
        assert st == 200 and json.loads(body)["results"] == [True]
        assert hdrs.get(GROUP_HEADER) == "all"
    for c in (_col(4), _col(5)):
        assert rig2.query(f'SetBit(rowID=1, frame="f", columnID={c})')[0] == 200
    assert rig2.direct_count(0) == 3  # g0 holds only s0's slices
    assert rig2.direct_count(1) == 2  # g1 holds only s1's
    st, body, hdrs = rig2.query('Count(Bitmap(rowID=1, frame="f"))')
    assert st == 200 and json.loads(body)["results"] == [5]
    assert hdrs.get(GROUP_HEADER) == "all"
    snap = rig2.stats.snapshot()
    assert snap["replica.shard.writes.s0"] == 3 + 2  # 3 data + 2 schema
    assert snap["replica.shard.writes.s1"] == 2 + 2
    assert snap["replica.shard.read_fanout"] >= 1
    assert snap["replica.shard.count"] == 2


def test_k_shard_read_costs_exactly_k_forwards(rig2):
    """A ``slices=``-scoped query touching K shards forwards to exactly
    K groups — the router analog of the executor's per-node fan-out."""
    rig2.seed()
    assert rig2.query(f'SetBit(rowID=1, frame="f", columnID={_col(0)})')[0] == 200
    assert rig2.query(f'SetBit(rowID=1, frame="f", columnID={_col(4)})')[0] == 200

    def routed():
        snap = rig2.stats.snapshot()
        return (snap.get("replica.routed.g0", 0), snap.get("replica.routed.g1", 0))

    q = 'Count(Bitmap(rowID=1, frame="f"))'
    before = routed()
    st, body, _ = rig2.query(q, qs="?slices=0,1")  # K=1: only s0
    assert st == 200 and json.loads(body)["results"] == [1]
    after = routed()
    assert (after[0] - before[0], after[1] - before[1]) == (1, 0)
    before = after
    st, body, _ = rig2.query(q, qs="?slices=4,9")  # K=1: only s1
    assert st == 200 and json.loads(body)["results"] == [1]
    after = routed()
    assert (after[0] - before[0], after[1] - before[1]) == (0, 1)
    before = after
    st, body, _ = rig2.query(q, qs="?slices=0,4")  # K=2: both
    assert st == 200 and json.loads(body)["results"] == [2]
    after = routed()
    assert (after[0] - before[0], after[1] - before[1]) == (1, 1)


def test_split_write_body_reassembles_results(rig2):
    """One PQL body spanning both shards splits into per-shard
    sub-batches; results come back in the ORIGINAL call order."""
    rig2.seed()
    st, body, hdrs = rig2.query(
        f'SetBit(rowID=1, frame="f", columnID={_col(4)}) '
        f'SetBit(rowID=1, frame="f", columnID=0) '
        f'SetBit(rowID=1, frame="f", columnID={_col(4)})'  # dup: False
    )
    assert st == 200
    assert json.loads(body)["results"] == [True, True, False]
    assert hdrs.get(GROUP_HEADER) == "all"
    assert rig2.direct_count(0) == 1 and rig2.direct_count(1) == 1
    snap = rig2.stats.snapshot()
    assert snap["replica.shard.split_writes"] == 1


def test_multi_shard_unroutable_bodies_answer_501(rig2):
    rig2.seed()
    # A read mixed into a write body.
    st, body, _ = rig2.query(
        f'SetBit(rowID=1, frame="f", columnID=0) Count(Bitmap(rowID=1, frame="f"))'
    )
    assert st == 501 and "mixes reads" in json.loads(body)["error"]
    # Broadcast (SetRowAttrs) mixed with column-routed writes.
    st, body, _ = rig2.query(
        f'SetBit(rowID=1, frame="f", columnID=0) '
        f'SetRowAttrs(rowID=1, frame="f", x="y")'
    )
    assert st == 501 and "broadcast" in json.loads(body)["error"]
    # Streaming ingest cannot be slice-routed across shards.
    st, body, _ = rig2.req(
        "POST", "/index/i/frame/f/ingest?off=0&total=1&crc=0", b"x"
    )
    assert st == 501
    snap = rig2.stats.snapshot()
    assert snap["replica.shard.unroutable"] >= 3


def test_read_your_writes_across_shards(rig2):
    """A write acked by its owning shard is visible on the immediate
    next read, scoped or fanned."""
    rig2.seed()
    total = 0
    for step in range(1, 5):
        for sl in (0, 4):
            c = _col(sl, step)
            assert rig2.query(f'SetBit(rowID=1, frame="f", columnID={c})')[0] == 200
            total += 1
            st, body, _ = rig2.query('Count(Bitmap(rowID=1, frame="f"))')
            assert st == 200 and json.loads(body)["results"] == [total]


def test_status_and_fleet_carry_shard_map(rig2):
    rig2.seed()
    assert rig2.query(f'SetBit(rowID=1, frame="f", columnID={_col(4)})')[0] == 200
    st, body, _ = rig2.req("GET", "/replica/status")
    assert st == 200
    status = json.loads(body)
    assert status["mapEpoch"] == 0
    assert [s["name"] for s in status["shards"]] == ["s0", "s1"]
    assert status["shards"][1]["slices"] == {"lo": 4, "hi": None}
    by_name = {g["name"]: g for g in status["groups"]}
    assert by_name["g0"]["shard"] == "s0" and by_name["g1"]["shard"] == "s1"
    # Lag is measured against the group's OWN shard's head: g0 never saw
    # s1's writes and owes nothing.
    assert by_name["g0"]["lag"] == 0 and by_name["g1"]["lag"] == 0
    st, body, _ = rig2.req("GET", "/debug/fleet")
    assert st == 200
    fleet = json.loads(body)
    router_side = fleet["router"] if "router" in fleet else fleet
    assert router_side["mapEpoch"] == 0
    assert [s["name"] for s in router_side["shards"]] == ["s0", "s1"]


# -- live resharding ----------------------------------------------------------


@pytest.fixture
def reshard_rig():
    """One open-ended shard on g0 plus a SPARE server (g1) standing by
    as the split target."""
    with tempfile.TemporaryDirectory() as tmp:
        r = _ShardRig(tmp, boundaries=(), n_servers=2, spare=1)
        try:
            yield r
        finally:
            r.close()


def test_reshard_validation_refuses_bad_requests(reshard_rig):
    rig = reshard_rig
    rig.seed()
    spare = f"g1={rig.servers[1].host}"

    def reshard(body):
        return rig.req("POST", "/replica/reshard", json.dumps(body).encode())

    st, body, _ = reshard({"shard": "nope", "at": 4, "groups": [spare]})
    assert st == 400 and "no runtime" in json.loads(body)["error"]
    st, body, _ = reshard({"shard": "s0", "at": 0, "groups": [spare]})
    assert st == 400 and "split point" in json.loads(body)["error"]
    st, body, _ = reshard({"shard": "s0", "at": 4, "groups": []})
    assert st == 400
    st, body, _ = reshard({  # bare spec: positional names would collide
        "shard": "s0", "at": 4, "groups": [rig.servers[1].host],
    })
    assert st == 400 and "name=host:port" in json.loads(body)["error"]
    st, body, _ = reshard({  # name collision with the live group
        "shard": "s0", "at": 4, "groups": [f"g0={rig.servers[1].host}"],
    })
    assert st == 400 and "duplicate group" in json.loads(body)["error"]
    st, body, _ = reshard({  # unreachable new group: refused, not erred
        "shard": "s0", "at": 4, "name": "s1", "groups": ["g1=127.0.0.1:1"],
    })
    assert st == 409 and "reshard refused" in json.loads(body)["error"]
    st, body, _ = rig.req("POST", "/replica/reshard", b"not json")
    assert st == 400
    assert rig.stats.snapshot()["replica.reshard.refused"] >= 6
    # Nothing changed ownership.
    assert json.loads(rig.req("GET", "/replica/status")[1])["mapEpoch"] == 0


def test_live_reshard_zero_failed_writes(reshard_rig):
    """Split the open-ended shard at slice 4 while a writer hammers the
    router: every write acks 200 (some briefly held at the fence), the
    map epoch bumps, the moved range serves from the new group only,
    and the old group no longer holds (or double-counts) moved bits."""
    rig = reshard_rig
    rig.seed()
    # Pre-load both halves of the future split.
    for sl in (0, 1, 4, 5, 6):
        assert rig.query(
            f'SetBit(rowID=1, frame="f", columnID={_col(sl)})'
        )[0] == 200
    assert rig.direct_count(0) == 5  # all on g0 pre-split

    failures, acks = [], [0]
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            i += 1
            sl = 4 + (i % 3)  # keep the MOVED range hot during the copy
            st, body, _ = rig.query(
                f'SetBit(rowID=2, frame="f", columnID={_col(sl, i)})',
                headers={}, )
            if st != 200:
                failures.append((st, body[:200]))
            else:
                acks[0] += 1
            time.sleep(0.002)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    time.sleep(0.05)  # writer in flight before the fence
    st, body, _ = rig.req(
        "POST", "/replica/reshard",
        json.dumps({
            "shard": "s0", "at": 4, "name": "s1",
            "groups": [f"g1={rig.servers[1].host}"],
        }).encode(),
        timeout=60,
    )
    assert st == 200, body
    flip = json.loads(body)
    assert flip["mapEpoch"] == 1
    assert [s["name"] for s in flip["shards"]] == ["s0", "s1"]
    assert flip["moved"]["fragments"] >= 1 and flip["clearErrors"] == []
    time.sleep(0.1)  # a few post-flip writes land through the new map
    stop.set()
    t.join(timeout=10)
    assert not failures, f"writes failed during live reshard: {failures[:5]}"
    assert acks[0] > 0

    # ZERO LOST WRITES: every acked row-2 bit is readable post-flip.
    st, body, _ = rig.query('Count(Bitmap(rowID=2, frame="f"))')
    assert st == 200 and json.loads(body)["results"] == [acks[0]]
    # Row 1: 2 bits stayed on s0/g0, 3 moved to s1/g1 — the fan-out sum
    # is exact (no double count: the moved range was cleared off g0).
    st, body, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))')
    assert st == 200 and json.loads(body)["results"] == [5]
    assert rig.direct_count(0) == 2
    assert rig.direct_count(1) == 3
    # DIGEST CONVERGENCE: the two groups now hold disjoint halves whose
    # union is the full slice set; post-flip writes routed to g1 only.
    st, body, _ = rig.query('Count(Bitmap(rowID=2, frame="f"))', qs="?slices=4,5,6")
    assert st == 200 and json.loads(body)["results"] == [acks[0]]
    status = json.loads(rig.req("GET", "/replica/status")[1])
    assert status["mapEpoch"] == 1
    assert {g["name"]: g["shard"] for g in status["groups"]} == {
        "g0": "s0", "g1": "s1"
    }
    snap = rig.stats.snapshot()
    assert snap["replica.reshard.rounds"] == 1
    assert snap["replica.shard.count"] == 2
    assert snap["replica.reshard.moved_fragments"] >= 1
    assert snap["replica.reshard.moved_bytes"] >= 1


def test_reshard_same_server_pairing_skips_clear(reshard_rig):
    """A dev-rig split where the 'new group' is the same server skips
    the moved-range clear (one holder backs both groups) and still
    flips ownership."""
    rig = reshard_rig
    rig.seed()
    for sl in (0, 4):
        assert rig.query(
            f'SetBit(rowID=1, frame="f", columnID={_col(sl)})'
        )[0] == 200
    st, body, _ = rig.req(
        "POST", "/replica/reshard",
        json.dumps({
            "shard": "s0", "at": 4, "name": "s1",
            "groups": [f"gx={rig.servers[0].host}"],  # SAME server
        }).encode(),
        timeout=60,
    )
    assert st == 200, body
    assert rig.stats.snapshot().get("replica.reshard.clear_skipped", 0) >= 1
    # The shared holder keeps every slice, so SCOPED reads stay exact;
    # an unscoped fan-out over a same-server pairing double-counts the
    # shared fragments — the documented dev-rig caveat (DEVELOPMENT.md).
    st, body, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))', qs="?slices=0")
    assert st == 200 and json.loads(body)["results"] == [1]
    st, body, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))', qs="?slices=4")
    assert st == 200 and json.loads(body)["results"] == [1]
