"""Roaring engine property tests.

Mirrors the reference's test strategy (roaring/roaring_test.go): random
bitmaps round-tripped through add/remove/serialize, container conversions
at the 4096 threshold, set ops vs Python-set ground truth, and op-log
encode/decode with checksum validation.
"""

import io

import numpy as np
import pytest

from pilosa_tpu import roaring
from pilosa_tpu.roaring import (
    ARRAY_MAX_SIZE,
    OP_ADD,
    OP_REMOVE,
    Bitmap,
    Container,
    decode_op,
    encode_op,
    fnv1a32,
)


def random_values(rng, n, hi=1 << 20):
    return np.unique(rng.integers(0, hi, size=n, dtype=np.uint64))


@pytest.mark.parametrize("seed,n", [(0, 10), (1, 1000), (2, 5000), (3, 60000)])
def test_add_contains_count(seed, n):
    rng = np.random.default_rng(seed)
    vals = random_values(rng, n)
    bm = Bitmap()
    bm.add_many(vals)
    assert bm.count() == len(vals)
    for v in vals[:50]:
        assert bm.contains(int(v))
    assert not bm.contains(int(vals.max()) + 1)
    bm.check()


def test_single_add_remove():
    bm = Bitmap()
    assert bm.add(42)
    assert not bm.add(42)
    assert bm.count() == 1
    assert bm.remove(42)
    assert not bm.remove(42)
    assert bm.count() == 0
    assert bm.containers == {}


def test_container_conversion_threshold():
    c = Container()
    # Fill to exactly ARRAY_MAX_SIZE: stays an array.
    for v in range(ARRAY_MAX_SIZE):
        assert c.add(v)
    assert c.is_array and c.n == ARRAY_MAX_SIZE
    # One more converts to bitmap.
    assert c.add(ARRAY_MAX_SIZE)
    assert not c.is_array and c.n == ARRAY_MAX_SIZE + 1
    # Removing brings it back to an array.
    assert c.remove(0)
    assert c.is_array and c.n == ARRAY_MAX_SIZE


@pytest.mark.parametrize("seed", range(4))
def test_set_ops_vs_python_sets(seed):
    rng = np.random.default_rng(seed)
    a_vals = random_values(rng, 3000, hi=1 << 18)
    b_vals = random_values(rng, 3000, hi=1 << 18)
    a, b = Bitmap(), Bitmap()
    a.add_many(a_vals)
    b.add_many(b_vals)
    sa, sb = set(a_vals.tolist()), set(b_vals.tolist())
    assert set(a.intersect(b).to_array().tolist()) == sa & sb
    assert set(a.union(b).to_array().tolist()) == sa | sb
    assert set(a.difference(b).to_array().tolist()) == sa - sb
    assert set(a.xor(b).to_array().tolist()) == sa ^ sb
    assert a.intersection_count(b) == len(sa & sb)


def test_set_ops_mixed_container_types(rng):
    # Force one side dense (bitmap container), other sparse (array).
    dense_vals = np.arange(0, 60000, dtype=np.uint64)  # > 4096 per container
    sparse_vals = np.array([1, 5, 100, 65535, 65536, 70000], dtype=np.uint64)
    a, b = Bitmap(), Bitmap()
    a.add_many(dense_vals)
    b.add_many(sparse_vals)
    sa, sb = set(dense_vals.tolist()), set(sparse_vals.tolist())
    assert set(a.intersect(b).to_array().tolist()) == sa & sb
    assert set(b.intersect(a).to_array().tolist()) == sa & sb
    assert set(a.difference(b).to_array().tolist()) == sa - sb
    assert set(b.difference(a).to_array().tolist()) == sb - sa
    assert a.intersection_count(b) == b.intersection_count(a) == len(sa & sb)
    assert set(a.union(b).to_array().tolist()) == sa | sb


@pytest.mark.parametrize("seed,n", [(0, 100), (1, 5000), (2, 70000)])
def test_serialization_roundtrip(seed, n):
    rng = np.random.default_rng(seed)
    vals = random_values(rng, n, hi=1 << 22)
    bm = Bitmap()
    bm.add_many(vals)
    data = bm.to_bytes()
    back = Bitmap.from_bytes(data)
    np.testing.assert_array_equal(back.to_array(), bm.to_array())
    # Stability: re-serialize identical bytes.
    assert back.to_bytes() == data


def test_serialization_format_header():
    bm = Bitmap()
    bm.add(1)
    bm.add(65536 + 5)
    data = bm.to_bytes()
    head = np.frombuffer(data[:8], dtype="<u4")
    assert int(head[0]) == 12346  # cookie
    assert int(head[1]) == 2  # two containers
    # First container header: key=0, n-1=0.
    assert int(np.frombuffer(data[8:16], dtype="<u8")[0]) == 0
    assert int(np.frombuffer(data[16:20], dtype="<u4")[0]) == 0


def test_oplog_roundtrip_and_replay():
    bm = Bitmap()
    wal = io.BytesIO()
    bm.op_writer = wal
    bm.add(7)
    bm.add(9)
    bm.remove(7)
    assert bm.op_n == 3
    # Snapshot-less replay: empty snapshot + ops appended.
    empty = Bitmap().to_bytes()
    restored = Bitmap.from_bytes(empty + wal.getvalue())
    assert restored.to_array().tolist() == [9]
    assert restored.op_n == 3


def test_op_checksum_rejects_corruption():
    rec = bytearray(encode_op(OP_ADD, 12345))
    rec[3] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        decode_op(bytes(rec))
    with pytest.raises(ValueError, match="invalid op type"):
        decode_op(encode_op(7, 1))
    assert decode_op(encode_op(OP_REMOVE, 99)) == (OP_REMOVE, 99)


def test_fnv1a32_known_vectors():
    # Published FNV-1a 32-bit test vectors.
    assert fnv1a32(b"") == 0x811C9DC5
    assert fnv1a32(b"a") == 0xE40C292C
    assert fnv1a32(b"foobar") == 0xBF9CF968


def test_count_range_and_slice(rng):
    vals = random_values(rng, 5000, hi=1 << 21)
    bm = Bitmap()
    bm.add_many(vals)
    for lo, hi in [(0, 1 << 21), (1000, 2000), (65536, 131072), (5, 5)]:
        want = int(((vals >= lo) & (vals < hi)).sum())
        assert bm.count_range(lo, hi) == want
        np.testing.assert_array_equal(bm.slice_values(lo, hi), vals[(vals >= lo) & (vals < hi)])


def test_offset_range(rng):
    from pilosa_tpu.pilosa import SLICE_WIDTH

    # Row extraction as the fragment does it: pos = row*W + col.
    row, slice_i = 3, 2
    cols = random_values(rng, 1000, hi=SLICE_WIDTH)
    bm = Bitmap()
    bm.add_many(cols + np.uint64(row * SLICE_WIDTH))
    seg = bm.offset_range(slice_i * SLICE_WIDTH, row * SLICE_WIDTH, (row + 1) * SLICE_WIDTH)
    want = cols + np.uint64(slice_i * SLICE_WIDTH)
    np.testing.assert_array_equal(seg.to_array(), want)


def test_dense_bridge_roundtrip(rng):
    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.pilosa import SLICE_WIDTH

    vals = random_values(rng, 9000, hi=SLICE_WIDTH)
    bm = Bitmap()
    bm.add_many(vals)
    words = bm.to_dense_words(0, SLICE_WIDTH)
    assert words.dtype == np.uint32 and words.shape == (SLICE_WIDTH // 32,)
    assert bw.np_count(words) == len(vals)
    np.testing.assert_array_equal(bw.pack_positions(vals), words)
    back = Bitmap.from_dense_words(words)
    np.testing.assert_array_equal(back.to_array(), vals)


def test_max():
    bm = Bitmap()
    assert bm.max() == 0
    bm.add_many(np.array([5, 100, 1 << 21], dtype=np.uint64))
    assert bm.max() == 1 << 21


def test_from_bytes_rejects_truncation(rng):
    vals = random_values(rng, 100)
    bm = Bitmap()
    bm.add_many(vals)
    data = bm.to_bytes()
    with pytest.raises(ValueError, match="out of bounds"):
        Bitmap.from_bytes(data[:-8])


def test_dense_words_validates_n_bits():
    bm = Bitmap([5, 1010])
    with pytest.raises(ValueError, match="n_bits"):
        bm.to_dense_words(0, 1000)
    words = bm.to_dense_words(0, 1 << 16)
    assert bw_count(words) == 2


def bw_count(words):
    import numpy as _np

    return int(roaring._POPCNT8[_np.ascontiguousarray(words).view(_np.uint8)].sum())


def test_dense_container_ops_stay_dense(rng):
    a, b = Bitmap(), Bitmap()
    a.add_many(np.arange(0, 60000, dtype=np.uint64))
    b.add_many(np.arange(30000, 90000, dtype=np.uint64))
    inter = a.intersect(b)
    assert inter.count() == 30000
    # result containers holding >4096 values stay dense bitmaps
    assert any(c.bitmap is not None for c in inter.containers.values())


def test_add_many_logged_matches_sequential_add(tmp_path):
    """Bulk logged add == per-value add: same added set, same containers
    (incl. dense containers past ARRAY_MAX_SIZE), same WAL replay."""
    rng = np.random.default_rng(11)
    # Dense cluster in one container (forces bitmap repr) + scattered keys.
    vals = np.concatenate(
        [
            rng.integers(0, 6000, size=5000, dtype=np.uint64),  # key 0, dense
            rng.integers(0, 1 << 30, size=2000, dtype=np.uint64),
        ]
    )
    a = roaring.Bitmap()
    want_added = sorted({int(v) for v in vals if a.add(int(v))})
    path = str(tmp_path / "b")
    b = roaring.Bitmap()
    with open(path, "wb") as fh:
        b.op_writer = fh
        got = b.add_many_logged(vals)
        # Second identical batch: nothing added, nothing logged.
        assert len(b.add_many_logged(vals)) == 0
    assert sorted(got.tolist()) == want_added
    assert np.array_equal(b.to_array(), a.to_array())
    assert b.op_n == len(want_added)


def test_container_contains_many_and_dense_add():
    rng = np.random.default_rng(5)
    vals = np.unique(rng.integers(0, 65536, size=5000, dtype=np.uint32))
    c = roaring.Container.from_values(vals)  # > 4096 -> bitmap repr
    assert not c.is_array
    probe = rng.integers(0, 65536, size=1000, dtype=np.uint32)
    want = np.isin(probe, vals)
    assert np.array_equal(c.contains_many(probe), want)
    # Dense bulk add stays dense and counts correctly.
    extra = np.unique(rng.integers(0, 65536, size=300, dtype=np.uint32))
    new = extra[~np.isin(extra, vals)]
    assert c.add_many(extra) == len(new)
    assert c.n == len(vals) + len(new)
    # Array-representation membership too.
    small = roaring.Container.from_values(np.array([3, 9, 100], dtype=np.uint32))
    assert small.is_array
    assert np.array_equal(
        small.contains_many(np.array([0, 3, 9, 99, 100], dtype=np.uint32)),
        np.array([False, True, True, False, True]),
    )
    assert np.array_equal(
        roaring.Container(array=np.empty(0, dtype=np.uint32)).contains_many(
            np.array([1, 2], dtype=np.uint32)
        ),
        np.array([False, False]),
    )


def test_serialization_independent_decoder():
    """Decode a written file with an INDEPENDENT reader built only from
    the documented reference layout (roaring.go:475-533) — cookie, 12-byte
    container headers, u32 offset table, array u32le / bitmap u64le
    payloads — no reuse of roaring.py's decoder."""
    rng = np.random.default_rng(9)
    vals = np.unique(
        np.concatenate(
            [
                rng.integers(0, 3000, size=500, dtype=np.uint64),  # array container
                np.uint64(1 << 16) + rng.integers(0, 60000, size=20000, dtype=np.uint64),  # bitmap
                np.uint64(5 << 16) + np.arange(10, dtype=np.uint64),  # sparse high key
            ]
        )
    )
    bm = Bitmap()
    bm.add_many(vals)
    data = bm.to_bytes()

    import struct

    cookie, n = struct.unpack_from("<II", data, 0)
    assert cookie == 12346
    decoded = []
    offsets_at = 8 + n * 12
    for i in range(n):
        key, n1 = struct.unpack_from("<QI", data, 8 + i * 12)
        count = n1 + 1
        (off,) = struct.unpack_from("<I", data, offsets_at + i * 4)
        if count <= 4096:
            lows = np.frombuffer(data, dtype="<u4", count=count, offset=off)
        else:
            words = np.frombuffer(data, dtype="<u8", count=1024, offset=off)
            bits = np.unpackbits(words.view(np.uint8), bitorder="little")
            lows = np.nonzero(bits)[0]
            assert len(lows) == count
        decoded.append(np.asarray(lows, dtype=np.uint64) + np.uint64(key << 16))
    got = np.concatenate(decoded)
    np.testing.assert_array_equal(np.sort(got), vals)


def test_snapshot_mirror_gate_and_equivalence():
    """The native incremental-snapshot mirror engages only on sparse
    many-container shapes; images stay byte-identical to the Python
    writer either way, including across the regime switch."""
    import io

    from pilosa_tpu import native as native_mod

    if native_mod.load() is None:
        import pytest

        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(8)
    bm = Bitmap()
    # Sparse: 2000 containers x ~2 values -> mirror engages.
    pos = (rng.integers(0, 2000, 6000).astype(np.uint64) << np.uint64(16)) | (
        rng.integers(0, 1 << 16, 6000).astype(np.uint64)
    )
    bm.add_many(pos)
    assert bm._snap_profitable()
    img = bm.to_bytes()
    b2 = io.BytesIO()
    bm._write_to_python(b2)
    assert b2.getvalue() == img
    assert bm._snap_handle is not None
    # Densify heavily -> avg payload rises past the gate.
    for k in range(2000):
        bm.add_many((np.uint64(k) << np.uint64(16)) | np.arange(5000, dtype=np.uint64))
    assert not bm._snap_profitable()
    img2 = bm.to_bytes()  # python writer now; mirror released
    assert bm._snap_handle is None
    b3 = io.BytesIO()
    bm._write_to_python(b3)
    assert b3.getvalue() == img2


def test_zero_copy_parse_and_cow():
    """Zero-copy decode: containers view the buffer (no payload copies);
    mutations promote to private copies (roaring.go:536-614 mmap attach)."""
    rng = np.random.default_rng(31)
    bm = Bitmap()
    vals = np.unique(rng.integers(0, 1 << 19, size=120000)).astype(np.uint64)
    bm.add_many_unlogged(vals)
    data = bm.to_bytes()

    z = Bitmap.from_bytes(data, zero_copy=True)
    assert z.count() == bm.count()
    z.check()
    # bitmap containers really are views into the buffer...
    dense = [c for c in z.containers.values() if c.bitmap is not None]
    assert dense, "shape should produce dense containers"
    assert all(not c.bitmap.flags.writeable for c in dense)
    assert all(c.bitmap.base is not None for c in dense)
    # ...and copy-on-write on mutation, without touching siblings.
    key = next(k for k, c in z.containers.items() if c.bitmap is not None)
    c = z.containers[key]
    v = (key << 16) | 7
    added = z.add(v)
    assert z.contains(v)
    if added:
        assert c.bitmap.flags.writeable  # promoted private copy
    assert z.count() == bm.count() + (1 if added else 0)
    # equivalence with the copying decode after a WAL-ish mutation mix
    z2 = Bitmap.from_bytes(data, zero_copy=True)
    c2 = Bitmap.from_bytes(data)
    for x in rng.integers(0, 1 << 21, size=500).tolist():
        assert z2.add(x) == c2.add(x)
    for x in rng.integers(0, 1 << 21, size=500).tolist():
        assert z2.remove(x) == c2.remove(x)
    assert z2.count() == c2.count()
    assert z2.to_bytes() == c2.to_bytes()
