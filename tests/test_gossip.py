"""SWIM gossip membership + broadcast transport tests.

Reference analog: gossip/gossip.go has no dedicated test file; the
behavior is exercised via server_test.go's TestMain_SendReceiveMessage.
Here we test the transport directly (membership convergence, sync/async
delivery, status push/pull, failure detection) plus the server-level
schema propagation over gossip.
"""

from __future__ import annotations

import time

import pytest

from pilosa_tpu.gossip import (
    STATE_ALIVE,
    STATE_DEAD,
    GossipNodeSet,
    Member,
    _pack_piggyback,
    _unpack_piggyback,
)


def _wait_for(cond, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _mknode(name, seed="", status_handler=None, **kw):
    n = GossipNodeSet(
        name,
        bind="127.0.0.1:0",
        seed=seed,
        status_handler=status_handler,
        probe_interval=0.1,
        probe_timeout=0.3,
        suspect_timeout=0.6,
        push_pull_interval=0.5,
        **kw,
    )
    n.start(lambda msg: None)
    n.open()
    return n


class _Recorder:
    def __init__(self):
        self.messages = []

    def __call__(self, msg: bytes):
        self.messages.append(msg)


class _Status:
    def __init__(self, blob: bytes):
        self.blob = blob
        self.remote = []

    def local_status(self) -> bytes:
        return self.blob

    def handle_remote_status(self, buf: bytes) -> None:
        self.remote.append(buf)


def test_piggyback_roundtrip():
    items = [(0, b"alpha"), (1, b""), (1, b"\x00\xff" * 10)]
    assert _unpack_piggyback(_pack_piggyback(items)) == items


def test_open_requires_start():
    n = GossipNodeSet("n0", bind="127.0.0.1:0")
    with pytest.raises(RuntimeError):
        n.open()  # gossip.go:64-66 ordering requirement


def test_join_and_membership_convergence():
    a = _mknode("node-a:10101")
    b = _mknode("node-b:10101", seed=a.addr)
    try:
        assert _wait_for(lambda: a.nodes() == ["node-a:10101", "node-b:10101"])
        assert _wait_for(lambda: b.nodes() == ["node-a:10101", "node-b:10101"])
    finally:
        a.close()
        b.close()


def test_transitive_membership():
    """C joins via B; A must learn C through gossip (not direct contact)."""
    a = _mknode("a:1")
    b = _mknode("b:1", seed=a.addr)
    c = _mknode("c:1", seed=b.addr)
    try:
        assert _wait_for(lambda: a.nodes() == ["a:1", "b:1", "c:1"], timeout=10)
        assert _wait_for(lambda: c.nodes() == ["a:1", "b:1", "c:1"], timeout=10)
    finally:
        for n in (a, b, c):
            n.close()


def test_send_sync_delivers_to_all_members():
    rec_b, rec_c = _Recorder(), _Recorder()
    a = _mknode("a:1")
    b = _mknode("b:1", seed=a.addr)
    c = _mknode("c:1", seed=a.addr)
    b.handler = rec_b
    c.handler = rec_c
    try:
        assert _wait_for(lambda: len(a.nodes()) == 3)
        a.send_sync(b"schema-mutation")
        assert _wait_for(lambda: rec_b.messages == [b"schema-mutation"])
        assert _wait_for(lambda: rec_c.messages == [b"schema-mutation"])
    finally:
        for n in (a, b, c):
            n.close()


def test_send_async_piggybacks_on_probes():
    rec = _Recorder()
    a = _mknode("a:1")
    b = _mknode("b:1", seed=a.addr)
    b.handler = rec
    try:
        assert _wait_for(lambda: len(a.nodes()) == 2)
        a.send_async(b"async-news")
        assert _wait_for(lambda: b"async-news" in rec.messages, timeout=5)
    finally:
        a.close()
        b.close()


def test_status_push_pull_on_join():
    sa, sb = _Status(b"status-of-a"), _Status(b"status-of-b")
    a = _mknode("a:1", status_handler=sa)
    b = _mknode("b:1", seed=a.addr, status_handler=sb)
    try:
        # Join push/pull exchanges both directions (gossip.go:193-222).
        assert _wait_for(lambda: b"status-of-a" in sb.remote)
        assert _wait_for(lambda: b"status-of-b" in sa.remote)
    finally:
        a.close()
        b.close()


def test_failure_detection_marks_dead():
    a = _mknode("a:1")
    b = _mknode("b:1", seed=a.addr)
    try:
        assert _wait_for(lambda: len(a.nodes()) == 2)
        b.close()  # silent death — no goodbye message
        assert _wait_for(lambda: a.nodes() == ["a:1"], timeout=10)
        assert a.member_states()["b:1"] == STATE_DEAD
    finally:
        a.close()


def test_refutation_keeps_live_node_alive():
    """A live node that hears its own suspicion re-announces with a higher
    incarnation (SWIM refutation)."""
    a = _mknode("a:1")
    b = _mknode("b:1", seed=a.addr)
    try:
        assert _wait_for(lambda: len(b.nodes()) == 2)
        # Inject a false suspicion of B into B itself.
        b._merge_member(Member(name="b:1", addr=b.addr, incarnation=0, state="suspect"))
        assert b._incarnation >= 1
        assert b.member_states()["b:1"] == STATE_ALIVE
        assert _wait_for(lambda: len(a.nodes()) == 2)
    finally:
        a.close()
        b.close()


def test_dead_member_revives_on_higher_incarnation():
    a = _mknode("a:1")
    try:
        a._merge_member(Member(name="x:1", addr="127.0.0.1:9", incarnation=0))
        a._mark("x:1", STATE_DEAD)
        assert "x:1" not in a.nodes()
        a._merge_member(Member(name="x:1", addr="127.0.0.1:9", incarnation=1, state=STATE_ALIVE))
        assert "x:1" in a.nodes()
    finally:
        a.close()


def test_server_schema_propagates_over_gossip(tmp_path):
    """Two full servers with gossip transport: schema created on A appears
    on B via the status push/pull (server_test.go TestMain_SendReceiveMessage
    analog, over SWIM instead of httpbroadcast)."""
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    def mkserver(name, port, data_dir, seed=""):
        cfg = Config()
        cfg.data_dir = str(data_dir)
        cfg.host = f"127.0.0.1:{port}"
        cfg.cluster.type = "gossip"
        cfg.cluster.hosts = ["127.0.0.1:0"]  # membership comes from gossip
        cfg.cluster.gossip_seed = seed
        srv = Server(cfg)
        # speed up the gossip clocks for the test
        g = srv.receiver
        g.probe_interval, g.probe_timeout = 0.1, 0.3
        g.push_pull_interval = 0.4
        srv.open()
        return srv

    a = mkserver("a", 0, tmp_path / "a")
    b = None
    try:
        seed_addr = a.receiver.addr
        b = mkserver("b", 0, tmp_path / "b", seed=seed_addr)
        # Create schema on A only.
        from pilosa_tpu.core.frame import FrameOptions
        from pilosa_tpu.core.index import IndexOptions

        idx = a.holder.create_index("gossidx", IndexOptions(column_label="col"))
        idx.create_frame("gframe", FrameOptions(row_label="row"))
        assert _wait_for(
            lambda: b.holder.index("gossidx") is not None
            and b.holder.frame("gossidx", "gframe") is not None,
            timeout=10,
        )
        fr = b.holder.frame("gossidx", "gframe")
        assert fr.row_label == "row"
    finally:
        a.close()
        if b is not None:
            b.close()


def test_four_node_convergence_and_death():
    """Membership converges through a single seed at 4 nodes; a killed
    node is marked dead everywhere and survivors keep broadcasting."""
    a = _mknode("n0:1")
    b = _mknode("n1:1", seed=a.addr)
    c = _mknode("n2:1", seed=a.addr)
    d = _mknode("n3:1", seed=a.addr)
    nodes = [a, b, c, d]
    rec = _Recorder()
    d.handler = rec
    try:
        want = ["n0:1", "n1:1", "n2:1", "n3:1"]
        for n in nodes:
            assert _wait_for(lambda n=n: sorted(n.nodes()) == want, timeout=12), (
                n.name, n.nodes())
        # Kill one non-seed node; everyone else marks it dead.
        c.close()
        alive = ["n0:1", "n1:1", "n3:1"]
        for n in (a, b, d):
            assert _wait_for(lambda n=n: sorted(n.nodes()) == alive, timeout=12), (
                n.name, n.nodes())
        # Survivors still deliver broadcasts end to end.
        a.send_async(b"after-death")
        assert _wait_for(lambda: b"after-death" in rec.messages, timeout=8)
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass
