"""Holder/Index/Frame/View + time quantum + attr store tests.

Reference analogs: holder_test.go, index_test.go, frame_test.go,
view_test.go, time_test.go, attr_test.go.
"""

from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.attr import ATTR_BLOCK_SIZE, AttrStore, blocks_diff
from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.pilosa import (
    ErrColumnRowLabelEqual,
    ErrFrameExists,
    ErrIndexExists,
    SLICE_WIDTH,
)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def test_holder_create_and_reopen(tmp_path, holder):
    idx = holder.create_index("i0")
    f = idx.create_frame("f0", FrameOptions())
    f.set_bit(VIEW_STANDARD, 10, 100)
    f.set_bit(VIEW_STANDARD, 10, SLICE_WIDTH + 5)  # second slice
    holder.close()

    h2 = Holder(holder.path)
    h2.open()
    assert sorted(h2.indexes.keys()) == ["i0"]
    frag0 = h2.fragment("i0", "f0", VIEW_STANDARD, 0)
    frag1 = h2.fragment("i0", "f0", VIEW_STANDARD, 1)
    assert frag0.contains(10, 100)
    assert frag1.contains(10, SLICE_WIDTH + 5)
    assert h2.index("i0").max_slice() == 1
    h2.close()


def test_holder_schema_and_errors(holder):
    idx = holder.create_index("aaa", IndexOptions(column_label="col"))
    idx.create_frame("fr", FrameOptions(row_label="row", time_quantum="YM"))
    with pytest.raises(ErrIndexExists):
        holder.create_index("aaa")
    with pytest.raises(ErrFrameExists):
        idx.create_frame("fr", FrameOptions())
    schema = holder.schema()
    assert schema[0]["name"] == "aaa"
    assert schema[0]["columnLabel"] == "col"
    assert schema[0]["frames"][0]["timeQuantum"] == "YM"


def test_row_column_label_collision(holder):
    idx = holder.create_index("i", IndexOptions(column_label="thing"))
    with pytest.raises(ErrColumnRowLabelEqual):
        idx.create_frame("f", FrameOptions(row_label="thing"))


def test_frame_inverse_and_time_views(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", FrameOptions(inverse_enabled=True, time_quantum="YMDH"))
    ts = datetime(2017, 3, 2, 15)
    f.set_bit(VIEW_STANDARD, 1, 2, timestamp=ts)
    f.set_bit(VIEW_INVERSE, 2, 1, timestamp=ts)
    names = set(f.views.keys())
    assert {
        "standard",
        "inverse",
        "standard_2017",
        "standard_201703",
        "standard_20170302",
        "standard_2017030215",
        "inverse_2017",
    } <= names
    assert f.view("standard_201703").fragment(0).contains(1, 2)
    assert f.view(VIEW_INVERSE).fragment(0).contains(2, 1)


def test_frame_import_with_inverse_and_time(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", FrameOptions(inverse_enabled=True, time_quantum="Y"))
    ts = datetime(2018, 6, 1)
    f.import_bits([1, 2], [10, SLICE_WIDTH + 20], [ts, None])
    assert f.view(VIEW_STANDARD).fragment(0).contains(1, 10)
    assert f.view(VIEW_STANDARD).fragment(1).contains(2, SLICE_WIDTH + 20)
    # inverse transposed: row=col, col=row
    assert f.view(VIEW_INVERSE).fragment(0).contains(10, 1)
    assert f.view(VIEW_INVERSE).fragment(0).contains(SLICE_WIDTH + 20, 2)
    # time view only for the timestamped bit
    assert f.view("standard_2018").fragment(0).contains(1, 10)
    assert f.view("standard_2018").fragment(1) is None


def test_frame_meta_persistence(tmp_path, holder):
    idx = holder.create_index("i")
    f = idx.create_frame(
        "f", FrameOptions(row_label="rid", cache_type="ranked", cache_size=123, time_quantum="YM")
    )
    holder.close()
    h2 = Holder(holder.path)
    h2.open()
    f2 = h2.frame("i", "f")
    assert f2.row_label == "rid"
    assert f2.cache_type == "ranked"
    assert f2.cache_size == 123
    assert f2.time_quantum == "YM"
    h2.close()


def test_new_fragment_hook(holder):
    events = []
    holder.on_new_fragment = lambda *a: events.append(a)
    idx = holder.create_index("i")
    f = idx.create_frame("f", FrameOptions())
    f.set_bit(VIEW_STANDARD, 0, 0)
    f.set_bit(VIEW_STANDARD, 0, 2 * SLICE_WIDTH + 1)
    assert ("i", "f", VIEW_STANDARD, 0) in events
    assert ("i", "f", VIEW_STANDARD, 2) in events


def test_remote_max_slice(holder):
    idx = holder.create_index("i")
    assert idx.max_slice() == 0
    idx.set_remote_max_slice(7)
    assert idx.max_slice() == 7
    idx.set_remote_max_slice(3)  # never decreases
    assert idx.max_slice() == 7


# -- time quantum -----------------------------------------------------------


def test_views_by_time():
    t = datetime(2017, 4, 9, 12)
    assert tq.views_by_time("standard", t, "YMDH") == [
        "standard_2017",
        "standard_201704",
        "standard_20170409",
        "standard_2017040912",
    ]


def test_views_by_time_range_ymdh():
    # Reference time_test.go style: partial-hour → day → month spans.
    got = tq.views_by_time_range(
        "std", datetime(2017, 1, 31, 22), datetime(2017, 2, 2, 2), "YMDH"
    )
    assert got == [
        "std_2017013122",
        "std_2017013123",
        "std_20170201",
        "std_2017020200",
        "std_2017020201",
    ]


def test_views_by_time_range_year_span():
    got = tq.views_by_time_range("std", datetime(2016, 11, 1), datetime(2018, 2, 1), "YMDH")
    assert got == ["std_201611", "std_201612", "std_2017", "std_201801"]


def test_views_by_time_range_only_days():
    got = tq.views_by_time_range("std", datetime(2017, 5, 1), datetime(2017, 5, 4), "D")
    assert got == ["std_20170501", "std_20170502", "std_20170503"]


def test_parse_time_quantum():
    from pilosa_tpu.pilosa import ErrInvalidTimeQuantum

    assert tq.parse_time_quantum("ymdh") == "YMDH"
    assert tq.parse_time_quantum("") == ""
    with pytest.raises(ErrInvalidTimeQuantum):
        tq.parse_time_quantum("XY")


# -- attr store -------------------------------------------------------------


@pytest.fixture
def store(tmp_path):
    s = AttrStore(str(tmp_path / "attrs.db"))
    s.open()
    yield s
    s.close()


def test_attr_set_get_merge(store):
    assert store.attrs(1) is None
    store.set_attrs(1, {"name": "alice", "n": 3, "ok": True, "x": 1.5})
    assert store.attrs(1) == {"name": "alice", "n": 3, "ok": True, "x": 1.5}
    store.set_attrs(1, {"n": 4, "name": None})  # merge + delete
    assert store.attrs(1) == {"n": 4, "ok": True, "x": 1.5}


def test_attr_persistence(tmp_path):
    s = AttrStore(str(tmp_path / "a.db"))
    s.open()
    s.set_attrs(42, {"v": "x"})
    s.close()
    s2 = AttrStore(s.path)
    s2.open()
    assert s2.attrs(42) == {"v": "x"}
    s2.close()


def test_attr_rejects_bad_types(store):
    with pytest.raises(TypeError):
        store.set_attrs(1, {"bad": [1, 2]})


def test_attr_blocks_and_diff(store, tmp_path):
    store.set_attrs(1, {"a": 1})
    store.set_attrs(ATTR_BLOCK_SIZE + 1, {"b": 2})
    blocks = store.blocks()
    assert [b for b, _ in blocks] == [0, 1]

    other = AttrStore(str(tmp_path / "other.db"))
    other.open()
    other.set_attrs(1, {"a": 1})
    other.set_attrs(ATTR_BLOCK_SIZE + 1, {"b": 999})
    assert blocks_diff(store.blocks(), other.blocks()) == [1]
    assert blocks_diff(store.blocks(), store.blocks()) == []
    assert other.block_data(1) == {ATTR_BLOCK_SIZE + 1: {"b": 999}}
    other.close()


def test_create_frame_rejects_bad_cache_type(tmp_path):
    """Invalid cacheType fails at creation (handler 400), leaving no ghost
    frame directory behind (handler_internal_test.go analog)."""
    import os
    import pytest
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.pilosa import ErrInvalidCacheType

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    with pytest.raises(ErrInvalidCacheType):
        idx.create_frame("bad", FrameOptions(cache_type="bogus"))
    assert idx.frame("bad") is None
    assert not os.path.exists(os.path.join(idx.path, "bad"))
    h.close()
    # Restart: no ghost frame rediscovered.
    h2 = Holder(str(tmp_path / "d"))
    h2.open()
    assert h2.index("i").frame("bad") is None
    h2.close()


def test_create_frame_rejects_bad_options_without_ghosts(tmp_path):
    """Every invalid FrameOption fails BEFORE any on-disk state exists."""
    import os
    import pytest
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.pilosa import PilosaError

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    for bad in (
        FrameOptions(time_quantum="bogus"),
        FrameOptions(row_label="BAD LABEL"),
        FrameOptions(cache_type="bogus"),
    ):
        with pytest.raises(PilosaError):
            idx.create_frame("bad", bad)
        assert idx.frame("bad") is None
        assert not os.path.exists(os.path.join(idx.path, "bad"))
    h.close()
    h2 = Holder(str(tmp_path / "d"))
    h2.open()
    assert h2.index("i").frame("bad") is None
    h2.close()


def test_create_index_rejects_bad_options_without_ghosts(tmp_path):
    """Invalid IndexOptions fail BEFORE any on-disk state exists."""
    import os
    import pytest
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.index import IndexOptions
    from pilosa_tpu.pilosa import PilosaError

    h = Holder(str(tmp_path / "d"))
    h.open()
    for bad in (IndexOptions(column_label="BAD LABEL"), IndexOptions(time_quantum="bogus")):
        with pytest.raises(PilosaError):
            h.create_index("ghost", bad)
        assert h.index("ghost") is None
        assert not os.path.exists(os.path.join(h.path, "ghost"))
    h.close()
    h2 = Holder(str(tmp_path / "d"))
    h2.open()
    assert h2.index("ghost") is None
    h2.close()


def test_inverse_disabled_raises_specific_error(holder):
    """Reads against a non-inverse frame raise ErrFrameInverseDisabled,
    and inverse views cannot be created on it (frame.go:413-415)."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import ErrFrameInverseDisabled

    idx = holder.create_index("inv")
    idx.create_frame("f", FrameOptions())  # inverse disabled
    e = Executor(holder, engine="numpy")
    e.execute("inv", 'SetBit(rowID=1, frame="f", columnID=2)')
    with pytest.raises(ErrFrameInverseDisabled):
        e.execute("inv", 'Bitmap(columnID=2, frame="f")')
    with pytest.raises(ErrFrameInverseDisabled):
        idx.frame("f").create_view_if_not_exists(VIEW_INVERSE)
    with pytest.raises(ErrFrameInverseDisabled):  # time sub-views too
        idx.frame("f").create_view_if_not_exists(VIEW_INVERSE + "_2017")
