"""Sharded slice-axis execution on a virtual 8-device CPU mesh.

Exercises real GSPMD partitioning + collectives (psum/all-gather) exactly
as the multi-chip path would run them on ICI; conftest forces
xla_force_host_platform_device_count=8.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitwise as bw

W = 1024


@pytest.fixture(scope="module")
def mesh():
    import jax

    from pilosa_tpu.parallel import SliceMesh

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (virtual) devices")
    return SliceMesh(jax.devices())


def test_sharded_count_and(mesh, rng):
    n = mesh.n_devices * 2
    a = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    da, db = mesh.shard_stack(a), mesh.shard_stack(b)
    from pilosa_tpu.parallel import sharded_count_and

    got = int(sharded_count_and(mesh, da, db))
    want = sum(bw.np_count_and(a[i], b[i]) for i in range(n))
    assert got == want


@pytest.mark.parametrize("op,npfn", [
    ("or", bw.np_count_or),
    ("xor", bw.np_count_xor),
    ("andnot", bw.np_count_andnot),
])
def test_sharded_count_ops(mesh, rng, op, npfn):
    from pilosa_tpu.parallel import sharded_count_call

    n = mesh.n_devices
    a = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    got = int(sharded_count_call(mesh, op, mesh.shard_stack(a), mesh.shard_stack(b)))
    want = sum(npfn(a[i], b[i]) for i in range(n))
    assert got == want


def test_sharded_union_stays_sharded(mesh, rng):
    from pilosa_tpu.parallel import sharded_union_reduce

    n = mesh.n_devices
    a = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    out = sharded_union_reduce(mesh, [mesh.shard_stack(a), mesh.shard_stack(b)])
    np.testing.assert_array_equal(np.asarray(out), a | b)


def test_sharded_topn_counts(mesh, rng):
    from pilosa_tpu.parallel.sharded import sharded_topn_counts

    n, k = mesh.n_devices, 5
    rows = rng.integers(0, 1 << 32, size=(n, k, W), dtype=np.uint32)
    src = rng.integers(0, 1 << 32, size=(n, W), dtype=np.uint32)
    got = np.asarray(sharded_topn_counts(mesh, mesh.shard_stack(rows), mesh.shard_stack(src)))
    want = np.array(
        [sum(bw.np_count_and(rows[s, r], src[s]) for s in range(n)) for r in range(k)]
    )
    np.testing.assert_array_equal(got, want)


def test_divisibility_guard(mesh):
    from pilosa_tpu.parallel.sharded import _require_divisible

    _require_divisible(16, 8)
    with pytest.raises(ValueError):
        _require_divisible(9, 8)


def test_mesh_engine_matches_numpy(tmp_path):
    """The executor running on the MeshEngine (slice axis sharded over the
    8-device CPU mesh) returns the same results as the numpy engine."""
    import numpy as np

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(11)
    # bits across 8 slices so every device owns one shard
    for r in range(4):
        for s in range(8):
            for c in rng.choice(1000, size=20, replace=False):
                fr.set_bit("standard", r, s * SLICE_WIDTH + int(c))
    e_np = Executor(h, engine="numpy")
    e_mesh = Executor(h, engine="mesh")
    queries = [
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))',
        'Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))',
        'Bitmap(rowID=1, frame="f")',
        'TopN(frame="f", n=3)',
    ]
    for q in queries:
        (a,) = e_np.execute("i", q)
        (b,) = e_mesh.execute("i", q)
        if hasattr(a, "bits"):
            assert a.bits() == b.bits(), q
        else:
            assert a == b, q
    # fused batch path on the mesh engine
    batch = " ".join(
        f'Count(Intersect(Bitmap(rowID={x}, frame="f"), Bitmap(rowID={y}, frame="f")))'
        for x, y in [(0, 1), (1, 2), (2, 3)]
    )
    assert e_np.execute("i", batch) == e_mesh.execute("i", batch)
    h.close()


def test_sharded_pallas_kernels_interpret(mesh):
    """shard_map'd Pallas kernels (interpret mode on the CPU mesh): the
    multi-chip kernel tier agrees with numpy ground truth."""
    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.parallel.sharded import (
        sharded_gather_count,
        sharded_gather_count_multi,
    )

    rng = np.random.default_rng(12)
    n_slices, n_rows, W = 8, 6, 1024
    rows = rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)
    drows = mesh.shard_stack(rows)
    for op, fold in (
        ("and", lambda a, b: a & b),
        ("or", lambda a, b: a | b),
        ("xor", lambda a, b: a ^ b),
        ("andnot", lambda a, b: a & ~b),
    ):
        pairs = rng.integers(0, n_rows, size=(5, 2)).astype(np.int32)
        got = np.asarray(sharded_gather_count(mesh, op, drows, pairs, interpret=True))
        want = [
            int(bw.np_popcount(fold(rows[:, int(a)], rows[:, int(b)])).sum())
            for a, b in pairs
        ]
        assert got.tolist() == want, op
    idx = rng.integers(0, n_rows, size=(3, 4)).astype(np.int32)
    got = np.asarray(sharded_gather_count_multi(mesh, "or", drows, idx, interpret=True))
    want = []
    for q in range(3):
        acc = rows[:, idx[q, 0]].copy()
        for j in range(1, 4):
            acc |= rows[:, idx[q, j]]
        want.append(int(bw.np_popcount(acc).sum()))
    assert got.tolist() == want
    # Tree kernel under the mesh: random perfect-tree programs vs numpy.
    from pilosa_tpu.parallel.sharded import sharded_gather_count_tree

    leaves = rng.integers(0, n_rows, size=(4, 8), dtype=np.int32)
    opc = rng.integers(0, 5, size=(4, 7), dtype=np.int32)
    got_t = np.asarray(
        sharded_gather_count_tree(mesh, drows, leaves, opc, interpret=True)
    )
    assert got_t.tolist() == bw.np_gather_count_tree(rows, leaves, opc).tolist()


def test_mesh_engine_picks_interpret_pallas(monkeypatch):
    """With PILOSA_TPU_PALLAS_INTERPRET=1 the mesh engine routes fused
    counts through the shard_map'd kernels and matches the jnp form."""
    monkeypatch.setenv("PILOSA_TPU_PALLAS_INTERPRET", "1")
    from pilosa_tpu.engine import MeshEngine

    eng = MeshEngine()
    rng = np.random.default_rng(13)
    rows = rng.integers(0, 1 << 32, size=(8, 4, 1024), dtype=np.uint32)
    assert eng._pallas_mode(8, 1024) == "interpret"
    pairs = rng.integers(0, 4, size=(6, 2)).astype(np.int32)
    got = eng.gather_count("and", rows, pairs)
    from pilosa_tpu.ops import bitwise as bw

    want = [
        int(bw.np_popcount(rows[:, int(a)] & rows[:, int(b)]).sum()) for a, b in pairs
    ]
    assert got.tolist() == want


def test_replica_mesh_gather_count(rng):
    """(4, 2) slice x replica mesh: the batch splits over the replica
    axis, each replica group answers its half against its full
    slice-sharded copy with a replica-group psum, and the reassembled
    counts equal numpy (VERDICT r3 item 9; cluster.go:220-240 analog)."""
    import jax

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.parallel import ReplicaMesh, replica_gather_count

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ReplicaMesh(n_replicas=2, devices=jax.devices()[:8])
    assert mesh.n_devices == 4 and mesh.n_replicas == 2

    S, R, W, B = 8, 16, 1024, 12
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
    drm = mesh.shard_stack(rm)  # sharded over slice, replicated over replica
    for op in ("and", "or", "xor", "andnot"):
        got = np.asarray(
            replica_gather_count(mesh, op, drm, jax.numpy.asarray(pairs), interpret=True)
        )
        want = []
        for p0, p1 in pairs:
            a, b2 = rm[:, int(p0)], rm[:, int(p1)]
            v = {"and": a & b2, "or": a | b2, "xor": a ^ b2, "andnot": a & ~b2}[op]
            want.append(int(bw.np_popcount(v).sum()))
        assert got.tolist() == want, op
    # Batch not divisible by replica_n is a loud error, not silent truncation.
    with pytest.raises(ValueError):
        replica_gather_count(mesh, "and", drm, jax.numpy.asarray(pairs[:11]), interpret=True)
