"""Generation-keyed query result cache (pilosa_tpu/qcache/).

Covers: exact cache/execution equivalence under interleaved writes (a
stateful property test in the style of test_fragment_stateful.py), the
admission/eviction/error/bypass unit semantics, the X-Pilosa-No-Cache
header end to end through the HTTP handler, deletion purge hooks, the
canonical call-tree fingerprint, /debug/vars counters, and the
[cache] ranking-debounce-s promotion (satellite).
"""

import json
import tempfile

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.pilosa import SLICE_WIDTH, PilosaError
from pilosa_tpu.qcache import (
    NO_CACHE_HEADER,
    QueryCache,
    generation_vector,
    referenced_frames,
)

Q_PAIR = 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'


@pytest.fixture()
def env(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    fr = h.index("i").frame("f")
    for c in range(10):
        fr.set_bit("standard", 0, c)
    for c in range(5, 15):
        fr.set_bit("standard", 1, c)
    qc = QueryCache(min_cost_ms=0.0)
    ex = Executor(h, engine="numpy", qcache=qc)
    yield h, fr, ex, qc
    h.close()


def test_hit_serves_identical_results(env):
    h, fr, ex, qc = env
    r1 = ex.execute("i", Q_PAIR)
    r2 = ex.execute("i", Q_PAIR)
    assert r1 == r2 == [5]
    assert (qc.hits, qc.misses, qc.stores) == (1, 1, 1)
    assert len(qc) == 1 and qc.bytes > 0


def test_executor_write_invalidates(env):
    h, fr, ex, qc = env
    assert ex.execute("i", Q_PAIR) == [5]
    ex.execute("i", 'SetBit(rowID=0, frame="f", columnID=7)')  # already set: no change
    # An idempotent write that changed nothing bumps no generation, so
    # the entry stays valid.
    assert ex.execute("i", Q_PAIR) == [5] and qc.hits == 1
    ex.execute("i", 'SetBit(rowID=0, frame="f", columnID=12)')
    # Read-your-writes: the generation bump forces a miss and the fresh
    # answer reflects the write.
    assert ex.execute("i", Q_PAIR) == [6]
    assert qc.misses == 2


def test_direct_fragment_write_invalidates(env):
    """The validity token is the fragment generation, maintained inside
    the fragment's own locked mutators — so writers that never touch
    this executor (imports, sync, another executor) still invalidate."""
    h, fr, ex, qc = env
    assert ex.execute("i", Q_PAIR) == [5]
    fr.set_bit("standard", 1, 2)
    assert ex.execute("i", Q_PAIR) == [6]
    fr.import_bits(np.array([0], dtype=np.uint64), np.array([13], dtype=np.uint64))
    assert ex.execute("i", Q_PAIR) == [7]
    assert qc.hits == 0 and qc.misses == 3


def test_new_slice_invalidates(env):
    h, fr, ex, qc = env
    assert ex.execute("i", Q_PAIR) == [5]
    fr.set_bit("standard", 0, SLICE_WIDTH + 3)  # new max slice
    fr.set_bit("standard", 1, SLICE_WIDTH + 3)
    assert ex.execute("i", Q_PAIR) == [6]


def test_admission_min_cost_ms():
    """Only results whose measured cost clears min-cost-ms are stored."""
    clk = [0.0]

    def fake_clock():
        return clk[0]

    qc = QueryCache(min_cost_ms=5.0, clock=fake_clock)
    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        h.create_index("i").create_frame("f", FrameOptions())
        h.index("i").frame("f").set_bit("standard", 0, 1)
        # Cheap execution (0 ms on the fake clock): not admitted.
        _, tok = qc.lookup(h, "i", Q_PAIR, None)
        assert tok is not None
        assert not qc.commit(h, tok, [1])
        assert qc.stores == 0 and len(qc) == 0
        # Expensive execution (10 ms): admitted.
        _, tok = qc.lookup(h, "i", Q_PAIR, None)
        clk[0] += 0.010
        assert qc.commit(h, tok, [1])
        assert qc.stores == 1 and len(qc) == 1
        cached, _ = qc.lookup(h, "i", Q_PAIR, None)
        assert cached == [1]
        h.close()


def test_byte_bound_eviction(env):
    h, fr, ex, qc = env
    qc.max_bytes = 2 * 560 + 10  # room for ~2 count entries
    qs = [
        f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={a}, frame="f")))'
        for a in range(6)
    ]
    for q in qs:
        ex.execute("i", q)
    assert qc.evictions > 0
    assert qc.bytes <= qc.max_bytes
    assert len(qc) >= 1
    # LRU: the most recent entry survived, the oldest was evicted.
    assert ex.execute("i", qs[-1]) == ex.execute("i", qs[-1])
    hits0 = qc.hits
    ex.execute("i", qs[-1])
    assert qc.hits == hits0 + 1
    misses0 = qc.misses
    ex.execute("i", qs[0])
    assert qc.misses == misses0 + 1


def test_oversized_result_never_stored(env):
    h, fr, ex, qc = env
    qc.max_bytes = 8  # smaller than any entry
    ex.execute("i", Q_PAIR)
    assert qc.stores == 0 and qc.bytes == 0


def test_errors_never_cached(env):
    h, fr, ex, qc = env
    bad = 'Count(Bitmap(rowID=0, frame="nope"))'
    for _ in range(2):
        with pytest.raises(PilosaError):
            ex.execute("i", bad)
    assert qc.stores == 0 and qc.hits == 0
    assert qc.misses == 2  # eligible shape, but the error aborts the commit


def test_write_and_nondeterministic_trees_ineligible(env):
    h, fr, ex, qc = env
    # Writes, TopN (rank-cache debounce timing), and top-level Bitmap
    # (attaches attrs, which mutate without a generation bump) must
    # never be cached.
    ex.execute("i", 'SetBit(rowID=0, frame="f", columnID=99)')
    ex.execute("i", 'TopN(frame="f", n=2)')
    ex.execute("i", 'Bitmap(rowID=0, frame="f")')
    # A mixed request carrying any write stays uncacheable as a whole.
    ex.execute("i", f'SetBit(rowID=0, frame="f", columnID=98) {Q_PAIR}')
    assert qc.stores == 0 and len(qc) == 0
    # Uncacheable traffic counts as INELIGIBLE, never as a bypass — the
    # bypass counter is reserved for explicit X-Pilosa-No-Cache requests
    # so the A/B hit-rate denominator stays clean.
    assert qc.ineligible == 4 and qc.bypasses == 0


def test_no_cache_exec_option(env):
    h, fr, ex, qc = env
    r1 = ex.execute("i", Q_PAIR)
    nc = ExecOptions(no_cache=True)
    r2 = ex.execute("i", Q_PAIR, opt=nc)
    assert r1 == r2
    # Bypass neither read nor stored: one store from r1, no hit for r2.
    assert qc.stores == 1 and qc.hits == 0 and qc.bypasses == 1


def test_no_cache_header_through_handler(env):
    """X-Pilosa-No-Cache: 1 threads through the HTTP handler into
    ExecOptions — the per-request A/B lever."""
    from pilosa_tpu.server.handler import Handler

    h, fr, ex, qc = env
    handler = Handler(h, ex)

    def post(headers=None):
        status, _, payload = handler.dispatch(
            "POST", "/index/i/query", {}, Q_PAIR.encode(), headers or {}
        )[:3]
        assert status == 200
        return json.loads(payload)["results"]

    assert post() == [5]
    assert post() == [5] and qc.hits == 1
    assert post({NO_CACHE_HEADER.lower(): "1"}) == [5]
    assert qc.hits == 1 and qc.bypasses == 1  # neither served nor stored


def test_client_sets_no_cache_header():
    from pilosa_tpu.server.client import Client

    captured = {}

    class _Cli(Client):
        def _request(self, method, path, body=None, **kw):
            captured.update(kw.get("headers") or {})
            from pilosa_tpu import wire

            return 200, wire.encode_query_response(results=[0])

    c = _Cli("localhost:1")
    c.execute_query("i", "Count(Bitmap(rowID=0))", no_cache=True)
    assert captured.get(NO_CACHE_HEADER) == "1"
    captured.clear()
    c.execute_query("i", "Count(Bitmap(rowID=0))")
    assert NO_CACHE_HEADER not in captured


def test_purge_on_frame_and_index_drop(env):
    h, fr, ex, qc = env
    ex.execute("i", Q_PAIR)
    assert len(qc) == 1
    ex.drop_frame_state("i", "f")
    assert len(qc) == 0 and qc.bytes == 0
    ex.execute("i", Q_PAIR)
    assert len(qc) == 1
    ex.drop_index_state("i")
    assert len(qc) == 0 and qc.bytes == 0


def test_delete_frame_route_purges(env):
    """The HTTP deletion route drives the purge, so a recreated
    namesake frame can never serve the old frame's results."""
    from pilosa_tpu.server.handler import Handler

    h, fr, ex, qc = env
    handler = Handler(h, ex)
    assert ex.execute("i", Q_PAIR) == [5]
    status, _, _ = handler.dispatch("DELETE", "/index/i/frame/f", {}, b"", {})[:3]
    assert status == 200 and len(qc) == 0
    h.index("i").create_frame("f", FrameOptions())
    fr2 = h.index("i").frame("f")
    fr2.set_bit("standard", 0, 1)
    fr2.set_bit("standard", 1, 1)
    assert ex.execute("i", Q_PAIR) == [1]


def test_canonical_fingerprint_shares_entry(env):
    h, fr, ex, qc = env
    ex.execute("i", Q_PAIR)
    # Same call tree, different formatting: one entry, served as a hit.
    variant = 'Count(Intersect(Bitmap(rowID=0,frame="f"),Bitmap(rowID=1,frame="f")))'
    assert ex.execute("i", variant) == [5]
    assert qc.hits == 1 and len(qc) == 1


def test_slices_key_separates_partial_requests(env):
    h, fr, ex, qc = env
    full = ex.execute("i", Q_PAIR)
    part = ex.execute("i", Q_PAIR, slices=[0])
    assert full == part == [5]  # single-slice dataset: same answer
    assert len(qc) == 2 and qc.hits == 0
    assert ex.execute("i", Q_PAIR, slices=[0]) == [5]
    assert qc.hits == 1


def test_slices_key_order_insensitive_and_empty_distinct(env):
    """The slice-set key is a SET: the same slices in a different order
    share one entry, and an explicit empty list never aliases the
    all-slices (None) request."""
    h, fr, ex, qc = env
    fr.set_bit("standard", 0, SLICE_WIDTH + 3)
    fr.set_bit("standard", 1, SLICE_WIDTH + 3)
    assert ex.execute("i", Q_PAIR, slices=[0, 1]) == [6]
    assert ex.execute("i", Q_PAIR, slices=[1, 0]) == [6]  # same entry: hit
    assert qc.hits == 1 and len(qc) == 1
    full = ex.execute("i", Q_PAIR)  # None = all slices: its own entry
    assert full == [6] and len(qc) == 2
    # An explicit empty list keys its own entry — it never aliases the
    # all-slices (None) key (execution happens to answer both the same
    # way today; the key must not bake that coincidence in).
    misses0 = qc.misses
    ex.execute("i", Q_PAIR, slices=[])
    assert qc.misses == misses0 + 1 and len(qc) == 3
    assert ex.execute("i", Q_PAIR) == [6]
    assert qc.hits == 2


def test_multi_node_cluster_scope_never_cached(tmp_path):
    """Clustered executors cache ONLY remote-scope sub-requests: a
    coordinator-scope answer covers remotely-owned slices whose writes
    never bump local generations (the coordinator forwards them without
    a local write), so caching it would serve stale reads forever."""
    from pilosa_tpu.cluster import Cluster, Node

    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    fr = h.index("i").frame("f")
    for s in range(4):
        fr.set_bit("standard", 0, s * SLICE_WIDTH + 1)
        fr.set_bit("standard", 1, s * SLICE_WIDTH + 1)

    hosts = ["h0:1", "h1:1"]
    cluster = Cluster([Node(host) for host in hosts], replica_n=2)

    class PeerClient:
        """Stand-in peer answering from the same holder, uncached."""

        def __init__(self, host):
            self.host = host

        def execute_remote(self, index, query, slices=None, **kw):
            return Executor(h, engine="numpy").execute(
                index, query, slices=slices, opt=ExecOptions(remote=True)
            )

        def execute_remote_call(self, index, call, slices, **kw):
            from pilosa_tpu import pql

            return self.execute_remote(index, pql.Query(calls=[call]), slices)[0]

    qc = QueryCache(min_cost_ms=0.0)
    ex = Executor(
        h, engine="numpy", cluster=cluster, client_factory=PeerClient,
        host="h0:1", qcache=qc,
    )
    try:
        # Coordinator scope: correct answers, but never cached.
        assert ex.execute("i", Q_PAIR) == [4]
        assert ex.execute("i", Q_PAIR) == [4]
        assert qc.ineligible == 2 and qc.stores == 0 and len(qc) == 0
        # Remote scope (what peers ask THIS node): cacheable, and a
        # local write (the forwarded-write path on an owner) invalidates.
        ropt = ExecOptions(remote=True)
        assert ex.execute("i", Q_PAIR, slices=[0], opt=ropt) == [1]
        assert ex.execute("i", Q_PAIR, slices=[0], opt=ropt) == [1]
        assert qc.hits == 1 and qc.stores == 1
        fr.set_bit("standard", 0, 2)
        fr.set_bit("standard", 1, 2)
        assert ex.execute("i", Q_PAIR, slices=[0], opt=ropt) == [2]
    finally:
        h.close()


def test_stats_counters_at_debug_vars(tmp_path):
    from pilosa_tpu.stats import ExpvarStatsClient

    stats = ExpvarStatsClient()
    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    h.index("i").frame("f").set_bit("standard", 0, 1)
    h.index("i").frame("f").set_bit("standard", 1, 1)
    qc = QueryCache(min_cost_ms=0.0, stats=stats)
    ex = Executor(h, engine="numpy", qcache=qc)
    ex.execute("i", Q_PAIR)
    ex.execute("i", Q_PAIR)
    ex.execute("i", Q_PAIR, opt=ExecOptions(no_cache=True))
    ex.execute("i", 'SetBit(rowID=2, frame="f", columnID=3)')
    snap = stats.snapshot()
    assert snap["qcache.hit"] == 1
    assert snap["qcache.miss"] == 1
    assert snap["qcache.store"] == 1
    assert snap["qcache.bypass"] == 1
    assert snap["qcache.ineligible"] == 1  # the write, not a bypass
    assert snap["qcache.bytes"] > 0
    h.close()


def test_generation_vector_shape(env):
    h, fr, ex, qc = env
    v1 = generation_vector(h, "i", ("f",))
    v2 = generation_vector(h, "i", ("f",))
    assert v1 == v2
    fr.set_bit("standard", 3, 3)
    assert generation_vector(h, "i", ("f",)) != v1
    assert generation_vector(h, "missing", ("f",)) is None
    # Missing frames are distinguishable from empty ones.
    assert ("ghost", None) in generation_vector(h, "i", ("ghost",))


def test_referenced_frames():
    from pilosa_tpu import pql

    q = pql.parse(
        'Count(Intersect(Bitmap(rowID=1, frame="a"), Bitmap(rowID=2, frame="b")))'
        ' Count(Bitmap(rowID=3))'
    )
    assert referenced_frames(q) == ("a", "b", "general")


def test_executor_env_default(monkeypatch):
    """Direct Executor construction keeps pre-qcache behavior unless
    PILOSA_TPU_QCACHE opts in (the server wires [qcache] explicitly)."""
    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        monkeypatch.delenv("PILOSA_TPU_QCACHE", raising=False)
        assert Executor(h, engine="numpy").qcache is None
        monkeypatch.setenv("PILOSA_TPU_QCACHE", "1")
        monkeypatch.setenv("PILOSA_TPU_QCACHE_MAX_BYTES", "1024")
        monkeypatch.setenv("PILOSA_TPU_QCACHE_MIN_COST_MS", "2.5")
        ex = Executor(h, engine="numpy")
        assert ex.qcache is not None
        assert ex.qcache.max_bytes == 1024
        assert ex.qcache.min_cost_ms == 2.5
        h.close()


def test_server_wiring_and_debug_vars(tmp_path):
    """[qcache] config reaches the real server: repeated HTTP queries
    hit, /debug/vars carries the counters, and disabling via config
    yields no cache at all."""
    import urllib.request

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=str(tmp_path / "d"), host="127.0.0.1:0", engine="numpy",
        qcache_min_cost_ms=0.0,
    )
    s = Server(cfg)
    s.open()
    try:
        base = f"http://{s.host}"

        def post(path, data):
            req = urllib.request.Request(base + path, data=data.encode(), method="POST")
            return json.loads(urllib.request.urlopen(req, timeout=30).read())

        post("/index/i", "{}")
        post("/index/i/frame/f", "{}")
        post("/index/i/query", 'SetBit(rowID=0, frame="f", columnID=1)')
        post("/index/i/query", 'SetBit(rowID=1, frame="f", columnID=1)')
        r1 = post("/index/i/query", Q_PAIR)
        r2 = post("/index/i/query", Q_PAIR)
        assert r1 == r2 and r1["results"] == [1]
        assert s.qcache is not None and s.qcache.hits == 1
        with urllib.request.urlopen(base + "/debug/vars", timeout=30) as resp:
            snap = json.loads(resp.read())
        assert snap["qcache.hit"] == 1 and snap["qcache.bytes"] > 0
    finally:
        s.close()
    cfg2 = Config(data_dir=str(tmp_path / "d2"), host="127.0.0.1:0",
                  engine="numpy", qcache_enabled=False)
    s2 = Server(cfg2)
    assert s2.qcache is None and s2.executor.qcache is None


# -- config surface ---------------------------------------------------------


def test_qcache_config_toml_and_env(monkeypatch):
    from pilosa_tpu.config import Config

    cfg = Config.from_dict(
        {"qcache": {"enabled": False, "max-bytes": 4096, "min-cost-ms": 7.5}}
    )
    assert cfg.qcache_enabled is False
    assert cfg.qcache_max_bytes == 4096
    assert cfg.qcache_min_cost_ms == 7.5
    monkeypatch.setenv("PILOSA_TPU_QCACHE", "true")
    monkeypatch.setenv("PILOSA_TPU_QCACHE_MAX_BYTES", "8192")
    monkeypatch.setenv("PILOSA_TPU_QCACHE_MIN_COST_MS", "0.5")
    cfg.apply_env()
    assert cfg.qcache_enabled is True
    assert cfg.qcache_max_bytes == 8192
    assert cfg.qcache_min_cost_ms == 0.5


def test_ranking_debounce_promotion(tmp_path, monkeypatch):
    """[cache] ranking-debounce-s: Config resolves TOML + env ONCE
    (apply_env), the value threads through Holder -> Index -> Frame ->
    View -> Fragment construction (no module global — two holders in
    one process keep independent settings), and the debounce moves."""
    from pilosa_tpu.config import Config
    from pilosa_tpu.core.cache import RankCache

    cfg = Config.from_dict({"cache": {"ranking-debounce-s": "2s"}})
    assert cfg.ranking_debounce_s == 2.0
    monkeypatch.setenv("PILOSA_TPU_RANKING_DEBOUNCE_S", "3.5")
    cfg.apply_env()
    assert cfg.ranking_debounce_s == 3.5

    now = [100.0]
    rc = RankCache(4, _now=lambda: now[0], debounce_s=2.0)
    assert rc.debounce_s == 2.0
    rc.add(1, 10)  # first invalidate recalculates (update_time far past)
    t0 = rc._update_time
    now[0] += 1.0
    rc.add(2, 20)  # inside the 2 s debounce: no recalc
    assert rc._update_time == t0
    now[0] += 1.5
    rc.add(3, 30)  # past it: recalc
    assert rc._update_time > t0

    # RankCache itself never reads the env — Config is the only
    # resolution point, so construction is deterministic.
    assert RankCache(4, _now=lambda: now[0]).debounce_s == 10.0

    # The configured value reaches deeply-nested fragment caches through
    # holder construction, and a second holder keeps its own setting.
    ha = Holder(str(tmp_path / "a"), ranking_debounce_s=cfg.ranking_debounce_s)
    hb = Holder(str(tmp_path / "b"))
    for h in (ha, hb):
        h.open()
        h.create_index("i").create_frame(
            "f", FrameOptions(cache_type="ranked", cache_size=4)
        )
        h.index("i").frame("f").set_bit("standard", 0, 1)
    frag_a = ha.index("i").frame("f").view("standard").fragment(0)
    frag_b = hb.index("i").frame("f").view("standard").fragment(0)
    assert frag_a.cache.debounce_s == 3.5
    assert frag_b.cache.debounce_s == 10.0  # module default, not leaked
    ha.close()
    hb.close()


# -- stateful equivalence (style of test_fragment_stateful.py) ---------------

_QUERIES = [
    'Count(Bitmap(rowID=0, frame="f"))',
    'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))',
    'Count(Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"),'
    ' Bitmap(rowID=3, frame="f")))',
    'Count(Difference(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))',
    'Count(Xor(Bitmap(rowID=4, frame="f"), Bitmap(rowID=5, frame="f")))',
    'Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=4, frame="f"))',
]


def _assert_equivalent(got, want):
    if hasattr(got[0], "segments"):  # QueryBitmap: compare bit sets
        assert got[0].bits() == want[0].bits()
    else:
        assert got == want


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_equivalence_random_interleaving(tmp_path, seed):
    """Random interleavings of writes (executor + direct-fragment),
    clears, and repeated queries: every answer from the cached executor
    must equal a FRESH uncached execution of the same query — the
    exactness contract (read-your-writes included, since a fresh
    execution by definition sees every prior write).  Deterministic
    seeds so the suite needs no hypothesis; the machine below upgrades
    to shrinking fuzz when hypothesis is installed."""
    rng = np.random.default_rng(seed)
    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    fr = h.index("i").frame("f")
    qc = QueryCache(min_cost_ms=0.0)
    ex = Executor(h, engine="numpy", qcache=qc)
    fresh = Executor(h, engine="numpy", qcache=None)
    try:
        for _ in range(200):
            op = rng.integers(0, 5)
            r = int(rng.integers(0, 6))
            c = int(rng.integers(0, 64)) if rng.random() < 0.7 else int(
                rng.integers(SLICE_WIDTH - 8, SLICE_WIDTH + 64)
            )
            if op == 0:
                ex.execute("i", f'SetBit(rowID={r}, frame="f", columnID={c})')
            elif op == 1:
                fr.set_bit("standard", r, c)
            elif op == 2:
                fr.clear_bit("standard", r, c)
            else:  # queries twice as likely as any single write kind
                q = _QUERIES[int(rng.integers(0, len(_QUERIES)))]
                _assert_equivalent(ex.execute("i", q), fresh.execute("i", q))
        assert qc.hits > 0  # the interleaving really exercised the cache
    finally:
        h.close()


try:
    from hypothesis import settings
    from hypothesis import strategies as st
    from hypothesis.stateful import RuleBasedStateMachine, rule
except ImportError:
    pass
else:
    _ROW = st.integers(0, 5)
    _COL = st.one_of(
        st.integers(0, 64), st.integers(SLICE_WIDTH - 8, SLICE_WIDTH + 64)
    )
    _QIDX = st.integers(0, len(_QUERIES) - 1)

    class QCacheEquivalenceMachine(RuleBasedStateMachine):
        """Shrinking-fuzz upgrade of the seeded interleaving test."""

        def __init__(self):
            super().__init__()
            import shutil

            self._dir = tempfile.mkdtemp()
            self.h = Holder(self._dir)
            self.h.open()
            self.h.create_index("i").create_frame("f", FrameOptions())
            self.fr = self.h.index("i").frame("f")
            self.qc = QueryCache(min_cost_ms=0.0)
            self.ex = Executor(self.h, engine="numpy", qcache=self.qc)
            self.fresh = Executor(self.h, engine="numpy", qcache=None)
            self._shutil = shutil

        def teardown(self):
            try:
                self.h.close()
            finally:
                self._shutil.rmtree(self._dir, ignore_errors=True)

        @rule(r=_ROW, c=_COL)
        def executor_write(self, r, c):
            self.ex.execute("i", f'SetBit(rowID={r}, frame="f", columnID={c})')

        @rule(r=_ROW, c=_COL)
        def direct_write(self, r, c):
            self.fr.set_bit("standard", r, c)

        @rule(r=_ROW, c=_COL)
        def clear(self, r, c):
            self.fr.clear_bit("standard", r, c)

        @rule(k=_QIDX)
        def query(self, k):
            _assert_equivalent(
                self.ex.execute("i", _QUERIES[k]),
                self.fresh.execute("i", _QUERIES[k]),
            )

    QCacheEquivalenceMachine.TestCase.settings = settings(
        max_examples=20, stateful_step_count=30, deadline=None
    )
    TestQCacheEquivalence = QCacheEquivalenceMachine.TestCase
