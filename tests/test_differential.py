"""Randomized end-to-end differential tests: numpy vs jax engines.

The strongest correctness harness we have: generate random (valid) PQL
against a randomly-populated holder and require the numpy engine (pure
host reference) and the jax engine (the production device path, CPU
backend under the suite) to agree EXACTLY on every result — counts,
bitmaps, TopN pairs — across fused, Gram-upgraded, fast-lane, and
sequential paths.
"""

import random

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.pilosa import SLICE_WIDTH


def _norm(results):
    out = []
    for r in results:
        if hasattr(r, "bits"):
            out.append(("bitmap", tuple(r.bits()), tuple(sorted(r.attrs.items()))))
        elif isinstance(r, list):  # TopN pairs
            out.append(("pairs", tuple((p.id, p.count) for p in r)))
        else:
            out.append(r)
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_pql_numpy_vs_jax(tmp_path, seed):
    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("d")
    idx.create_frame("f", FrameOptions(inverse_enabled=True, cache_type="ranked"))
    idx.create_frame("g", FrameOptions())
    idx.create_frame("empty", FrameOptions())  # never written: zero paths
    for frame in ("f", "g"):
        fr = idx.frame(frame)
        rows = nprng.integers(0, 8, size=400)
        cols = nprng.integers(0, 3 * SLICE_WIDTH, size=400)
        fr.import_bits(rows, cols)
    e_np = Executor(h, engine="numpy")
    e_jx = Executor(h, engine="jax")

    def bitmap(frame):
        if frame == "f" and rng.random() < 0.3:
            return f'Bitmap(columnID={rng.randrange(200)}, frame="f")'
        if rng.random() < 0.1:  # missing rows / empty frame: zero paths
            frame = rng.choice([frame, "empty"])
            return f'Bitmap(rowID={rng.randrange(50, 60)}, frame="{frame}")'
        return f'Bitmap(rowID={rng.randrange(8)}, frame="{frame}")'

    def tree(depth, frame):
        if depth == 0 or rng.random() < 0.4:
            return bitmap(frame)
        op = rng.choice(["Intersect", "Union", "Difference", "Xor"])
        kids = ", ".join(tree(depth - 1, frame) for _ in range(rng.choice([2, 2, 3])))
        return f"{op}({kids})"

    def call():
        roll = rng.random()
        frame = rng.choice(["f", "g"])
        if roll < 0.45:
            return f"Count({tree(rng.choice([1, 2]), frame)})"
        if roll < 0.75:
            return tree(rng.choice([1, 2]), frame)
        if roll < 0.88:
            return f'TopN(frame="{frame}", n={rng.randrange(1, 6)})'
        # TopN with a src bitmap: the engine-backed candidate scorer path.
        return f'TopN({bitmap(rng.choice(["f", "g"]))}, frame="f", n={rng.randrange(1, 6)})'

    for _ in range(35):
        q = " ".join(call() for _ in range(rng.randrange(1, 6)))
        got_np = _norm(e_np.execute("d", q))
        got_jx = _norm(e_jx.execute("d", q))
        assert got_np == got_jx, f"divergence on: {q}"
        # Occasional writes between queries exercise cache invalidation
        # (matrix patch/append, Gram rebuild, device row caches).
        if rng.random() < 0.4:
            wq = (
                f'SetBit(rowID={rng.randrange(8)}, frame="f", columnID={rng.randrange(2 * SLICE_WIDTH)}) '
                f'SetBit(rowID={rng.randrange(8)}, frame="g", columnID={rng.randrange(SLICE_WIDTH)})'
            )
            assert e_np.execute("d", wq) is not None
    h.close()


@pytest.mark.parametrize("seed", [7, 8])
def test_random_range_queries_numpy_vs_jax(tmp_path, seed):
    """Time-quantum Range covers through both engines must agree."""
    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("d")
    idx.create_frame("t", FrameOptions(time_quantum="YMDH"))
    fr = idx.frame("t")
    e_np = Executor(h, engine="numpy")
    months = [f"2017-{m:02d}-{d:02d}T{hh:02d}:00" for m in (1, 2, 3) for d in (1, 15) for hh in (0, 12)]
    for _ in range(120):
        r = int(nprng.integers(0, 4))
        c = int(nprng.integers(0, 2 * SLICE_WIDTH))
        ts = rng.choice(months)
        e_np.execute("d", f'SetBit(rowID={r}, frame="t", columnID={c}, timestamp="{ts}")')
    e_jx = Executor(h, engine="jax")
    spans = [("2017-01-01T00:00", "2017-02-01T00:00"), ("2017-01-10T00:00", "2017-03-20T12:00"),
             ("2016-12-01T00:00", "2018-01-01T00:00"), ("2017-02-15T06:00", "2017-02-15T18:00")]
    counts = []
    singles = []
    for _ in range(12):
        r = rng.randrange(4)
        start, end = rng.choice(spans)
        q = f'Range(rowID={r}, frame="t", start="{start}", end="{end}")'
        got_np = _norm(e_np.execute("d", q))
        got_jx = _norm(e_jx.execute("d", q))
        assert got_np == got_jx, f"divergence on: {q}"
        q2 = f"Count({q})"
        got_c = e_np.execute("d", q2)
        assert got_c == e_jx.execute("d", q2)
        counts.append(q2)
        singles.extend(got_c)
    # The same Counts as ONE batched request take the fused multi-view OR
    # path in both engines and must match the sequential singles.
    batch = " ".join(counts)
    assert e_np.execute("d", batch) == singles
    assert e_jx.execute("d", batch) == singles
    h.close()


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_random_nested_trees_through_fused_lane(tmp_path, seed):
    """All-Count batches of RANDOM nested trees must (a) take the fused
    tree lane, (b) agree across engines, and (c) agree with the
    sequential per-call path — the differential fuzz for the tree lane
    (executor.go:261-276 fused; VERDICT r4 item 5's done-criterion)."""
    from pilosa_tpu.executor import ExecOptions
    from pilosa_tpu.pql.parser import parse

    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("d")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    fr.import_bits(
        nprng.integers(0, 10, size=500), nprng.integers(0, 3 * SLICE_WIDTH, size=500)
    )
    e_np = Executor(h, engine="numpy")
    e_jx = Executor(h, engine="jax")

    def tree(depth):
        if depth == 0 or rng.random() < 0.3:
            return f'Bitmap(rowID={rng.randrange(10)}, frame="f")'
        op = rng.choice(["Intersect", "Union", "Difference", "Xor"])
        kids = ", ".join(
            tree(depth - 1) for _ in range(rng.choice([2, 2, 2, 3, 4]))
        )
        return f"{op}({kids})"

    fused_batches = 0
    for round_i in range(12):
        qs = []
        # The first 4 batches draw only depth<=2, arity<=3 trees — within
        # the fuse depth cap BY CONSTRUCTION, so the >=4 exercise floor
        # below holds for ANY seed (soak runs use arbitrary seeds); the
        # rest draw unrestricted shapes to also cover the decline path.
        depths = [1, 2] if round_i < 4 else [1, 2, 3]
        while len(qs) < rng.randrange(2, 7):
            t = tree(rng.choice(depths))
            if t.startswith("Bitmap"):
                continue  # Count(Bitmap) isn't a tree-lane shape
            qs.append(f"Count({t})")
        batch = " ".join(qs)
        calls = parse(batch).calls
        # (a) the lane fires EXACTLY when every call compiles (flat
        # pair/multi shapes or trees within the depth cap); deeper trees
        # decline the whole batch to the sequential path.
        def compilable(c):
            ch = c.children[0]
            if all(k.name == "Bitmap" for k in ch.children) and (
                ch.name != "Xor" or len(ch.children) == 2
            ):
                return True  # flat lanes
            return e_np._compile_count_tree("d", ch) is not None

        fused = e_np._fuse_count_pair_batch(
            "d", calls, list(range(3)), None, ExecOptions()
        )
        if all(compilable(c) for c in calls):
            assert fused is not None and len(fused) == len(qs), batch
            fused_batches += 1
        # (b)+(c): engines agree with each other and with sequential
        seq = [e_np.execute("d", q)[0] for q in qs]
        if fused is not None:
            assert [fused[i] for i in range(len(qs))] == seq, batch
        assert e_np.execute("d", batch) == seq, batch
        assert e_jx.execute("d", batch) == seq, batch
        if rng.random() < 0.3:  # writes between batches: cache invalidation
            e_np.execute(
                "d",
                f'SetBit(rowID={rng.randrange(10)}, frame="f", columnID={rng.randrange(3 * SLICE_WIDTH)})',
            )
    assert fused_batches >= 4  # the lane actually exercised, not all-declines
    h.close()


@pytest.mark.parametrize("seed", [21, 22])
def test_serve_lane_interleaved_writes_fuzz(tmp_path, seed):
    """Stateful fuzz for the single-call native serve lane: random
    interleavings of singleton writes and flat Count batches through the
    jax executor must match a numpy executor on the same holder at every
    step (the serve state must invalidate on every write, never serve a
    pre-write Gram)."""
    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("d")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    fr.import_bits(
        nprng.integers(0, 16, size=300), nprng.integers(0, 2 * SLICE_WIDTH, size=300)
    )
    import os as _os

    e_jx = Executor(h, engine="jax")
    e_np = Executor(h, engine="numpy")

    def oracle(q):
        # The oracle must NOT share the native fast lanes with the code
        # under test (the serve lane is engine-independent — the numpy
        # executor would arm its own serve state and mask a staleness
        # bug); NO_FASTLANE is read per request, so toggling it forces
        # the full-parse sequential path for the oracle only.
        _os.environ["PILOSA_TPU_NO_FASTLANE"] = "1"
        try:
            return e_np.execute("d", q)
        finally:
            del _os.environ["PILOSA_TPU_NO_FASTLANE"]

    def batch():
        ops = ["Intersect", "Union", "Xor", "Difference"]
        return " ".join(
            f'Count({rng.choice(ops)}(Bitmap(rowID={rng.randrange(16)}, frame="f"), '
            f'Bitmap(rowID={rng.randrange(16)}, frame="f")))'
            for _ in range(rng.randrange(2, 20))
        )

    wrote = False
    served_after_write = 0
    for step in range(60):
        roll = rng.random()
        if roll < 0.3:
            q = (
                f'SetBit(rowID={rng.randrange(16)}, frame="f", '
                f'columnID={rng.randrange(2 * SLICE_WIDTH)})'
            )
            e_jx.execute("d", q)
            # Write visibility: the oracle's re-issue must observe it.
            assert oracle(q) == [False]
            wrote = True
        else:
            q = batch()
            got = e_jx.execute("d", q)
            want = oracle(q)
            assert got == want, f"step {step}: {q}"
            if wrote and e_jx._serve_states:
                served_after_write += 1
    # The lane re-armed and served AFTER invalidating writes.
    assert served_after_write > 5
    h.close()
