"""Executor tests (reference analog: executor_test.go, local paths)."""

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.executor import ExecOptions, Executor, QueryBitmap
from pilosa_tpu.pilosa import PilosaError, ErrTooManyWrites, SLICE_WIDTH


@pytest.fixture
def env(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("general", FrameOptions())
    idx.create_frame("f", FrameOptions(inverse_enabled=True, time_quantum="YMDH"))
    e = Executor(h, engine="numpy")
    yield h, e
    h.close()


def test_setbit_bitmap_roundtrip(env):
    h, e = env
    (changed,) = e.execute("i", 'SetBit(rowID=10, frame="f", columnID=100)')
    assert changed is True
    (changed,) = e.execute("i", 'SetBit(rowID=10, frame="f", columnID=100)')
    assert changed is False
    (bm,) = e.execute("i", 'Bitmap(rowID=10, frame="f")')
    assert bm.bits() == [100]
    # inverse view was maintained
    (inv,) = e.execute("i", 'Bitmap(columnID=100, frame="f")')
    assert inv.bits() == [10]


def test_multi_slice_count_intersect(env):
    h, e = env
    cols_a = [1, 2, 3, SLICE_WIDTH + 1, SLICE_WIDTH + 2, 3 * SLICE_WIDTH + 7]
    cols_b = [2, 3, SLICE_WIDTH + 2, 2 * SLICE_WIDTH + 5]
    for c in cols_a:
        e.execute("i", f'SetBit(rowID=1, frame="f", columnID={c})')
    for c in cols_b:
        e.execute("i", f'SetBit(rowID=2, frame="f", columnID={c})')
    (n,) = e.execute("i", 'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))')
    assert n == 3  # {2, 3, W+2}
    (bm,) = e.execute("i", 'Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    assert bm.bits() == [2, 3, SLICE_WIDTH + 2]


def test_union_difference_xor(env):
    h, e = env
    for c in [1, 2]:
        e.execute("i", f'SetBit(rowID=1, frame="f", columnID={c})')
    for c in [2, 3]:
        e.execute("i", f'SetBit(rowID=2, frame="f", columnID={c})')
    (u,) = e.execute("i", 'Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    assert u.bits() == [1, 2, 3]
    (d,) = e.execute("i", 'Difference(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    assert d.bits() == [1]
    (x,) = e.execute("i", 'Xor(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    assert x.bits() == [1, 3]


def test_range_time_views(env):
    h, e = env
    e.execute("i", 'SetBit(rowID=1, frame="f", columnID=7, timestamp="2017-03-02T15:00")')
    e.execute("i", 'SetBit(rowID=1, frame="f", columnID=8, timestamp="2017-05-01T00:00")')
    (bm,) = e.execute(
        "i", 'Range(rowID=1, frame="f", start="2017-03-01T00:00", end="2017-04-01T00:00")'
    )
    assert bm.bits() == [7]
    (bm2,) = e.execute(
        "i", 'Range(rowID=1, frame="f", start="2017-01-01T00:00", end="2018-01-01T00:00")'
    )
    assert bm2.bits() == [7, 8]


def test_topn_two_phase(env):
    h, e = env
    idx = h.index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    # row 1: bits in slices 0 and 1; row 2: fewer bits.
    bits = [(1, c) for c in range(20)] + [(1, SLICE_WIDTH + c) for c in range(15)]
    bits += [(2, c) for c in range(10)] + [(3, 2 * SLICE_WIDTH + 1)]
    frame = h.frame("i", "r")
    rows, cols = zip(*bits)
    frame.import_bits(rows, cols)
    (pairs,) = e.execute("i", 'TopN(frame="r", n=2)')
    assert [(p.id, p.count) for p in pairs] == [(1, 35), (2, 10)]


def test_topn_with_src(env):
    h, e = env
    idx = h.index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    frame = h.frame("i", "r")
    frame.import_bits([1] * 10 + [2] * 10, list(range(10)) + list(range(5, 15)))
    # src = row 1 of frame f
    for c in range(8):
        e.execute("i", f'SetBit(rowID=9, frame="f", columnID={c})')
    (pairs,) = e.execute("i", 'TopN(Bitmap(rowID=9, frame="f"), frame="r", n=5)')
    assert [(p.id, p.count) for p in pairs] == [(1, 8), (2, 3)]


def test_topn_ids_and_threshold(env):
    h, e = env
    idx = h.index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    frame = h.frame("i", "r")
    frame.import_bits([1] * 5 + [2] * 3 + [3] * 1, list(range(5)) + list(range(3)) + [0])
    (pairs,) = e.execute("i", 'TopN(frame="r", ids=[2,3])')
    assert {(p.id, p.count) for p in pairs} == {(2, 3), (3, 1)}
    (pairs2,) = e.execute("i", 'TopN(frame="r", n=10, threshold=3)')
    assert {(p.id, p.count) for p in pairs2} == {(1, 5), (2, 3)}


def test_attrs(env):
    h, e = env
    e.execute("i", 'SetBit(rowID=1, frame="f", columnID=2)')
    (res,) = e.execute("i", 'SetRowAttrs(rowID=1, frame="f", name="alice", active=true)')
    assert res is None
    (bm,) = e.execute("i", 'Bitmap(rowID=1, frame="f")')
    assert bm.attrs == {"name": "alice", "active": True}
    e.execute("i", 'SetColumnAttrs(columnID=2, info="x")')
    (inv,) = e.execute("i", 'Bitmap(columnID=2, frame="f")')
    assert inv.attrs == {"info": "x"}
    # exclude_attrs opt
    (bm2,) = e.execute("i", 'Bitmap(rowID=1, frame="f")', opt=ExecOptions(exclude_attrs=True))
    assert bm2.attrs == {}


def test_errors(env):
    h, e = env
    with pytest.raises(PilosaError):
        e.execute("i", "Bogus(x=1)")
    with pytest.raises(PilosaError):
        e.execute("i", 'Bitmap(rowID=1, frame="nope")')
    with pytest.raises(PilosaError):
        e.execute("i", 'Bitmap(frame="f")')  # neither row nor col
    with pytest.raises(PilosaError):
        e.execute("i", 'Count(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    e2 = Executor(h, engine="numpy", max_writes_per_request=1)
    with pytest.raises(ErrTooManyWrites):
        e2.execute("i", 'SetBit(rowID=1, frame="f", columnID=1) SetBit(rowID=1, frame="f", columnID=2)')


def test_count_on_general_default_frame(env):
    h, e = env
    e.execute("i", "SetBit(rowID=5, frame=general, columnID=9)")
    (n,) = e.execute("i", "Count(Bitmap(rowID=5))")
    assert n == 1


def test_jax_engine_matches_numpy(env, tmp_path):
    # Same queries through the JaxEngine (CPU backend under conftest).
    h, e = env
    for c in [1, 2, 3, SLICE_WIDTH + 4]:
        e.execute("i", f'SetBit(rowID=1, frame="f", columnID={c})')
    for c in [2, SLICE_WIDTH + 4]:
        e.execute("i", f'SetBit(rowID=2, frame="f", columnID={c})')
    ej = Executor(h, engine="jax")
    q = 'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))'
    assert e.execute("i", q) == ej.execute("i", q)
    (bm_np,) = e.execute("i", 'Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    (bm_j,) = ej.execute("i", 'Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f"))')
    assert bm_np.bits() == bm_j.bits()


def test_mapreduce_node_failure_retry(tmp_path):
    """A remote node erroring mid-query re-maps its slices onto the
    remaining replica owners instead of failing the query
    (executor.go:1147-1159)."""
    from pilosa_tpu.cluster import Cluster, Node

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    # Bits in 4 slices, all stored locally (this host holds every replica's
    # data so the fallback path can answer).
    for s in range(4):
        idx.frame("f").set_bit("standard", 1, s * SLICE_WIDTH + 3)

    hosts = ["h0:1", "h1:1"]
    cluster = Cluster([Node(host) for host in hosts], replica_n=2)

    calls = []

    class FailingClient:
        def __init__(self, host):
            self.host = host

        def execute_remote_call(self, index, call, slices, deadline=None):
            calls.append((self.host, list(slices)))
            raise ConnectionError("node down")

    e = Executor(
        h, engine="numpy", cluster=cluster, client_factory=FailingClient, host="h0:1"
    )
    (n,) = e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
    assert n == 4  # all slices answered locally after h1 failed
    assert any(host == "h1:1" for host, _ in calls)  # remote was tried
    # With NO replicas (replica_n=1) the same failure surfaces an error.
    cluster1 = Cluster([Node(host) for host in hosts], replica_n=1)
    e1 = Executor(
        h, engine="numpy", cluster=cluster1, client_factory=FailingClient, host="h0:1"
    )
    with pytest.raises(Exception):
        e1.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_count_intersect_batch_fusion(tmp_path, engine):
    """A request carrying several Count(Intersect(Bitmap,Bitmap)) calls runs
    through the fused gather path and matches per-call execution."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(5)
    for r in range(6):
        for c in rng.choice(2 * SLICE_WIDTH, size=50, replace=False):
            fr.set_bit("standard", r, int(c))
    e = Executor(h, engine=engine)

    batch_q = "\n".join(
        f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for a, b in [(0, 1), (2, 3), (4, 5), (0, 5)]
    )
    fused = e.execute("i", batch_q)
    singles = [
        e.execute("i", f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))')[0]
        for a, b in [(0, 1), (2, 3), (4, 5), (0, 5)]
    ]
    assert fused == singles

    # Mutation invalidates the device row cache: counts update.
    before = e.execute("i", batch_q)[0]
    col = 123456
    fr.set_bit("standard", 0, col)
    fr.set_bit("standard", 1, col)
    after = e.execute("i", batch_q)[0]
    assert after == before + 1

    # The fused path generalizes across pair ops — a mixed batch of
    # Count(Intersect/Union/Difference/Xor) matches per-call execution.
    mixed = " ".join(
        f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for op, a, b in [
            ("Intersect", 0, 1), ("Union", 0, 1), ("Difference", 0, 1),
            ("Xor", 0, 1), ("Union", 2, 3), ("Difference", 4, 5),
        ]
    )
    fused_mixed = e.execute("i", mixed)
    singles_mixed = [
        e.execute("i", f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))')[0]
        for op, a, b in [
            ("Intersect", 0, 1), ("Union", 0, 1), ("Difference", 0, 1),
            ("Xor", 0, 1), ("Union", 2, 3), ("Difference", 4, 5),
        ]
    ]
    assert fused_mixed == singles_mixed
    h.close()


def test_fusion_respects_preceding_writes(tmp_path):
    """A write earlier in the same request must be visible to later Counts —
    mixed requests take the sequential path, not the fused one."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    fr.set_bit("standard", 0, 1)
    fr.set_bit("standard", 1, 1)
    e = Executor(h, engine="numpy")
    q = (
        'SetBit(rowID=0, frame="f", columnID=5) '
        'SetBit(rowID=1, frame="f", columnID=5) '
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=0, frame="f")))'
    )
    res = e.execute("i", q)
    assert res == [True, True, 2, 2]  # counts observe the writes
    h.close()


def test_set_bit_batch_fusion_matches_sequential(tmp_path):
    """An all-SetBit request runs through the batched write path and
    returns the same per-call changed bools as sequential execution —
    including inverse + time-quantum views and in-request duplicates."""
    def build(d):
        h = Holder(str(tmp_path / d))
        h.open()
        idx = h.create_index("i")
        idx.create_frame("f", FrameOptions(inverse_enabled=True, time_quantum="YMD"))
        return h, Executor(h, engine="numpy")

    calls = [
        'SetBit(rowID=1, frame="f", columnID=100)',
        'SetBit(rowID=1, frame="f", columnID=%d)' % (SLICE_WIDTH + 7),
        'SetBit(rowID=2, frame="f", columnID=100, timestamp="2017-03-02T15:00")',
        'SetBit(rowID=1, frame="f", columnID=100)',  # duplicate -> False
        'SetBit(rowID=3, frame="f", columnID=200)',
    ]
    h1, e1 = build("seq")
    want = [e1.execute("i", q)[0] for q in calls]
    h2, e2 = build("batch")
    got = e2.execute("i", " ".join(calls))
    assert got == want == [True, True, True, False, True]
    # Data identical on both paths, all views.
    for q in (
        'Bitmap(rowID=1, frame="f")',
        'Bitmap(columnID=100, frame="f")',  # inverse view
        'Count(Range(rowID=2, frame="f", start="2017-03-01T00:00", end="2017-04-01T00:00"))',
    ):
        assert _norm(e1.execute("i", q)) == _norm(e2.execute("i", q))
    h1.close()
    h2.close()


def _norm(results):
    return [r.bits() if hasattr(r, "bits") else r for r in results]


def test_set_bit_batch_remote_forwarding(tmp_path):
    """In a 2-node cluster an all-SetBit request sends ONE batched request
    per remote owner instead of one per call, and merges changed bools."""
    from pilosa_tpu.cluster import Cluster, Node

    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    hosts = ["h0:1", "h1:1"]
    cluster = Cluster([Node(host) for host in hosts], replica_n=1)
    requests = []

    class RecordingClient:
        def __init__(self, host):
            self.host = host

        def execute_remote(self, index, query, slices=None, deadline=None):
            requests.append((self.host, len(query.calls)))
            return [True] * len(query.calls)

    e = Executor(
        h, engine="numpy", cluster=cluster, client_factory=RecordingClient, host="h0:1"
    )
    # Spread bits over slices so both nodes own some.
    calls = [
        'SetBit(rowID=1, frame="f", columnID=%d)' % (s * SLICE_WIDTH + 5)
        for s in range(8)
    ]
    got = e.execute("i", " ".join(calls))
    assert got == [True] * len(calls)
    assert requests and all(host == "h1:1" for host, _ in requests)
    assert len(requests) == 1  # one batched forward, not one per call
    n_remote = requests[0][1]
    assert 0 < n_remote < len(calls)  # split ownership
    # Locally-owned slices actually wrote.
    owned = sum(
        1
        for s in range(8)
        if any(n.host == "h0:1" for n in cluster.fragment_nodes("i", s))
    )
    assert owned == len(calls) - n_remote
    h.close()


def test_set_bit_batch_bad_timestamp_partial_commit(env):
    """A malformed timestamp mid-batch follows sequential semantics: calls
    before it commit, the error surfaces."""
    h, e = env
    q = (
        'SetBit(rowID=1, frame="f", columnID=5) '
        'SetBit(rowID=2, frame="f", columnID=6, timestamp="garbage")'
    )
    with pytest.raises(ValueError):
        e.execute("i", q)
    assert e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))') == [1]


def test_fused_matrix_cache_survives_frame_recreate(env):
    """The fused-path row-matrix cache must not serve a deleted frame's
    data after the frame is recreated with a mutation history that lands
    on a look-alike state (generations are process-global, so an object
    swap can never repeat a cached generation tuple)."""
    h, e = env
    idx = h.index("i")
    fr = idx.frame("general")
    for c in range(10):
        fr.set_bit("standard", 0, c)
        fr.set_bit("standard", 1, c)
    q = " ".join(
        ['Count(Intersect(Bitmap(rowID=0, frame="general"), Bitmap(rowID=1, frame="general")))'] * 2
    )
    assert e.execute("i", q) == [10, 10]  # populates the matrix cache
    idx.delete_frame("general")
    idx.create_frame("general", FrameOptions())
    fr2 = idx.frame("general")
    for c in range(10):
        fr2.set_bit("standard", 0, c)
    fr2.set_bit("standard", 1, 0)
    assert e.execute("i", q) == [1, 1]


def test_fused_matrix_cache_sees_writes(env):
    """Mutations between fused requests invalidate the cached matrix."""
    h, e = env
    fr = h.index("i").frame("general")
    for c in range(5):
        fr.set_bit("standard", 0, c)
        fr.set_bit("standard", 1, c)
    q = " ".join(
        ['Count(Intersect(Bitmap(rowID=0, frame="general"), Bitmap(rowID=1, frame="general")))'] * 2
    )
    assert e.execute("i", q) == [5, 5]
    e.execute("i", 'SetBit(rowID=0, frame="general", columnID=100) '
                   'SetBit(rowID=1, frame="general", columnID=100)')
    assert e.execute("i", q) == [6, 6]


@pytest.mark.parametrize("engine", ["numpy", "jax", "mesh"])
def test_fused_matrix_incremental_refresh(tmp_path, engine):
    """The cached matrix is patched per-slice after writes and extended
    per-row for new rowIDs, staying correct across both paths — on both
    the numpy and jax (device scatter/concat) engines."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("general", FrameOptions())
    e = Executor(h, engine=engine)
    fr = h.index("i").frame("general")
    # Two slices, rows 0/1 in both.
    for base in (0, SLICE_WIDTH):
        for c in range(5):
            fr.set_bit("standard", 0, base + c)
            fr.set_bit("standard", 1, base + c)
    q01 = " ".join(
        ['Count(Intersect(Bitmap(rowID=0, frame="general"), Bitmap(rowID=1, frame="general")))'] * 2
    )
    assert e.execute("i", q01) == [10, 10]  # seeds the cache
    # Write to slice 1 only -> patch path (stale plane re-densified).
    fr.set_bit("standard", 0, SLICE_WIDTH + 100)
    fr.set_bit("standard", 1, SLICE_WIDTH + 100)
    assert e.execute("i", q01) == [11, 11]
    # New rows in the same frame -> append path.
    fr.set_bit("standard", 7, 0)
    fr.set_bit("standard", 8, 0)
    q78 = " ".join(
        ['Count(Intersect(Bitmap(rowID=7, frame="general"), Bitmap(rowID=8, frame="general")))'] * 2
    )
    assert e.execute("i", q78) == [1, 1]
    # Patched + appended entry still serves the original rows correctly.
    assert e.execute("i", q01) == [11, 11]
    h.close()


def test_fused_batch_pages_past_pool_capacity(env):
    """A request whose unique row set exceeds the pool capacity is served
    by CHUNKING the batch and paging rows through the device pool (the
    old design fell back to an uncached one-shot matrix; the row ceiling
    is gone)."""
    h, e = env
    fr = h.index("i").frame("general")
    for r in range(8):
        fr.set_bit("standard", r, r)
        fr.set_bit("standard", r, 100)
    q = " ".join(
        f'Count(Intersect(Bitmap(rowID={r}, frame="general"), Bitmap(rowID={(r + 1) % 8}, frame="general")))'
        for r in range(8)
    )
    pool = e._pool_for("i", "general", "standard", [0])
    pool.cap_max = 4  # force the paging regime for this 8-row batch
    assert e.execute("i", q) == [1] * 8
    assert pool.stat_evictions > 0  # rows actually paged out and back
    assert pool.cap <= 4
    # Repeat request stays correct while still paging.
    assert e.execute("i", q) == [1] * 8
    # A small request afterwards is served resident (no new evictions
    # once its rows are in).
    small = (
        'Count(Intersect(Bitmap(rowID=0, frame="general"), Bitmap(rowID=1, frame="general"))) '
        'Count(Intersect(Bitmap(rowID=2, frame="general"), Bitmap(rowID=3, frame="general")))'
    )
    assert e.execute("i", small) == [1, 1]
    ev = pool.stat_evictions
    assert e.execute("i", small) == [1, 1]
    assert pool.stat_evictions == ev


def test_fused_batch_distributed_one_request_per_node(tmp_path):
    """In a cluster, a fused batch forwards ONE Query per remote node
    (not one request per call), sums per-call counts across nodes, and
    fails over to replicas when the remote dies."""
    from pilosa_tpu.cluster import Cluster, Node

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    # All data locally resident (this host holds every replica's data).
    for s in range(4):
        for c in range(10):
            fr.set_bit("standard", 0, s * SLICE_WIDTH + c)
            fr.set_bit("standard", 1, s * SLICE_WIDTH + c + 5)

    hosts = ["h0:1", "h1:1"]
    cluster = Cluster([Node(host) for host in hosts], replica_n=2)
    remote_batches = []

    class SpyClient:
        def __init__(self, host):
            self.host = host

        def execute_remote(self, index, query, slices=None, deadline=None):
            remote_batches.append((self.host, len(query.calls), list(slices)))
            # Answer from the same holder (stand-in for the peer's data).
            peer = Executor(h, engine="numpy")
            return peer.execute(
                index, query, slices=slices, opt=ExecOptions(remote=True)
            )

    e = Executor(h, engine="numpy", cluster=cluster, client_factory=SpyClient, host="h0:1")
    q = " ".join(
        ['Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'] * 3
    )
    got = e.execute("i", q)
    assert got == [20, 20, 20]  # 5 per slice x 4 slices... verified below
    single = Executor(h, engine="numpy").execute(
        "i", 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    )
    assert got == single * 3
    # Exactly one remote batch request carrying all 3 calls.
    assert len(remote_batches) == 1
    host_seen, n_calls, slices_seen = remote_batches[0]
    assert host_seen == "h1:1" and n_calls == 3 and slices_seen

    # Failover: a dying remote re-maps its slices locally; counts intact.
    class DyingClient(SpyClient):
        def execute_remote(self, index, query, slices=None, deadline=None):
            raise ConnectionError("node down")

    e2 = Executor(h, engine="numpy", cluster=cluster, client_factory=DyingClient, host="h0:1")
    assert e2.execute("i", q) == got
    h.close()


def test_fused_gram_upgrade_and_invalidation(tmp_path):
    """Repeated fused requests against an unchanged matrix upgrade to the
    cached Gram (host lookups); any write invalidates it with the entry."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    for r in range(4):
        for c in range(10 + r):
            fr.set_bit("standard", r, c)
    e = Executor(h, engine="jax")
    q = (
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
        'Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))'
    )
    first = e.execute("i", q)
    boxes = [pool.box for pool in e._matrix_cache.values()]
    assert boxes and all("gram" not in b for b in boxes)  # cold: direct kernels
    second = e.execute("i", q)
    assert second == first
    boxes = [pool.box for pool in e._matrix_cache.values()]
    assert any("gram" in b for b in boxes)  # upgraded on 2nd hit
    third = e.execute("i", q)  # served from Gram lookups
    assert third == first
    # A write invalidates the entry (and its Gram); counts update.
    fr.set_bit("standard", 0, 500)
    fr.set_bit("standard", 1, 500)
    after = e.execute("i", q)
    assert after[0] == first[0] + 1 and after[1] == first[1]
    h.close()


def test_flat_fast_lane_matches_slow_path(tmp_path):
    """The AST-free compiled-query lane must agree with the parse path on
    results, fall back for out-of-shape requests, and preserve errors."""
    import os

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(2)
    for r in range(5):
        for c in rng.choice(2 * SLICE_WIDTH, size=60, replace=False):
            fr.set_bit("standard", r, int(c))
    e = Executor(h, engine="numpy")
    batch = " ".join(
        f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for op, a, b in [("Intersect", 0, 1), ("Union", 1, 2), ("Difference", 3, 4), ("Xor", 2, 4)]
    )
    fast = e.execute("i", batch)
    os.environ["PILOSA_TPU_NO_FASTLANE"] = "1"
    try:
        slow = e.execute("i", batch)
    finally:
        del os.environ["PILOSA_TPU_NO_FASTLANE"]
    assert fast == slow

    # Out-of-shape requests fall back and still work.
    mixed = 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) Bitmap(rowID=2, frame="f")'
    res = e.execute("i", mixed)
    assert res[0] == slow[0] and res[1].bits()
    # Unknown frame: identical error through the fallback.
    with pytest.raises(PilosaError):
        e.execute("i", 'Count(Intersect(Bitmap(rowID=0, frame="nope"), Bitmap(rowID=1, frame="nope"))) '
                       'Count(Intersect(Bitmap(rowID=0, frame="nope"), Bitmap(rowID=1, frame="nope")))')
    # Parse errors surface identically (fast lane defers to slow path).
    with pytest.raises(Exception):
        e.execute("i", "Count(Intersect(Bitmap(rowID=0")
    h.close()


def test_flat_fast_lane_rejects_conflicting_args(env):
    """Bitmap(columnID=.., rowID=..) must raise through the slow path, not
    be silently answered by the fast lane (arg-conflict parity)."""
    h, e = env
    fr = h.index("i").frame("general")
    for c in range(5):
        fr.set_bit("standard", 0, c)
        fr.set_bit("standard", 1, c)
    bad = (
        'Count(Intersect(Bitmap(columnID=2, rowID=0), Bitmap(rowID=1))) '
        'Count(Intersect(Bitmap(rowID=0), Bitmap(rowID=1)))'
    )
    with pytest.raises(PilosaError):
        e.execute("i", bad)


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_inverse_view_fused_batch(tmp_path, engine):
    """A batch of Count(op(Bitmap(columnID=..), ...)) calls (inverse view)
    fuses like the standard view and matches per-call execution; a batch
    mixing views falls back and stays correct."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions(inverse_enabled=True))
    e = Executor(h, engine=engine)
    rng = np.random.default_rng(6)
    for r in range(4):
        for c in rng.choice(300, size=40, replace=False):
            e.execute("i", f'SetBit(rowID={r}, frame="f", columnID={int(c)})')
    inv_batch = " ".join(
        f'Count({op}(Bitmap(columnID={a}, frame="f"), Bitmap(columnID={b}, frame="f")))'
        for op, a, b in [("Intersect", 5, 6), ("Union", 7, 8), ("Xor", 5, 8)]
    )
    fused = e.execute("i", inv_batch)
    singles = [
        e.execute("i", f'Count({op}(Bitmap(columnID={a}, frame="f"), Bitmap(columnID={b}, frame="f")))')[0]
        for op, a, b in [("Intersect", 5, 6), ("Union", 7, 8), ("Xor", 5, 8)]
    ]
    assert fused == singles
    # Mixed views in one request: sequential path, still correct.
    mixed = (
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
        'Count(Intersect(Bitmap(columnID=5, frame="f"), Bitmap(columnID=6, frame="f")))'
    )
    got = e.execute("i", mixed)
    want = [
        e.execute("i", 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))')[0],
        e.execute("i", 'Count(Intersect(Bitmap(columnID=5, frame="f"), Bitmap(columnID=6, frame="f")))')[0],
    ]
    assert got == want
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_count_range_batch_fusion(tmp_path, engine):
    """An all-Count(Range(...)) request runs through the fused multi-view
    OR kernel and matches per-call execution, across frames and covers."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions(time_quantum="YMDH"))
    idx.create_frame("g", FrameOptions(time_quantum="YM"))
    idx.create_frame("plain", FrameOptions())  # no quantum: Range counts 0
    e = Executor(h, engine=engine)
    rng = np.random.default_rng(9)
    stamps = [
        "2017-01-05T10:00", "2017-02-14T00:00", "2017-03-02T15:00",
        "2017-06-30T23:00", "2017-12-31T12:00",
    ]
    for fr_name in ("f", "g"):
        for r in (1, 2):
            for t in stamps:
                for c in rng.choice(2 * SLICE_WIDTH, size=5, replace=False):
                    e.execute(
                        "i",
                        f'SetBit(rowID={r}, frame="{fr_name}", columnID={int(c)}, timestamp="{t}")',
                    )
    ranges = [
        ("f", 1, "2017-01-01T00:00", "2018-01-01T00:00"),
        ("f", 2, "2017-03-01T00:00", "2017-04-01T00:00"),
        ("f", 1, "2017-02-01T00:00", "2017-07-01T00:00"),
        ("g", 1, "2017-01-01T00:00", "2017-07-01T00:00"),
        ("g", 2, "2017-06-01T00:00", "2017-06-02T00:00"),
        ("plain", 1, "2017-01-01T00:00", "2018-01-01T00:00"),
        ("f", 1, "2017-05-01T00:00", "2017-05-01T00:00"),  # empty cover
    ]
    calls = [
        f'Count(Range(rowID={r}, frame="{fr}", start="{s}", end="{en}"))'
        for fr, r, s, en in ranges
    ]
    fused = e.execute("i", " ".join(calls))
    singles = [e.execute("i", q)[0] for q in calls]  # len<2: no fusion
    assert fused == singles
    assert fused[0] > 0 and fused[5] == 0 and fused[6] == 0

    # Writes invalidate the cached multi-view matrix (generation check).
    before = e.execute("i", " ".join(calls))
    e.execute(
        "i",
        'SetBit(rowID=1, frame="f", columnID=999999, timestamp="2017-03-15T00:00")',
    )
    after = e.execute("i", " ".join(calls))
    assert after[0] == before[0] + 1  # year cover sees the new bit
    assert after[2] == before[2] + 1  # Feb-Jul cover too
    assert after[1] == before[1]      # row 2 unchanged
    h.close()


def test_fused_range_batch_distributed(tmp_path):
    """Fused Count(Range) batches forward ONE Query per remote node and
    sum per-call counts across the slice split, with replica failover."""
    from pilosa_tpu.cluster import Cluster, Node

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions(time_quantum="YMD"))
    e0 = Executor(h, engine="numpy")
    for s in range(4):
        for c in range(8):
            e0.execute(
                "i",
                f'SetBit(rowID=1, frame="f", columnID={s * SLICE_WIDTH + c}, '
                'timestamp="2017-03-02T00:00")',
            )

    hosts = ["h0:1", "h1:1"]
    cluster = Cluster([Node(host) for host in hosts], replica_n=2)
    remote_batches = []

    class SpyClient:
        def __init__(self, host):
            self.host = host

        def execute_remote(self, index, query, slices=None, deadline=None):
            remote_batches.append((self.host, len(query.calls), list(slices)))
            peer = Executor(h, engine="numpy")
            return peer.execute(index, query, slices=slices, opt=ExecOptions(remote=True))

    e = Executor(h, engine="numpy", cluster=cluster, client_factory=SpyClient, host="h0:1")
    q = " ".join(
        ['Count(Range(rowID=1, frame="f", start="2017-03-01T00:00", end="2017-04-01T00:00"))'] * 3
    )
    got = e.execute("i", q)
    single = e0.execute(
        "i", 'Count(Range(rowID=1, frame="f", start="2017-03-01T00:00", end="2017-04-01T00:00"))'
    )
    assert got == single * 3 == [32, 32, 32]
    assert len(remote_batches) == 1 and remote_batches[0][1] == 3

    class DyingClient(SpyClient):
        def execute_remote(self, index, query, slices=None, deadline=None):
            raise ConnectionError("node down")

    e2 = Executor(h, engine="numpy", cluster=cluster, client_factory=DyingClient, host="h0:1")
    assert e2.execute("i", q) == got
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_fused_range_matrix_grow_alignment(tmp_path, engine):
    """Growing the cached multi-view matrix past its capacity must keep
    id_pos aligned with physical rows (regression: append after spare
    zero rows shifted every new cover onto the wrong plane and poisoned
    the memo)."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions(time_quantum="YMD"))
    e = Executor(h, engine=engine)
    # One Y-covering span per row: each (row, span) is exactly one
    # (view, row) combo, so combo counts are easy to control.
    span = ('start="2017-01-01T00:00", end="2018-01-01T00:00"')
    for r in range(8):
        e.execute(
            "i",
            f'SetBit(rowID={r}, frame="f", columnID={100 + r}, '
            'timestamp="2017-06-15T00:00")',
        )
        e.execute(
            "i",
            f'SetBit(rowID={r}, frame="f", columnID={200 + r}, '
            'timestamp="2017-06-16T00:00")',
        )

    def counts(rows_):
        q = " ".join(
            f'Count(Range(rowID={r}, frame="f", {span}))' for r in rows_
        )
        return e.execute("i", q)

    # 3 combos -> capacity pow2(3)=4; then +2 new combos forces a grow
    # (one into spare capacity, one appended).
    assert counts([0, 1, 2]) == [2, 2, 2]
    assert counts([0, 1, 2, 3, 4]) == [2, 2, 2, 2, 2]
    # Re-query only the grown rows: the memo must hold correct values.
    assert counts([3, 4, 5, 6, 7]) == [2] * 5
    h.close()


def test_topn_src_scoring_engine_parity(tmp_path):
    """TopN(src) candidate scoring through the engine-backed device
    scorer must match the numpy host path exactly (threshold pruning,
    tanimoto band, two-phase refetch included)."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("r")
    rng = np.random.default_rng(21)
    rows, cols = [], []
    for r in range(40):
        n_bits = int(rng.integers(5, 200))
        rows.extend([r] * n_bits)
        cols.extend(rng.choice(2 * SLICE_WIDTH, size=n_bits, replace=False).tolist())
    fr.import_bits(rows, cols)
    e_np = Executor(h, engine="numpy")
    for c in range(0, 600, 3):
        e_np.execute("i", f'SetBit(rowID=9, frame="f", columnID={c})')
    e_jx = Executor(h, engine="jax")
    for q in (
        'TopN(Bitmap(rowID=9, frame="f"), frame="r", n=5)',
        'TopN(Bitmap(rowID=9, frame="f"), frame="r", n=25)',
        'TopN(Bitmap(rowID=9, frame="f"), frame="r")',
        'TopN(Bitmap(rowID=9, frame="f"), frame="r", n=3, tanimotoThreshold=10)',
        'TopN(Bitmap(rowID=9, frame="f"), frame="r", ids=[1,5,11,33])',
    ):
        got_np = [(p.id, p.count) for p in e_np.execute("i", q)[0]]
        got_jx = [(p.id, p.count) for p in e_jx.execute("i", q)[0]]
        assert got_np == got_jx, q
    h.close()


def test_topn_scorer_budget_crossover_parity(tmp_path):
    """When the candidate set crosses the matrix row budget mid-query,
    the scorer hands remaining chunks back to the fragment's host path;
    results must still match the numpy engine exactly."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("r")
    rng = np.random.default_rng(33)
    rows, cols = [], []
    # >256 candidates so chunk 1 (256 ids) scores on-device under a 280
    # budget and chunk 2 crosses it, handing back to the host path.
    for r in range(300):
        n_bits = int(rng.integers(5, 40))
        rows.extend([r] * n_bits)
        cols.extend(rng.choice(SLICE_WIDTH, size=n_bits, replace=False).tolist())
    fr.import_bits(rows, cols)
    e_np = Executor(h, engine="numpy")
    for c in range(0, 800, 2):
        e_np.execute("i", f'SetBit(rowID=7, frame="f", columnID={c})')
    e_jx = Executor(h, engine="jax")
    e_jx._matrix_rows_max = 280  # crossover between chunk 1 and chunk 2
    q = 'TopN(Bitmap(rowID=7, frame="f"), frame="r", n=8)'
    got_np = [(p.id, p.count) for p in e_np.execute("i", q)[0]]
    got_jx = [(p.id, p.count) for p in e_jx.execute("i", q)[0]]
    assert got_np == got_jx
    # Also cover the decline-from-the-first-chunk shape.
    e_jx2 = Executor(h, engine="jax")
    e_jx2._matrix_rows_max = 16
    got_jx2 = [(p.id, p.count) for p in e_jx2.execute("i", q)[0]]
    assert got_np == got_jx2
    h.close()


def test_topn_does_not_evict_count_lane_pool(tmp_path):
    """TopN candidate streaming pages through its OWN pool lane, leaving
    the Count lane's pool residency and Gram untouched (regression:
    alternating TopN/Count traffic must not ping-pong either lane)."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("r", FrameOptions(cache_type="ranked"))
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("r")
    rng = np.random.default_rng(44)
    rows, cols = [], []
    for r in range(30):
        n_bits = int(rng.integers(10, 60))
        rows.extend([r] * n_bits)
        cols.extend(rng.choice(SLICE_WIDTH, size=n_bits, replace=False).tolist())
    fr.import_bits(rows, cols)
    e = Executor(h, engine="jax")
    for c in range(0, 500, 2):
        e.execute("i", f'SetBit(rowID=5, frame="f", columnID={c})')
    # Count lane populates its pool with 20 rows (and a Gram on repeat).
    pair_q = " ".join(
        f'Count(Intersect(Bitmap(rowID={i}, frame="r"), Bitmap(rowID={i+1}, frame="r")))'
        for i in range(0, 20, 2)
    )
    want_counts = e.execute("i", pair_q)
    assert e.execute("i", pair_q) == want_counts  # builds the Gram
    count_pool = e._pool_for("i", "r", "standard", [0])
    box0 = count_pool.box
    n0 = len(count_pool.slot_of)
    assert n0 >= 10
    # TopN over 30 candidates pages through the "topn" lane only.
    topn_q = 'TopN(Bitmap(rowID=5, frame="f"), frame="r", n=5)'
    got_np = [(p.id, p.count) for p in Executor(h, engine="numpy").execute("i", topn_q)[0]]
    got = [(p.id, p.count) for p in e.execute("i", topn_q)[0]]
    assert got == got_np
    assert e._pool_for("i", "r", "standard", [0], lane="topn") is not count_pool
    assert count_pool.box is box0  # count lane box (and Gram) untouched
    assert len(count_pool.slot_of) == n0  # residency preserved
    assert e.execute("i", pair_q) == want_counts  # still served correctly
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_count_multi_operand_batch_fusion(tmp_path, engine):
    """Requests of Count over 3+-operand Intersect/Union/Difference trees
    fuse into multi-fold kernel dispatches and match per-call results,
    including mixed-arity batches (pairs share the same matrix/Gram)."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(6)
    for r in range(8):
        for c in rng.choice(2 * SLICE_WIDTH, size=60, replace=False):
            fr.set_bit("standard", r, int(c))
    e = Executor(h, engine=engine)

    trees = [
        "Intersect(Bitmap(rowID=0), Bitmap(rowID=1), Bitmap(rowID=2))",
        "Union(Bitmap(rowID=1), Bitmap(rowID=2), Bitmap(rowID=3), Bitmap(rowID=4))",
        "Difference(Bitmap(rowID=0), Bitmap(rowID=5), Bitmap(rowID=6))",
        "Intersect(Bitmap(rowID=3), Bitmap(rowID=4))",  # pair lane
        "Difference(Bitmap(rowID=7), Bitmap(rowID=0), Bitmap(rowID=1), Bitmap(rowID=2), Bitmap(rowID=3))",
    ]
    calls = [f"Count({t})".replace("Bitmap(", 'Bitmap(frame="f", ') for t in trees]
    fused = e.execute("i", " ".join(calls))
    singles = [e.execute("i", q)[0] for q in calls]
    assert fused == singles
    assert any(v > 0 for v in fused)

    # Mutation invalidates the shared matrix; counts update.
    before = e.execute("i", " ".join(calls))
    fr.set_bit("standard", 0, 999_999)
    fr.set_bit("standard", 1, 999_999)
    fr.set_bit("standard", 2, 999_999)
    after = e.execute("i", " ".join(calls))
    assert after[0] == before[0] + 1  # 3-way intersect gained the bit
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_fused_batch_slice_streaming(tmp_path, monkeypatch, engine):
    """When the working set exceeds the HBM pool budget, fused count
    batches stream the SLICE axis: transient per-chunk matrices,
    accumulated counts — identical results to sequential execution.
    Tiny budgets force the regime on a small index."""
    monkeypatch.setenv("PILOSA_TPU_POOL_BYTES", str(1 << 20))
    monkeypatch.setenv("PILOSA_TPU_STREAM_BYTES", str(1 << 20))
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(9)
    n_slices, n_rows = 4, 8
    rows = rng.integers(0, n_rows, size=3000).astype(np.uint64)
    cols = rng.integers(0, n_slices * SLICE_WIDTH, size=3000).astype(np.uint64)
    fr.import_bits(rows, cols)
    e = Executor(h, engine=engine)
    pool = e._pool_for("i", "f", "standard", list(range(n_slices)))
    assert pool.cap_max < n_rows  # proves the streaming regime is forced
    pairs = rng.integers(0, n_rows, size=(24, 2))
    q = " ".join(
        f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for a, b in pairs
    ) + (
        # Mixed arity in the same batch: a 3-operand union streams too.
        ' Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"),'
        ' Bitmap(rowID=2, frame="f")))'
    )
    got = e.execute("i", q)
    # Ground truth: one call at a time (no fusion possible).
    e_seq = Executor(h, engine="numpy")
    want = [
        e_seq.execute(
            "i",
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))',
        )[0]
        for a, b in pairs
    ] + [
        e_seq.execute(
            "i",
            'Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"),'
            ' Bitmap(rowID=2, frame="f")))',
        )[0]
    ]
    assert got == want
    h.close()


def test_map_reduce_slice_chunking(tmp_path, monkeypatch):
    """Non-fused calls fold local slice chunks through reduce_fn — a
    Count/Bitmap/TopN over many slices never materializes them all at
    once, and results match the unchunked evaluation."""
    monkeypatch.setenv("PILOSA_TPU_SLICE_CHUNK", "3")
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions(cache_type="ranked"))
    fr = idx.frame("f")
    rng = np.random.default_rng(10)
    n_slices = 10
    rows = rng.integers(0, 5, size=2000).astype(np.uint64)
    cols = rng.integers(0, n_slices * SLICE_WIDTH, size=2000).astype(np.uint64)
    fr.import_bits(rows, cols)
    e = Executor(h, engine="numpy")
    got_count = e.execute(
        "i", 'Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    )
    got_bits = e.execute("i", 'Bitmap(rowID=2, frame="f")')[0].bits()
    got_top = [(p.id, p.count) for p in e.execute("i", 'TopN(frame="f", n=3)')[0]]
    monkeypatch.setenv("PILOSA_TPU_SLICE_CHUNK", "2048")
    e2 = Executor(h, engine="numpy")
    assert got_count == e2.execute(
        "i", 'Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    )
    assert got_bits == e2.execute("i", 'Bitmap(rowID=2, frame="f")')[0].bits()
    assert got_top == [(p.id, p.count) for p in e2.execute("i", 'TopN(frame="f", n=3)')[0]]
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_single_wide_count_streams_instead_of_raising(tmp_path, monkeypatch, engine):
    """One Count(Union(...)) whose operand rows exceed the pool row cap
    must stream the slice axis, not fail the request."""
    monkeypatch.setenv("PILOSA_TPU_POOL_BYTES", str(1 << 20))
    monkeypatch.setenv("PILOSA_TPU_STREAM_BYTES", str(1 << 21))
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    n_rows = 10
    for r in range(n_rows):
        fr.set_bit("standard", r, r)
        fr.set_bit("standard", r, SLICE_WIDTH + 2 * r)
    e = Executor(h, engine=engine)
    pool = e._pool_for("i", "f", "standard", [0, 1])
    assert pool.cap_max < n_rows
    operands = ", ".join(f'Bitmap(rowID={r}, frame="f")' for r in range(n_rows))
    # Two fusable calls so the fused lane (not the sequential path) runs.
    q = f"Count(Union({operands})) Count(Union({operands}))"
    assert e.execute("i", q) == [2 * n_rows, 2 * n_rows]
    h.close()


def test_write_queue_group_commit(tmp_path):
    """Concurrent singleton SetBit requests group-commit through the
    ingest queue: results match the sequential path, acks are durable
    (bits persisted), and batching actually happened under contention."""
    from concurrent.futures import ThreadPoolExecutor

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    e = Executor(h, engine="numpy", write_queue=True)
    n = 600
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 20, size=n).tolist()
    cols = rng.integers(0, 3 * SLICE_WIDTH, size=n).tolist()
    queries = [
        f'SetBit(rowID={r}, frame="f", columnID={c})' for r, c in zip(rows, cols)
    ]
    with ThreadPoolExecutor(8) as pool:
        results = list(pool.map(lambda q: e.execute("i", q), queries))
    # Every submission acked with a bool; uniqueness: exactly the distinct
    # (row, col) pairs were "changed" True.
    changed = sum(1 for r in results if r[0])
    assert changed == len({(r, c) for r, c in zip(rows, cols)})
    # Duplicate write now reports unchanged (read-your-writes).
    assert e.execute("i", queries[0]) == [False]
    # Count agrees with an independent sequential executor.
    got = e.execute("i", 'Count(Union(%s))' % ", ".join(
        f'Bitmap(rowID={r}, frame="f")' for r in range(20)))
    want = Executor(h, engine="numpy").execute("i", 'Count(Union(%s))' % ", ".join(
        f'Bitmap(rowID={r}, frame="f")' for r in range(20)))
    assert got == want
    h.close()


def test_write_queue_invalid_call_does_not_poison_batch(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    e = Executor(h, engine="numpy", write_queue=True)
    with pytest.raises(PilosaError):
        e.execute("i", 'SetBit(rowID=1, frame="nope", columnID=1)')
    assert e.execute("i", 'SetBit(rowID=1, frame="f", columnID=1)') == [True]
    h.close()


def test_read_coalescing_queue_matches_sequential(tmp_path):
    """Concurrent flat-lane count requests coalesce through the serve
    queue into one vectorized evaluation; results match per-request
    sequential execution exactly."""
    from concurrent.futures import ThreadPoolExecutor

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(6)
    for r in range(12):
        for c in rng.integers(0, 2 * SLICE_WIDTH, size=40).tolist():
            fr.set_bit("standard", r, c)
    e = Executor(h, engine="numpy", write_queue=True)
    e_seq = Executor(h, engine="numpy")
    queries = []
    for _ in range(40):
        pairs = rng.integers(0, 12, size=(8, 2))
        queries.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in pairs))
    with ThreadPoolExecutor(8) as pool:
        got = list(pool.map(lambda q: e.execute("i", q), queries))
    want = [e_seq.execute("i", q) for q in queries]
    assert got == want
    assert e._serve_queue.stat_items == 40
    # Reads after writes stay correct through the queue (gens refresh).
    fr.set_bit("standard", 0, 5)
    fr.set_bit("standard", 1, 5)
    q = ('Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
         'Count(Intersect(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))')
    assert e.execute("i", q) == e_seq.execute("i", q)
    h.close()


def test_rowmajor_pool_lane(tmp_path, monkeypatch):
    """Tall working sets page through the ROW-MAJOR pool lane (one
    contiguous DMA descriptor per operand row on TPU); forced on here so
    the CPU suite exercises the row-major fetch/scatter/paging plumbing
    and its parity with the numpy engine.  Covers miss paging, the
    write-invalidation (stale plane) refresh, and mixed pair/3-operand
    groups."""
    import pilosa_tpu.engine as engine_mod

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(9)
    n_rows = 160
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 12)
    for s in range(2):
        cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(
            np.uint64
        ) + np.uint64(s * SLICE_WIDTH)
        fr.import_bits(rows, cols)

    monkeypatch.setattr(
        engine_mod.JaxEngine, "supports_row_major_gather", property(lambda self: True)
    )
    # The Gram outranks the rm lane when eligible (it would serve this
    # 160-row set); disable it so the test drives the rm plumbing.
    monkeypatch.setenv("PILOSA_TPU_NO_GRAM", "1")
    e = Executor(h, engine="jax")
    if e.engine.name == "numpy":
        pytest.skip("jax engine unavailable")
    e_np = Executor(h, engine="numpy")

    # All-distinct pair operands: want == 2 * n_pairs, exactly the
    # boundary where the resident-kernel predicate hands over to the
    # gather kernels (and so the row-major lane).
    perm = rng.permutation(n_rows)
    prs = [[int(perm[2 * i]), int(perm[2 * i + 1])] for i in range(64)]
    tris = rng.integers(0, n_rows, size=(8, 3)).tolist()
    q = " ".join(
        f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for a, b in prs
    ) + " " + " ".join(
        f'Count(Union(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f"), '
        f'Bitmap(rowID={c}, frame="f")))'
        for a, b, c in tris
    )
    assert e.execute("i", q) == e_np.execute("i", q)
    pool = e._pool_for("i", "f", "standard", [0, 1], lane="rmgather")
    assert pool.row_major and pool.matrix is not None
    assert pool.matrix.shape[0] >= len({x for p in prs for x in p})
    # Write invalidation: the stale-plane refresh path in row-major layout.
    fr.set_bit("standard", int(prs[0][0]), 5)
    assert e.execute("i", q) == e_np.execute("i", q)
    # Eviction paging in the row-major pool.  The batch chunker consults
    # the default lane's capacity, so shrink both pools together (in
    # production they share the same budget formula).
    pool.cap_max = 64
    e._pool_for("i", "f", "standard", [0, 1]).cap_max = 64
    pool._reset()
    assert e.execute("i", q) == e_np.execute("i", q)
    assert pool.stat_evictions > 0 or pool.stat_resets > 0
    h.close()


def test_gram_eligibility_covers_tall_row_sets(env, monkeypatch):
    """The chunked Gram builder (bitwise.pair_gram word-axis subdivision)
    removed the per-slice unpack ceiling: eligibility is now a rows gate
    (PILOSA_TPU_GRAM_ROWS_MAX, default 4096 = a 64 MiB Gram) plus the
    int32 slice bound — the round-3 gather-regime shapes (1024/4096
    distinct rows) are Gram-served product paths."""
    _, e = env
    monkeypatch.delenv("PILOSA_TPU_NO_GRAM", raising=False)
    e._gram_env_cache = None  # env settings are cached once per Executor
    assert e._gram_could_serve(1024, 4)
    assert e._gram_could_serve(4096, 4)       # round-3 regression shape
    assert not e._gram_could_serve(4097, 4)   # bucket 8192 > rows max
    assert e._gram_could_serve(64, 2047)
    assert not e._gram_could_serve(64, 2048)  # int32 count bound
    monkeypatch.setenv("PILOSA_TPU_GRAM_ROWS_MAX", "8192")
    e._gram_env_cache = None
    assert e._gram_could_serve(8192, 4)
    monkeypatch.setenv("PILOSA_TPU_NO_GRAM", "1")
    e._gram_env_cache = None
    assert not e._gram_could_serve(64, 4)


def test_count_exact_past_int32_full_density(tmp_path, monkeypatch):
    """A >=2.2B-column full-density Count must return the EXACT value:
    device kernels accumulate in int32, so the executor must never span
    more than _INT32_SAFE_SLICES in one dispatch (the pooled branch
    falls back to slice streaming, chunks clamp to the bound, and the
    partials sum in int64 host-side).  BASELINE.md round-3 addendum 3
    measured the raw overflow; this pins the engine-level guard."""
    from pilosa_tpu.executor import _INT32_SAFE_SLICES, _WORDS

    n_slices = 2112  # > _INT32_SAFE_SLICES; full density = 2.2e9 > int32
    monkeypatch.setenv("PILOSA_TPU_STREAM_BYTES", str(32 * 1024 * 1024))
    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    fr = h.index("i").frame("f")
    # One real bit per slice per row establishes max_slice and fragments;
    # density is injected below (4.3B real bit writes would dwarf CI).
    for row in (0, 1):
        fr.import_bits(
            np.full(n_slices, row, dtype=np.uint64),
            (np.arange(n_slices, dtype=np.uint64) * np.uint64(SLICE_WIDTH)),
        )
    e = Executor(h, engine="jax")
    if not getattr(e.engine, "wants_static_shapes", False):
        pytest.skip("jax engine unavailable")

    def dense_block(index, frame, view, chunk_slices, rows, row_major=False):
        shape = (
            (len(rows), len(chunk_slices), _WORDS)
            if row_major
            else (len(chunk_slices), len(rows), _WORDS)
        )
        return np.full(shape, 0xFFFFFFFF, dtype=np.uint32)

    monkeypatch.setattr(
        Executor,
        "_densify_block",
        lambda self, index, frame, view, chunk_slices, rows, row_major=False:
            dense_block(index, frame, view, chunk_slices, rows, row_major),
    )
    want = n_slices * SLICE_WIDTH  # 2,214,592,512 > 2^31-1
    q = (
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
        'Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    )
    got = e.execute("i", q)
    assert got == [want, want]
    # The chunk clamp itself: a huge byte budget must still cap at the
    # int32-safe slice span.
    monkeypatch.setenv("PILOSA_TPU_STREAM_BYTES", str(1 << 62))
    assert e._slice_chunk(2) == _INT32_SAFE_SLICES
    h.close()


def test_singleton_write_fast_lane_parity(tmp_path, monkeypatch):
    """The singleton SetBit/ClearBit fast lane must be observably
    identical to the general path: changed semantics, label validation
    (declining non-matching arg names), inverse-frame decline, and
    interleaving with reads."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    idx.create_frame("inv", FrameOptions(inverse_enabled=True))
    e = Executor(h, engine="numpy")

    # fast lane serves the canonical shape
    assert e.execute("i", 'SetBit(rowID=3, frame="f", columnID=9)') == [True]
    assert e.execute("i", 'SetBit(rowID=3, frame="f", columnID=9)') == [False]
    assert e.execute("i", 'Count(Bitmap(rowID=3, frame="f"))') == [1]
    assert e.execute("i", 'ClearBit(rowID=3, frame="f", columnID=9)') == [True]
    assert e.execute("i", 'ClearBit(rowID=3, frame="f", columnID=9)') == [False]
    # wrong arg label: declines to the general path, which raises the
    # same error as before the lane existed
    with pytest.raises(PilosaError):
        e.execute("i", 'SetBit(wrongID=3, frame="f", columnID=9)')
    # inverse frames decline (dual-view write handled by the general path)
    assert e.execute("i", 'SetBit(rowID=1, frame="inv", columnID=5)') == [True]
    assert e.execute("i", 'Count(Bitmap(rowID=5, frame="inv", inverse=true))')[0] >= 0
    inv_fr = h.frame("i", "inv")
    assert inv_fr.views.get("inverse") is not None, "inverse view must be written"
    # frame recreation invalidates the identity cache
    idx.delete_frame("f")
    idx.create_frame("f", FrameOptions())
    assert e.execute("i", 'SetBit(rowID=3, frame="f", columnID=9)') == [True]
    assert e.execute("i", 'Count(Bitmap(rowID=3, frame="f"))') == [1]
    h.close()


def test_effective_max_opn_scaling(tmp_path, monkeypatch):
    """Snapshot-trigger scaling: DEFAULT-tuned fragments scale the
    threshold with container count (bounded); explicit max_opn and the
    env kill switch keep exact reference behavior."""
    from pilosa_tpu.core.fragment import DEFAULT_MAX_OPN, Fragment

    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    assert f._effective_max_opn() >= DEFAULT_MAX_OPN
    # explicit max_opn: honored exactly
    g = Fragment(str(tmp_path / "1"), "i", "f", "standard", 1, max_opn=5)
    g.open()
    assert g._effective_max_opn() == 5
    for i in range(7):
        g.set_bit(0, i)
    assert g.storage.op_n < 5  # snapshot fired at the explicit threshold
    # env kill switch restores the fixed default
    monkeypatch.setenv("PILOSA_TPU_MAX_OPN_SCALE", "0")
    k = Fragment(str(tmp_path / "2"), "i", "f", "standard", 2)
    k.open()
    assert k._effective_max_opn() == DEFAULT_MAX_OPN
    f.close(); g.close(); k.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_fused_tree_lane_matches_sequential(tmp_path, engine):
    """Nested mixed trees and multi-operand Xor fuse into the tree lane
    and agree exactly with the sequential path (executor.go:261-276's
    uniform any-depth evaluation, fused)."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(5)
    fr.import_bits(rng.integers(0, 10, 600), rng.integers(0, 3 * SLICE_WIDTH, 600))
    e = Executor(h, engine=engine)
    qs = [
        'Count(Intersect(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")), Bitmap(rowID=2, frame="f")))',
        'Count(Xor(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
        'Count(Difference(Union(Bitmap(rowID=3, frame="f"), Bitmap(rowID=4, frame="f")), Bitmap(rowID=5, frame="f"), Bitmap(rowID=6, frame="f")))',
        'Count(Union(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")), Intersect(Bitmap(rowID=3, frame="f"), Bitmap(rowID=4, frame="f"))))',
        'Count(Xor(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=7, frame="f")), Bitmap(rowID=8, frame="f"), Bitmap(rowID=9, frame="f"), Bitmap(rowID=1, frame="f")))',
        # flat shapes mixed in: pair + multi lanes coexist with tree groups
        'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
        'Count(Union(Bitmap(rowID=3, frame="f"), Bitmap(rowID=4, frame="f"), Bitmap(rowID=5, frame="f")))',
    ]
    seq = [e.execute("i", q)[0] for q in qs]
    # The batch must actually take the fused lane.
    from pilosa_tpu.pql.parser import parse

    fused = e._fuse_count_pair_batch(
        "i", parse(" ".join(qs)).calls, list(range(3)), None, ExecOptions()
    )
    assert fused is not None and len(fused) == len(qs)
    assert [fused[i] for i in range(len(qs))] == seq
    assert e.execute("i", " ".join(qs)) == seq
    h.close()


def test_fused_tree_lane_depth_cap_falls_back(tmp_path):
    """Trees past _TREE_DEPTH_MAX decline the fused lane but still
    answer correctly through the sequential path."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    fr.import_bits(np.arange(6) % 3, np.arange(6) * 1000)
    e = Executor(h, engine="numpy")
    deep = 'Bitmap(rowID=0, frame="f")'
    for _ in range(6):  # depth 6 > _TREE_DEPTH_MAX
        deep = f'Union({deep}, Bitmap(rowID=1, frame="f"))'
    q = f"Count({deep})"
    assert e._compile_count_tree("i", parse_query(q).calls[0].children[0]) is None
    assert e.execute("i", f"{q} {q}") == [e.execute("i", q)[0]] * 2
    h.close()


def parse_query(src):
    from pilosa_tpu.pql.parser import parse

    return parse(src)


class TestServeLane:
    """The single-call native serve lane (pn_serve_pairs + cached state):
    parity with the general path, and every invalidation edge."""

    def _setup(self, tmp_path, engine="jax"):
        h = Holder(str(tmp_path / "data"))
        h.open()
        h.create_index("p").create_frame("f", FrameOptions())
        fr = h.index("p").frame("f")
        rng = np.random.default_rng(7)
        fr.import_bits(
            rng.integers(0, 32, 3000), rng.integers(0, 3 * SLICE_WIDTH, 3000)
        )
        ex = Executor(h, engine=engine)
        rng2 = np.random.default_rng(1)
        batch = " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in rng2.integers(0, 32, size=(64, 2))
        )
        return h, ex, batch

    def _arm(self, ex, batch):
        ex.execute("p", batch)
        ex.execute("p", batch)  # Gram arms on the second request
        assert ex._serve_states, "serve state did not arm"

    def test_parity_and_all_ops(self, tmp_path):
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        e_np = Executor(h, engine="numpy")
        ops_batch = " ".join(
            f'Count({op}(Bitmap(rowID=3, frame="f"), Bitmap(rowID=9, frame="f")))'
            for op in ("Intersect", "Union", "Xor", "Difference")
        )
        got = ex.execute("p", ops_batch)  # through pn_serve_pairs
        assert got == e_np.execute("p", ops_batch)
        h.close()

    def test_write_invalidates(self, tmp_path):
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        before = ex.execute("p", batch)
        ex.execute("p", 'SetBit(rowID=3, frame="f", columnID=12345678)')
        after = ex.execute("p", batch)
        want = Executor(h, engine="numpy").execute("p", batch)
        assert after == want
        # the state re-arms and still serves correct counts
        again = ex.execute("p", batch)
        assert again == want
        del before  # counts may or may not change; correctness is vs `want`
        h.close()

    def test_new_slice_invalidates(self, tmp_path):
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        # a write in a NEW slice extends max_slice: state must not serve
        # stale slice ranges
        ex.execute("p", f'SetBit(rowID=3, frame="f", columnID={5 * SLICE_WIDTH + 1})')
        got = ex.execute("p", batch)
        assert got == Executor(h, engine="numpy").execute("p", batch)
        h.close()

    def test_unknown_rows_and_other_frames_fall_back(self, tmp_path):
        h, ex, batch = self._setup(tmp_path)
        h.index("p").create_frame("g", FrameOptions())
        h.index("p").frame("g").import_bits(
            np.arange(4, dtype=np.uint64), np.arange(4, dtype=np.uint64) * 100
        )
        self._arm(ex, batch)
        e_np = Executor(h, engine="numpy")
        # rows outside the captured table
        q1 = (
            'Count(Intersect(Bitmap(rowID=500, frame="f"), Bitmap(rowID=501, frame="f"))) '
            'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
        )
        assert ex.execute("p", q1) == e_np.execute("p", q1)
        # a different frame than the armed one
        q2 = (
            'Count(Intersect(Bitmap(rowID=0, frame="g"), Bitmap(rowID=1, frame="g"))) '
            'Count(Union(Bitmap(rowID=2, frame="g"), Bitmap(rowID=3, frame="g")))'
        )
        assert ex.execute("p", q2) == e_np.execute("p", q2)
        h.close()

    def test_threaded_parity(self, tmp_path):
        import threading

        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        want = ex.execute("p", batch)
        errs = []

        def client():
            try:
                for _ in range(20):
                    if ex.execute("p", batch) != want:
                        errs.append("mismatch")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        ts = [threading.Thread(target=client) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs[:3]
        h.close()

    def test_single_bit_write_repairs_warm_state(self, tmp_path):
        """Read-your-writes through the PATCH lane: a single-bit write
        below the repair budget must be served with updated counts by a
        REPAIRED warm state (matrix row rewrite + rank-k Gram update),
        not by dropping the state and rebuilding."""
        from pilosa_tpu.core.view import VIEW_STANDARD

        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        key = ("p", "f")
        st0 = ex._serve_states[key]
        pool = ex._matrix_cache[("p", "f", VIEW_STANDARD, (0, 1, 2), "")]
        # Deterministic delta: clear then set the same bit, counting the
        # row-3 diagonal through the warm lane around each write.
        q = 'Count(Intersect(Bitmap(rowID=3, frame="f"), Bitmap(rowID=3, frame="f")))'
        col = 2 * SLICE_WIDTH + 99
        ex.execute("p", f'ClearBit(rowID=3, frame="f", columnID={col})')
        before = ex.execute("p", q)[0]
        ex.execute("p", f'SetBit(rowID=3, frame="f", columnID={col})')
        after = ex.execute("p", q)[0]
        assert after == before + 1
        # The state was re-captured (patched), never dropped, and the
        # pool took the repair lane — no reset, no blind plane refresh.
        # (The ClearBit is usually a no-op on the random import — no
        # generation bump — so only the SetBit is guaranteed to repair.)
        st1 = ex._serve_states.get(key)
        assert st1 is not None and st1 is not st0
        assert pool.stat_repairs >= 1 and pool.stat_resets == 0
        # Full-batch parity with the sequential numpy path after repair.
        assert ex.execute("p", batch) == Executor(h, engine="numpy").execute("p", batch)
        h.close()

    def test_write_burst_over_budget_falls_back_to_rebuild(
        self, tmp_path, monkeypatch
    ):
        """A burst touching more rows than the repair budget must take
        the full invalidate-and-rebuild path — and still satisfy
        read-your-writes, then re-arm."""
        from pilosa_tpu.core.view import VIEW_STANDARD

        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "4")
        h, ex, batch = self._setup(tmp_path)  # Executor reads the env at init
        self._arm(ex, batch)
        pool = ex._matrix_cache[("p", "f", VIEW_STANDARD, (0, 1, 2), "")]
        burst = " ".join(
            f'SetBit(rowID={r}, frame="f", columnID={SLICE_WIDTH + 777 + r})'
            for r in range(10)  # 10 distinct rows > budget 4
        )
        ex.execute("p", burst)
        want = Executor(h, engine="numpy").execute("p", batch)
        assert ex.execute("p", batch) == want
        assert pool.stat_repairs == 0  # over budget: no patch attempted
        # The lane re-arms and keeps serving correct counts.
        assert ex.execute("p", batch) == want
        assert ex._serve_states, "serve lane did not re-arm after rebuild"
        h.close()

    def test_repair_disabled_env_forces_rebuild(self, tmp_path, monkeypatch):
        """PILOSA_TPU_REPAIR_ROWS_MAX=0 is the A/B lever bench_mixed
        uses: every write must invalidate, none may patch."""
        from pilosa_tpu.core.view import VIEW_STANDARD

        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "0")
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        pool = ex._matrix_cache[("p", "f", VIEW_STANDARD, (0, 1, 2), "")]
        ex.execute("p", 'SetBit(rowID=3, frame="f", columnID=98765)')
        assert ex.execute("p", batch) == Executor(h, engine="numpy").execute("p", batch)
        assert pool.stat_repairs == 0
        h.close()

    def test_frame_recreate_never_serves_stale(self, tmp_path):
        """Deleting and recreating a frame of the same name must drop the
        old warm state (identity/generation tokens) — counts come from
        the NEW frame's bits."""
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        h.index("p").delete_frame("f")
        h.index("p").create_frame("f", FrameOptions())
        fr = h.index("p").frame("f")
        fr.import_bits(np.array([3, 9], dtype=np.uint64), np.array([5, 5], dtype=np.uint64))
        got = ex.execute("p", batch)
        assert got == Executor(h, engine="numpy").execute("p", batch)
        h.close()

    def test_drop_frame_state_hook(self, tmp_path):
        """The deletion hook reclaims every cached artifact for the
        frame: serve states, row pools, fast-write pins, dirty ledger."""
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        ex.execute("p", 'SetBit(rowID=1, frame="f", columnID=424242)')
        assert any(k[:2] == ("p", "f") for k in ex._matrix_cache)
        epoch_before = ex._lane_epoch
        ex.drop_frame_state("p", "f")
        assert ("p", "f") not in ex._serve_states
        assert not any(k[:2] == ("p", "f") for k in ex._matrix_cache)
        # The per-thread armed lane tables invalidate via the epoch: the
        # drop bumps it, and the calling thread's table resets empty at
        # next access.
        assert ex._lane_epoch == epoch_before + 1
        fastwrite, writelane = ex._lane_tables()
        assert ("p", "f") not in fastwrite and ("p", "f") not in writelane
        assert ("p", "f") not in ex._dirty_rows
        # Still serves correctly from scratch afterwards.
        assert ex.execute("p", batch) == Executor(h, engine="numpy").execute("p", batch)
        # Index-level drop clears every frame's artifacts too.
        ex.execute("p", batch)
        ex.drop_index_state("p")
        assert not ex._serve_states
        assert not any(k[0] == "p" for k in ex._matrix_cache)
        h.close()

    def test_serve_state_cache_size_configurable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PILOSA_SERVE_STATE_CACHE", "2")
        h, ex, _ = self._setup(tmp_path)
        assert ex._serve_states_max == 2
        ex2 = Executor(h, serve_state_cache=7)  # explicit arg wins
        assert ex2._serve_states_max == 7
        h.close()

    def test_repair_and_gram_budgets_configurable(self, tmp_path, monkeypatch):
        """config.py plumbing for the repair/Gram budgets: constructor
        arg (server passes Config values) > PILOSA_TPU_* env > default,
        with 0 meaning 'disabled' for repair (so None is the
        not-configured sentinel)."""
        from pilosa_tpu.config import Config

        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "9")
        monkeypatch.setenv("PILOSA_TPU_GRAM_ROWS_MAX", "512")
        h, ex, _ = self._setup(tmp_path)
        assert ex._repair_rows_max == 9
        assert ex._gram_rows_max() == 512
        ex2 = Executor(h, repair_rows_max=0, gram_rows_max=128)  # args win
        assert ex2._repair_rows_max == 0
        assert ex2._gram_rows_max() == 128
        # TOML -> Config -> env precedence mirrors serve-state-cache.
        cfg = Config.from_dict({"repair-rows-max": 5, "gram-rows-max": 2048})
        assert cfg.repair_rows_max == 5 and cfg.gram_rows_max == 2048
        cfg.apply_env({"PILOSA_TPU_REPAIR_ROWS_MAX": "0",
                       "PILOSA_TPU_GRAM_ROWS_MAX": "64"})
        assert cfg.repair_rows_max == 0 and cfg.gram_rows_max == 64
        h.close()

    def test_ledger_skipped_when_repair_disabled(self, tmp_path, monkeypatch):
        """With PILOSA_TPU_REPAIR_ROWS_MAX=0 the dirty-row ledger must
        stay empty even while serve state is warm — its only consumer
        (the repair precheck) can never use it."""
        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "0")
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        ex.execute("p", 'SetBit(rowID=3, frame="f", columnID=424242)')
        assert not ex._dirty_rows
        h.close()

    def test_ledger_saturation_forces_rebuild(self, tmp_path, monkeypatch):
        """A burst past 4x the budget saturates the ledger (value None);
        the repair lane must refuse without walking journals, the state
        rebuilds, and counts stay read-your-writes correct."""
        from pilosa_tpu.core.view import VIEW_STANDARD

        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "2")  # cap = 24
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        pool = ex._matrix_cache[("p", "f", VIEW_STANDARD, (0, 1, 2), "")]
        burst = " ".join(
            f'SetBit(rowID={r}, frame="f", columnID={2 * SLICE_WIDTH + 600 + r})'
            for r in range(30)  # 30 distinct rows > 4*2+16
        )
        ex.execute("p", burst)
        assert ex._dirty_rows[("p", "f")] is None  # saturated
        walks = {"n": 0}
        orig = ex._journal_dirty_rows

        def counting(*a, **kw):
            walks["n"] += 1
            return orig(*a, **kw)

        ex._journal_dirty_rows = counting
        want = Executor(h, engine="numpy").execute("p", batch)
        assert ex.execute("p", batch) == want
        assert pool.stat_repairs == 0
        # The serve-lane repair precheck declined BEFORE the journal
        # walk; the only walks come from the pool acquire path (which
        # rebuilds because the delta is over budget anyway).  The lane
        # re-arms on the second post-write read (Gram warms on hit 2).
        assert ex.execute("p", batch) == want
        assert ex._serve_states, "lane did not re-arm"
        h.close()

    def test_over_budget_precheck_declines_without_journal_walk(
        self, tmp_path, monkeypatch
    ):
        """A ledger clearly over budget (but not saturated) must make
        _serve_state_repair decline before touching the fragment
        journals."""
        monkeypatch.setenv("PILOSA_TPU_REPAIR_ROWS_MAX", "4")
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        st = ex._serve_states[("p", "f")]
        with ex._dirty_mu:
            ex._dirty_rows[("p", "f")] = {1, 2, 3, 4, 5, 6}  # 6 > budget 4

        def boom(*a, **kw):
            raise AssertionError("journal walk after precheck decline")

        ex._journal_dirty_rows = boom
        assert ex._serve_state_repair(("p", "f"), st) is None
        h.close()

    def test_repair_bails_on_replaced_fragment(self, tmp_path):
        """A fragment deleted/recreated since capture fails the identity
        check: the repair lane returns None (rebuild path)."""
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        st = ex._serve_states[("p", "f")]
        h.index("p").delete_frame("f")
        h.index("p").create_frame("f", FrameOptions())
        h.index("p").frame("f").import_bits(
            np.array([1], dtype=np.uint64),
            np.array([2 * SLICE_WIDTH + 5], dtype=np.uint64),
        )
        assert ex._serve_state_repair(("p", "f"), st) is None
        h.close()

    def test_repair_bails_on_slice_growth(self, tmp_path):
        """A write extending max_slice makes the state's span wrong: the
        repair lane must decline (the general lane rebuilds wider)."""
        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        st = ex._serve_states[("p", "f")]
        ex.execute("p", f'SetBit(rowID=3, frame="f", columnID={7 * SLICE_WIDTH + 1})')
        assert ex._serve_state_repair(("p", "f"), st) is None
        # And the general path still serves correct post-growth counts.
        assert ex.execute("p", batch) == Executor(h, engine="numpy").execute("p", batch)
        h.close()

    def test_write_burst_coalesces_into_one_repair(self, tmp_path):
        """Batched write->repair dispatch: a burst of N singleton writes
        with no interleaved reads must be repaired by ONE deferred patch
        dispatch on the next read (not one per write), touching only the
        written slice's planes."""
        from pilosa_tpu.core.view import VIEW_STANDARD

        h, ex, batch = self._setup(tmp_path)
        self._arm(ex, batch)
        pool = ex._matrix_cache[("p", "f", VIEW_STANDARD, (0, 1, 2), "")]
        repairs0 = pool.stat_repairs
        # 8 writes to distinct rows, all landing in slice 1.
        for r in range(8):
            ex.execute(
                "p", f'SetBit(rowID={r}, frame="f", columnID={SLICE_WIDTH + 4000 + r})'
            )
        want = Executor(h, engine="numpy").execute("p", batch)
        assert ex.execute("p", batch) == want
        assert pool.stat_repairs == repairs0 + 1  # one repair for the burst
        # Per-(row, slice) granularity: 8 rows x ONE slice, not x3.
        assert pool.stat_patch_planes == 8
        h.close()


def test_serve_lane_multi_frame_alternation(tmp_path):
    """Two frames' dashboards alternating must BOTH stay armed (the
    serve-state LRU holds one entry per (index, frame)) and keep serving
    natively without thrash."""
    from pilosa_tpu import native

    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("p")
    rng = np.random.default_rng(9)
    for fname in ("f", "g"):
        idx.create_frame(fname, FrameOptions())
        idx.frame(fname).import_bits(
            rng.integers(0, 16, 400), rng.integers(0, 2 * SLICE_WIDTH, 400)
        )
    ex = Executor(h, engine="jax")

    def batch(fname):
        return " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="{fname}"), Bitmap(rowID={b}, frame="{fname}")))'
            for a, b in np.random.default_rng(1).integers(0, 16, size=(16, 2))
        )

    qf, qg = batch("f"), batch("g")
    want_f, want_g = ex.execute("p", qf), ex.execute("p", qg)
    for q, w in ((qf, want_f), (qg, want_g)):  # arm both (Gram on 2nd hit)
        assert ex.execute("p", q) == w
    assert set(ex._serve_states) == {("p", "f"), ("p", "g")}
    calls = {"n": 0}
    orig = native.serve_pairs

    def counting(*a, **kw):
        r = orig(*a, **kw)
        if r is not None:
            calls["n"] += 1
        return r

    native.serve_pairs = counting
    try:
        for _ in range(5):  # alternate: both frames keep serving natively
            assert ex.execute("p", qf) == want_f
            assert ex.execute("p", qg) == want_g
    finally:
        native.serve_pairs = orig
    assert calls["n"] == 10, f"only {calls['n']}/10 alternating requests served natively"
    h.close()


@pytest.mark.parametrize("engine", ["numpy", "jax"])
def test_count_bitmap_singles_fuse_with_pairs(tmp_path, engine):
    """Plain Count(Bitmap(r)) calls ride the pair lane as (r, r): a
    dashboard mixing row counts, pair counts, and nested trees stays ONE
    fused batch instead of falling to sequential per-call evaluation."""
    from pilosa_tpu.pql.parser import parse

    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    fr = h.index("i").frame("f")
    rng = np.random.default_rng(6)
    fr.import_bits(rng.integers(0, 12, 500), rng.integers(0, 3 * SLICE_WIDTH, 500))
    e = Executor(h, engine=engine)
    qs = [
        'Count(Bitmap(rowID=3, frame="f"))',
        'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
        'Count(Bitmap(rowID=7, frame="f"))',
        'Count(Union(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")), Bitmap(rowID=3, frame="f")))',
    ]
    seq = [e.execute("i", q)[0] for q in qs]
    fused = e._fuse_count_pair_batch(
        "i", parse(" ".join(qs)).calls, list(range(3)), None, ExecOptions()
    )
    assert fused is not None and [fused[i] for i in range(4)] == seq
    assert e.execute("i", " ".join(qs)) == seq
    h.close()


class TestServeLaneBreadth:
    """The serve-lane breadth kernels (pn_serve_multi / pn_serve_tree /
    pn_pql_match_range): seeded differential parity with the Python
    lane, lane-selection proof (the native entry actually fires), the
    A/B env levers, and every decline edge falling back byte-identical."""

    def _pair_holder(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("p")
        idx.create_frame("f", FrameOptions())
        idx.create_frame("g", FrameOptions())
        rng = np.random.default_rng(7)
        h.index("p").frame("f").import_bits(
            rng.integers(0, 32, 3000), rng.integers(0, 3 * SLICE_WIDTH, 3000))
        h.index("p").frame("g").import_bits(
            rng.integers(0, 16, 2000), rng.integers(0, 3 * SLICE_WIDTH, 2000))
        parts = []
        for a, b in np.random.default_rng(1).integers(0, 16, size=(32, 2)):
            parts.append(f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))')
            parts.append(f'Count(Union(Bitmap(rowID={a}, frame="g"), Bitmap(rowID={b}, frame="g")))')
        return h, " ".join(parts)

    def _count_native(self, monkeypatch, name):
        """Wrap a pilosa_tpu.native entry to count successful serves."""
        from pilosa_tpu import native

        hits = {"n": 0}
        orig = getattr(native, name)

        def counting(*a, **k):
            r = orig(*a, **k)
            if r is not None:
                hits["n"] += 1
            return r

        monkeypatch.setattr(native, name, counting)
        return hits

    def test_multiframe_parity_and_lever(self, tmp_path, monkeypatch):
        h, multi = self._pair_holder(tmp_path)
        ex = Executor(h, engine="jax")
        e_np = Executor(h, engine="numpy")
        want = e_np.execute("p", multi)
        r1 = ex.execute("p", multi)
        r2 = ex.execute("p", multi)  # Gram warms; per-frame states arm
        assert len(ex._serve_states) == 2, "both frames should arm"
        hits = self._count_native(monkeypatch, "serve_multi")
        r3 = ex.execute("p", multi)
        assert hits["n"] == 1, "pn_serve_multi did not serve the batch"
        assert r1 == r2 == r3 == want
        # the A/B lever routes the identical batch off the native lane
        monkeypatch.setenv("PILOSA_TPU_NO_SERVEMULTI", "1")
        assert ex.execute("p", multi) == want
        h.close()

    def test_multiframe_write_invalidates(self, tmp_path):
        h, multi = self._pair_holder(tmp_path)
        ex = Executor(h, engine="jax")
        ex.execute("p", multi)
        ex.execute("p", multi)
        ex.execute("p", 'SetBit(rowID=3, frame="g", columnID=12345678)')
        assert ex.execute("p", multi) == Executor(h, engine="numpy").execute("p", multi)
        h.close()

    def _tree_holder(self, tmp_path, slices=1):
        h = Holder(str(tmp_path / "data"))
        h.open()
        h.create_index("t").create_frame("f", FrameOptions())
        rng = np.random.default_rng(3)
        h.index("t").frame("f").import_bits(
            rng.integers(0, 12, 4000), rng.integers(0, slices * SLICE_WIDTH, 4000))
        body = (
            'Count(Intersect(Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")), '
            'Difference(Bitmap(rowID=3, frame="f"), Bitmap(rowID=4, frame="f"), Bitmap(rowID=5, frame="f")))) '
            'Count(Xor(Bitmap(rowID=1, frame="f"), Bitmap(rowID=6, frame="f"), Bitmap(rowID=7, frame="f"))) '
            'Count(Bitmap(rowID=2, frame="f"))'
        )
        return h, body

    def test_tree_parity_and_lever(self, tmp_path, monkeypatch):
        h, body = self._tree_holder(tmp_path)
        ex = Executor(h, engine="numpy")
        hits = self._count_native(monkeypatch, "serve_tree")
        got = ex.execute("t", body)
        assert hits["n"] == 1, "pn_serve_tree did not serve the batch"
        monkeypatch.setenv("PILOSA_TPU_NO_SERVETREE", "1")
        assert got == ex.execute("t", body)
        h.close()

    def test_tree_direct_fragment_call(self, tmp_path):
        h, body = self._tree_holder(tmp_path)
        frag = h.fragment("t", "f", "standard", 0)
        counts = frag.serve_tree(body.encode(), b"f", False, b"rowID")
        assert counts is not None
        assert list(counts) == Executor(h, engine="numpy").execute("t", body)
        h.close()

    def test_tree_after_native_write_stays_correct(self, tmp_path):
        """Interleaved writes: the tree lane reads the same armed
        container table the native write lane mutates in place."""
        h, body = self._tree_holder(tmp_path)
        ex = Executor(h, engine="numpy")
        before = ex.execute("t", body)
        ex.execute("t", 'SetBit(rowID=2, frame="f", columnID=777777)')
        after = ex.execute("t", body)
        want = Executor(h, engine="numpy").execute("t", body)
        assert after == want and after[2] == before[2] + 1
        h.close()

    def test_tree_declines_multislice_index(self, tmp_path, monkeypatch):
        """The tree lane is single-slice only: a 2-slice index must fall
        back to the Python path with identical answers."""
        h, body = self._tree_holder(tmp_path, slices=2)
        ex = Executor(h, engine="numpy")
        hits = self._count_native(monkeypatch, "serve_tree")
        got = ex.execute("t", body)
        assert hits["n"] == 0, "tree lane must decline multi-slice indexes"
        monkeypatch.setenv("PILOSA_TPU_NO_SERVETREE", "1")
        assert got == ex.execute("t", body)
        h.close()

    def test_tree_depth_and_unknown_frame_fall_back(self, tmp_path, monkeypatch):
        h, _ = self._tree_holder(tmp_path)
        ex = Executor(h, engine="numpy")
        deep = 'Bitmap(rowID=1, frame="f")'
        for _ in range(8):  # depth past PN_TREE_MAX_DEPTH
            deep = f'Union({deep}, Bitmap(rowID=2, frame="f"))'
        q = f"Count({deep}) Count(Bitmap(rowID=1, frame=\"f\"))"
        got = ex.execute("t", q)
        monkeypatch.setenv("PILOSA_TPU_NO_SERVETREE", "1")
        assert got == ex.execute("t", q)
        monkeypatch.delenv("PILOSA_TPU_NO_SERVETREE")
        from pilosa_tpu.pilosa import ErrFrameNotFound

        bad = 'Count(Bitmap(rowID=1, frame="nope")) Count(Bitmap(rowID=1, frame="f"))'
        with pytest.raises(ErrFrameNotFound, match="nope"):
            ex.execute("t", bad)
        h.close()

    def _range_holder(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("r")
        idx.create_frame("f", FrameOptions(time_quantum="YMDH"))
        idx.create_frame("g", FrameOptions(time_quantum="YM"))
        idx.create_frame("plain", FrameOptions())
        e = Executor(h, engine="jax")
        rng = np.random.default_rng(9)
        stamps = ["2017-01-05T10:00", "2017-02-14T00:00", "2017-03-02T15:00",
                  "2017-06-30T23:00", "2017-12-31T12:00"]
        for fr_name in ("f", "g"):
            for r in (1, 2):
                for t in stamps:
                    for c in rng.choice(2 * SLICE_WIDTH, size=5, replace=False):
                        e.execute("r", f'SetBit(rowID={r}, frame="{fr_name}", columnID={int(c)}, timestamp="{t}")')
        body = " ".join(
            f'Count(Range(rowID={r}, frame="{fr}", start="{s}", end="{en}"))'
            for fr, r, s, en in [
                ("f", 1, "2017-01-01T00:00", "2018-01-01T00:00"),
                ("f", 2, "2017-03-01T00:00", "2017-04-01T00:00"),
                ("f", 1, "2017-02-01T00:00", "2017-07-01T00:00"),
                ("g", 1, "2017-01-01T00:00", "2017-07-01T00:00"),
                ("g", 2, "2017-06-01T00:00", "2017-06-02T00:00"),
                ("plain", 1, "2017-01-01T00:00", "2018-01-01T00:00"),
                ("f", 1, "2017-05-01T00:00", "2017-05-01T00:00"),
            ])
        return h, e, body

    def test_range_parity_and_lever(self, tmp_path, monkeypatch):
        h, ex, body = self._range_holder(tmp_path)
        hits = self._count_native(monkeypatch, "pql_match_range")
        got = ex.execute("r", body)
        assert hits["n"] == 1, "native Range matcher did not fire"
        want = Executor(h, engine="numpy").execute("r", body)
        monkeypatch.setenv("PILOSA_TPU_NO_RANGELANE", "1")
        py = ex.execute("r", body)
        assert got == want == py
        assert got[0] > 0 and got[5] == 0 and got[6] == 0
        h.close()

    def test_range_write_invalidates(self, tmp_path):
        h, ex, body = self._range_holder(tmp_path)
        before = ex.execute("r", body)
        ex.execute("r", 'SetBit(rowID=1, frame="f", columnID=999999, timestamp="2017-03-15T00:00")')
        after = ex.execute("r", body)
        assert after[0] == before[0] + 1 and after[2] == before[2] + 1
        assert after[1] == before[1]
        h.close()

    @pytest.mark.parametrize("q", [
        # unknown frame -> ErrFrameNotFound, identical through both lanes
        'Count(Range(rowID=1, frame="nope", start="2017-01-01T00:00", end="2017-02-01T00:00")) ' * 2,
        # month 13 -> "cannot parse Range() time" (calendar checks stay in Python)
        'Count(Range(rowID=1, frame="f", start="2017-13-01T00:00", end="2017-14-01T00:00")) ' * 2,
        # non-padded time declines the native matcher; Python still serves it
        'Count(Range(rowID=1, frame="f", start="2017-1-01T00:00", end="2017-02-01T00:00")) ' * 2,
    ])
    def test_range_edges_byte_identical(self, tmp_path, monkeypatch, q):
        h, ex, _ = self._range_holder(tmp_path)

        def run(e):
            try:
                return e.execute("r", q), None
            except Exception as exc:  # noqa: BLE001 — comparing error text
                return None, f"{type(exc).__name__}: {exc}"

        r_native, err_native = run(ex)
        monkeypatch.setenv("PILOSA_TPU_NO_RANGELANE", "1")
        r_py, err_py = run(ex)
        assert (r_native, err_native) == (r_py, err_py)
        h.close()
