"""PR 14 observability plane: Prometheus exposition at /metrics, the
cluster-wide fleet view at /debug/fleet, and the trace-derived
per-fingerprint cost ledger at /debug/costs.

The invariants pinned:

- The registry -> Prometheus name mapping is MECHANICAL (prom_name), so
  /metrics covers every series the stats client holds — asserted here
  by diffing the exposition's families against snapshot_typed().
- parse_exposition is STRICT (the bench preflight's contract): any
  malformed line raises with its line number.
- /debug/fleet over a 3-group cluster with one group DOWN serves a
  PARTIAL aggregate: the dead group stays present with an error and a
  staleness stamp, the survivors scrape live.
- The cost ledger folds recorded traces into bounded EWMA entries
  keyed (index, frame, fingerprint, lane); /debug/costs serves them
  cost-descending.
- ?min-ms=/?limit= on the debug endpoints CLAMP malformed values
  instead of answering 400.
- Spans and slow-query log lines carry qos_class + tenant tags.
"""

import json
import logging
import tempfile
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import metrics
from pilosa_tpu.config import Config
from pilosa_tpu.costs import CostLedger, DispatchMeter
from pilosa_tpu.stats import NOP_STATS, ExpvarStatsClient
from pilosa_tpu.trace import Trace, Tracer


# -- name mapping -------------------------------------------------------------


def test_prom_name_mechanical_mapping():
    assert metrics.prom_name("qcache.hit", "counter") == "pilosa_qcache_hit_total"
    assert metrics.prom_name("qos.latency_ms.read") == "pilosa_qos_latency_ms_read"
    # Registry placeholder segments stay valid names for the drift gate.
    assert metrics.valid_metric_name(metrics.prom_name("engine.dispatch_ms.<lane>"))
    assert metrics.prom_name("replica.healthy.g-0") == "pilosa_replica_healthy_g_0"


def test_split_key_tags_to_labels():
    assert metrics.split_key("index.query") == ("index.query", {})
    base, labels = metrics.split_key("index.query[index:foo,frame:f]")
    assert base == "index.query"
    assert labels == {"index": "foo", "frame": "f"}
    # A bare tag with no colon becomes a `tag` label.
    assert metrics.split_key("x[solo]")[1] == {"tag": "solo"}


def test_registry_collisions_invalid_and_colliding():
    # Clean set: no findings.
    assert metrics.registry_collisions({"a.b": "counter", "c.d": "gauge"}) == []
    # Two distinct series mangling onto one name (the _total rename).
    bad = metrics.registry_collisions({"a.b": "counter", "a.b.total": "gauge"})
    assert bad and bad[0][2] == "pilosa_a_b_total"
    # A name that mangles to nothing is invalid.
    assert metrics.registry_collisions({"!!!": "gauge"})[0][1] == ""


def test_clamp_float_and_int():
    assert metrics.clamp_float("2.5", 0.0) == 2.5
    assert metrics.clamp_float("bogus", 0.0) == 0.0
    assert metrics.clamp_float(None, 7.0) == 7.0
    assert metrics.clamp_float("nan", 3.0) == 3.0
    assert metrics.clamp_float("-4", 0.0) == 0.0  # lo clamp
    assert metrics.clamp_int("12", 64) == 12
    assert metrics.clamp_int("junk", 64) == 64
    assert metrics.clamp_int("-3", 64) == 0
    assert metrics.clamp_int("1e99", 64) == 1 << 30  # hi clamp


# -- render + strict parse ----------------------------------------------------


def _loaded_client() -> ExpvarStatsClient:
    c = ExpvarStatsClient()
    c.count("index.query", 3)
    c.with_tags("index:foo").count("index.query", 2)
    c.gauge("replica.wal_bytes", 123)
    c.set("node.state", "up")
    for v in (1.0, 2.0, 50.0):
        c.histogram("qos.latency_ms.read", v)
    c.timing("snapshot", 0.25)
    return c


def test_render_covers_every_series_and_parses():
    c = _loaded_client()
    text = metrics.render(c)
    fams = metrics.parse_exposition(text)
    # MECHANICAL coverage: every series the client holds appears as a
    # family in the exposition under its prom_name.
    typed = c.snapshot_typed()
    for key in typed["counters"]:
        base, _ = metrics.split_key(key)
        assert metrics.prom_name(base, "counter") in fams, (key, fams)
    for kind in ("gauges", "sets"):
        for key in typed[kind]:
            base, _ = metrics.split_key(key)
            assert metrics.prom_name(base) in fams, key
    for key in typed["histograms"]:
        base, _ = metrics.split_key(key)
        assert fams[metrics.prom_name(base)]["type"] == "summary"
    for key in typed["timings"]:
        base, _ = metrics.split_key(key)
        assert fams[metrics.prom_name(base) + "_seconds"]["type"] == "summary"
    # Tagged counter rendered with labels; summary carries its quantile
    # rows plus _count/_sum (5 samples toward the base family).
    assert 'pilosa_index_query_total{index="foo"} 2' in text
    assert fams["pilosa_qos_latency_ms_read"]["samples"] == 5
    assert 'pilosa_node_state{value="up"} 1' in text


def test_render_nop_stats_is_empty_valid_exposition():
    assert metrics.render(NOP_STATS) == ""
    assert metrics.parse_exposition("") == {}


def test_parse_exposition_rejects_malformed():
    with pytest.raises(ValueError, match="line 1"):
        metrics.parse_exposition("not a metric line!")
    with pytest.raises(ValueError, match="malformed TYPE"):
        metrics.parse_exposition("# TYPE pilosa_x")
    with pytest.raises(ValueError, match="bad sample value"):
        metrics.parse_exposition("pilosa_x twelve")
    with pytest.raises(ValueError, match="malformed labels"):
        metrics.parse_exposition('pilosa_x{a=unquoted} 1')
    # Label values holding commas/spaces inside the quotes are legal.
    fams = metrics.parse_exposition('pilosa_x{a="b, c d",e="f"} 1')
    assert fams["pilosa_x"]["samples"] == 1


# -- cost ledger --------------------------------------------------------------


def test_cost_ledger_ewma_and_lru_eviction():
    stats = ExpvarStatsClient()
    led = CostLedger(cap=2, alpha=0.5, stats=stats)
    led.observe(index="i", fp="a", lane="gram", ms=10.0, bytes_moved=1_000_000)
    led.observe(index="i", fp="a", lane="gram", ms=20.0)
    led.observe(index="i", fp="b", lane="gather", ms=5.0)
    e = led.snapshot()["entries"][0]
    assert e["fp"] == "a" and e["n"] == 2
    assert e["ewma_ms"] == pytest.approx(15.0)  # 10 + 0.5*(20-10)
    # Transfer-free second hit did not decay the bandwidth estimate.
    assert e["ewma_mbps"] == pytest.approx(100.0)  # 1 MB in 10 ms
    # Third key over cap=2 evicts the least-recently-touched ("a" was
    # last touched before "b" was inserted).
    led.observe(index="i", fp="c", lane="flat", ms=1.0)
    fps = {x["fp"] for x in led.snapshot()["entries"]}
    assert fps == {"b", "c"} and len(led) == 2
    snap = stats.snapshot()
    assert snap["costs.fold"] == 4 and snap["costs.evict"] == 1
    assert snap["costs.entries"] == 2


def test_cost_ledger_folds_device_spans_from_trace():
    led = CostLedger()
    tr = Trace("POST /index/foo/query")
    tr.root.tags.update({"tenant": "foo", "lane": "flat", "frame": "f"})
    d = tr.root.child("device")
    d.ms = 2.0
    d.tags.update({"lane": "flat", "bytes": 4096})
    led.fold(tr, dt_ms=9.0, body=b'Count(Bitmap(rowID=1, frame="f"))')
    e = led.snapshot()["entries"][0]
    assert (e["index"], e["frame"], e["lane"]) == ("foo", "f", "flat")
    assert e["fp"] and e["ewma_ms"] == 9.0 and e["ewma_device_ms"] == 2.0
    assert e["ewma_mbps"] > 0


def test_dispatch_meter_emits_tagged_series_and_device_span():
    class FakeEngine:
        stat_upload_bytes = 0

    stats = ExpvarStatsClient()
    eng = FakeEngine()
    meter = DispatchMeter(stats, engine=eng)
    tr = Trace("q")
    with meter.measure("stream", tr.root) as m:
        eng.stat_upload_bytes += 1 << 20  # the upload-ledger delta
        m.add_bytes(512)
    snap = stats.snapshot()
    assert snap["engine.dispatch_ms.stream"]["count"] == 1
    assert snap["engine.dispatch_bytes.stream"] == (1 << 20) + 512
    dev = tr.root.children[0]
    assert dev.name == "device" and dev.tags["lane"] == "stream"
    assert dev.tags["bytes"] == (1 << 20) + 512 and dev.ms >= 0
    meter.resident(123456)
    assert stats.snapshot()["engine.hbm_bytes"] == 123456


# -- server integration: /metrics, /debug/costs, clamp, span tags -------------


@pytest.fixture
def server(tmp_path):
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=str(tmp_path / "d"), host="127.0.0.1:0", engine="numpy",
        stats="expvar", trace_sample_rate=1.0,
    )
    s = Server(cfg)
    s.open()
    try:
        yield s
    finally:
        s.close()


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def _post(url, body, headers=None, timeout=30):
    rq = urllib.request.Request(url, data=body, method="POST")
    for k, v in (headers or {}).items():
        rq.add_header(k, v)
    with urllib.request.urlopen(rq, timeout=timeout) as resp:
        return resp.status, resp.read()


def test_server_metrics_endpoint_valid_and_complete(server):
    s = server
    base = f"http://{s.host}"
    _post(base + "/index/i", b"{}")
    _post(base + "/index/i/frame/f", b"{}")
    _post(base + "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=2)')
    _post(base + "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
    st, body, hdrs = _get(base + "/metrics")
    assert st == 200
    assert hdrs["Content-Type"].startswith("text/plain; version=0.0.4")
    fams = metrics.parse_exposition(body.decode())
    assert fams, "server exposition is empty after serving requests"
    # Every series emitted during the run is covered by the exposition.
    typed = s.stats.snapshot_typed()
    kinds = [("counters", "counter"), ("gauges", ""), ("sets", "")]
    for field, kind in kinds:
        for key in typed[field]:
            base_name, _ = metrics.split_key(key)
            assert metrics.prom_name(base_name, kind) in fams, key
    for key in typed["histograms"]:
        base_name, _ = metrics.split_key(key)
        assert metrics.prom_name(base_name) in fams, key
    # The QoS door's latency histogram made it through as a summary.
    assert fams["pilosa_qos_latency_ms_read"]["type"] == "summary"


def test_server_debug_costs_per_fingerprint_lanes(server):
    s = server
    base = f"http://{s.host}"
    _post(base + "/index/i", b"{}")
    _post(base + "/index/i/frame/f", b"{}")
    _post(base + "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=2)')
    q = b'Count(Bitmap(rowID=1, frame="f"))'
    for _ in range(3):
        _post(base + "/index/i/query", q)
    st, body, _ = _get(base + "/debug/costs")
    assert st == 200
    out = json.loads(body)
    assert out["cap"] > 0 and out["entries"]
    # The repeated Count folded into ONE entry keyed by its fingerprint,
    # tagged with the tenant index and a strategy lane.
    counts = [e for e in out["entries"] if e["index"] == "i" and e["n"] >= 3]
    assert counts, out["entries"]
    assert counts[0]["fp"] and counts[0]["lane"]
    assert counts[0]["ewma_ms"] > 0
    # ?limit= caps the payload (and clamps malformed values).
    st, body, _ = _get(base + "/debug/costs?limit=1")
    assert len(json.loads(body)["entries"]) == 1
    st, body, _ = _get(base + "/debug/costs?limit=bogus")
    assert st == 200


def test_debug_traces_clamps_malformed_filters(server):
    s = server
    base = f"http://{s.host}"
    _post(base + "/index/i", b"{}")
    _post(base + "/index/i/frame/f", b"{}")
    _post(base + "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
    # Malformed/out-of-range values clamp to defaults — never 400.
    for qs in ("?min-ms=bogus", "?min-ms=nan", "?limit=-5", "?min-ms=&limit="):
        st, body, _ = _get(base + "/debug/traces" + qs)
        assert st == 200, qs
        json.loads(body)
    # Valid filters still filter, newest-first.
    for t in s.tracer.traces_json():
        pass
    st, body, _ = _get(base + "/debug/traces?min-ms=999999")
    assert json.loads(body)["traces"] == []
    st, body, _ = _get(base + "/debug/traces?limit=1")
    traces = json.loads(body)["traces"]
    assert len(traces) <= 1
    all_traces = json.loads(_get(base + "/debug/traces")[1])["traces"]
    if len(all_traces) > 1:
        assert all_traces[0]["ms"] is not None  # newest first entry intact
        assert traces[0]["name"] == all_traces[0]["name"]


def test_span_and_slowlog_carry_qos_class_and_tenant(server, caplog):
    s = server
    base = f"http://{s.host}"
    _post(base + "/index/i", b"{}")
    _post(base + "/index/i/frame/f", b"{}")
    _post(base + "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
    entry = s.tracer.traces_json(limit=1)[0]
    tags = entry["spans"]["tags"]
    assert tags["qos_class"] == "read" and tags["tenant"] == "i"
    # Slow-query bypass: unsampled request over slow-ms synthesizes a
    # root-only trace and exactly ONE structured log line, both carrying
    # the QoS class + tenant tags.
    s.tracer.sample_rate = 0.0
    s.tracer.slow_ms = 1e-6
    before = len(s.tracer)
    with caplog.at_level(logging.WARNING, logger="pilosa_tpu.slowquery"):
        _post(base + "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
    slow = [r for r in caplog.records if r.name == "pilosa_tpu.slowquery"]
    assert len(slow) == 1, "expected exactly one slow-query line"
    rec = json.loads(slow[0].message.split("slow-query ", 1)[1])
    assert rec["tags"]["qos_class"] == "read" and rec["tags"]["tenant"] == "i"
    assert len(s.tracer) == before + 1
    entry = s.tracer.traces_json(limit=1)[0]
    assert entry["slow"] and entry["spans"]["tags"]["unsampled"] is True
    assert entry["spans"]["tags"]["qos_class"] == "read"
    assert "children" not in entry["spans"]  # root-only: synthesized late


# -- fleet view ---------------------------------------------------------------


class _FleetRig:
    """Three in-process group servers + a router (the test_replica rig
    shape, sized for the fleet view)."""

    def __init__(self, tmp, n_groups=3):
        from pilosa_tpu.replica import ReplicaRouter
        from pilosa_tpu.server.server import Server

        self.servers = []
        for i in range(n_groups):
            cfg = Config(
                data_dir=f"{tmp}/g{i}", host="127.0.0.1:0", engine="numpy",
                stats="expvar", qcache_enabled=False, replica_group=f"g{i}",
            )
            srv = Server(cfg)
            srv.open()
            self.servers.append(srv)
        self.stats = ExpvarStatsClient()
        self.router = ReplicaRouter(
            [f"g{i}={srv.host}" for i, srv in enumerate(self.servers)],
            probe_interval_s=0.1, stats=self.stats,
            tracer=Tracer(sample_rate=1.0),
        ).serve()
        self.base = f"http://127.0.0.1:{self.router.port}"
        self.closed = set()

    def close(self):
        self.router.close()
        for i, s in enumerate(self.servers):
            if i not in self.closed:
                s.close()

    def kill(self, i):
        self.servers[i].close()
        self.closed.add(i)


@pytest.fixture
def fleet():
    with tempfile.TemporaryDirectory() as tmp:
        r = _FleetRig(tmp)
        try:
            yield r
        finally:
            r.close()


def test_fleet_aggregates_and_degrades_partially(fleet):
    base = fleet.base
    _post(base + "/index/i", b"{}")
    _post(base + "/index/i/frame/f", b"{}")
    _post(base + "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=1)')
    _post(base + "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
    st, body, _ = _get(base + "/debug/fleet")
    assert st == 200
    fl = json.loads(body)
    assert fl["partial"] is False and len(fl["groups"]) == 3
    assert fl["quorum"] == 2 and fl["quorate"] is True
    # 3 sequenced mutations (2 schema + 1 SetBit); the Count is a read.
    assert fl["wal"]["lastSeq"] == fl["writeSeq"] == 3
    for g in fl["groups"]:
        assert g["staleScrape"] is False and g["ageMs"] is not None
        assert g["scrape"]["health"]["group"] == g["name"]
        assert g["scrape"]["appliedSeq"] == 3 and g["walDepth"] == 0
        # Latency percentiles surfaced from the group's QoS histograms
        # (every group saw the fanned-out writes at minimum).
        assert "write" in g["scrape"]["latencyMs"]
        assert g["scrape"]["latencyMs"]["write"]["p50"] >= 0
    # The one group that served the read carries its read percentiles.
    assert any("read" in g["scrape"]["latencyMs"] for g in fl["groups"])
    # Router-side progress counters ride along.
    assert fl["routerStats"]["replica.write_fanout"] == 3
    # Kill one group: the aggregate degrades to PARTIAL — the dead
    # group stays present, stamped stale with its error and the LAST
    # SUCCESSFUL scrape (aged), while the survivors scrape live.
    fleet.kill(2)
    st, body, _ = _get(base + "/debug/fleet?timeout-ms=200")
    fl = json.loads(body)
    assert st == 200 and fl["partial"] is True
    dead = next(g for g in fl["groups"] if g["name"] == "g2")
    assert dead["staleScrape"] is True and dead["error"]
    assert dead["scrape"] is not None  # cached from the earlier scrape
    assert dead["ageMs"] >= 0
    live = [g for g in fl["groups"] if g["name"] != "g2"]
    assert all(not g["staleScrape"] for g in live)
    # The router's own exposition stays scrapeable throughout.
    st, body, hdrs = _get(base + "/metrics")
    assert st == 200
    fams = metrics.parse_exposition(body.decode())
    assert "pilosa_replica_write_fanout_total" in fams


def test_router_debug_traces_clamp(fleet):
    base = fleet.base
    _post(base + "/index/i", b"{}")
    st, body, _ = _get(base + "/debug/traces?min-ms=bogus&limit=junk")
    assert st == 200
    json.loads(body)
