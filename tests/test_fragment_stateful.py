"""Hypothesis stateful fuzz for the fragment persistence layer.

Random interleavings of scalar/batched writes, snapshots, clean
close+reopen, and CRASH reopen (handles dropped without close, WAL
replayed) against a dict model — the directed crash-safety tests
(test_crashsafety.py) pin known failure modes; this machine searches
for unknown interleavings, with shrinking to a minimal op sequence.
"""

import os

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import RuleBasedStateMachine, invariant, precondition, rule

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.pilosa import SLICE_WIDTH

_ROW = st.integers(0, 7)
# Columns clustered inside two containers plus the slice tail.
_COL = st.one_of(
    st.integers(0, 1 << 17),
    st.integers(SLICE_WIDTH - 256, SLICE_WIDTH - 1),
)


class FragmentMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        import tempfile

        self._dir = tempfile.mkdtemp()
        self.path = os.path.join(self._dir, "frag")
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()
        self.model: set[tuple[int, int]] = set()

    def teardown(self):
        try:
            self.f.close()
        except Exception:
            pass
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)

    @rule(r=_ROW, c=_COL)
    def set_bit(self, r, c):
        assert self.f.set_bit(r, c) == ((r, c) not in self.model)
        self.model.add((r, c))

    @rule(r=_ROW, c=_COL)
    def clear_bit(self, r, c):
        assert self.f.clear_bit(r, c) == ((r, c) in self.model)
        self.model.discard((r, c))

    @rule(bits=st.lists(st.tuples(_ROW, _COL), min_size=1, max_size=40))
    def set_bits(self, bits):
        rows = np.asarray([b[0] for b in bits], dtype=np.uint64)
        cols = np.asarray([b[1] for b in bits], dtype=np.uint64)
        changed = self.f.set_bits(rows, cols)
        seen = set(self.model)
        for i, b in enumerate(bits):
            assert changed[i] == (b not in seen)
            seen.add(b)
        self.model |= set(bits)

    @rule()
    def snapshot(self):
        self.f.snapshot()

    @rule()
    def clean_reopen(self):
        self.f.close()
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()

    @rule()
    def crash_reopen(self):
        """Drop handles without close() — reopen must replay the WAL."""
        f = self.f
        if f._wal is not None:
            f._wal.close()
            f._wal = None
            f.storage.op_writer = None
        f._release_flock()
        f._open = False
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()

    @precondition(lambda self: len(self.model) > 0)
    @rule()
    def check_some_row(self):
        r = next(iter(self.model))[0]
        want = sum(1 for (rr, _c) in self.model if rr == r)
        assert self.f.row_count(r) == want

    @invariant()
    def total_count_matches(self):
        assert self.f.count() == len(self.model)
        self.f.storage.check()


TestFragmentModel = FragmentMachine.TestCase
TestFragmentModel.settings = settings(
    max_examples=25, stateful_step_count=40, deadline=None
)
