"""Hypothesis stateful fuzz for the fragment persistence layer.

Random interleavings of scalar/batched writes, snapshots, clean
close+reopen, and CRASH reopen (handles dropped without close, WAL
replayed) against a dict model — the directed crash-safety tests
(test_crashsafety.py) pin known failure modes; this machine searches
for unknown interleavings, with shrinking to a minimal op sequence.
"""

import os

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import RuleBasedStateMachine, invariant, precondition, rule

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.pilosa import SLICE_WIDTH

_ROW = st.integers(0, 7)
# Columns clustered inside two containers plus the slice tail.
_COL = st.one_of(
    st.integers(0, 1 << 17),
    st.integers(SLICE_WIDTH - 256, SLICE_WIDTH - 1),
)


class FragmentMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        import tempfile

        self._dir = tempfile.mkdtemp()
        self.path = os.path.join(self._dir, "frag")
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()
        self.model: set[tuple[int, int]] = set()

    def teardown(self):
        try:
            self.f.close()
        except Exception:
            pass
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)

    @rule(r=_ROW, c=_COL)
    def set_bit(self, r, c):
        assert self.f.set_bit(r, c) == ((r, c) not in self.model)
        self.model.add((r, c))

    @rule(r=_ROW, c=_COL)
    def clear_bit(self, r, c):
        assert self.f.clear_bit(r, c) == ((r, c) in self.model)
        self.model.discard((r, c))

    @rule(bits=st.lists(st.tuples(_ROW, _COL), min_size=1, max_size=40))
    def set_bits(self, bits):
        rows = np.asarray([b[0] for b in bits], dtype=np.uint64)
        cols = np.asarray([b[1] for b in bits], dtype=np.uint64)
        changed = self.f.set_bits(rows, cols)
        seen = set(self.model)
        for i, b in enumerate(bits):
            assert changed[i] == (b not in seen)
            seen.add(b)
        self.model |= set(bits)

    @rule()
    def snapshot(self):
        self.f.snapshot()

    @rule()
    def clean_reopen(self):
        self.f.close()
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()

    @rule()
    def crash_reopen(self):
        """Drop handles without close() — reopen must replay the WAL."""
        f = self.f
        if f._wal is not None:
            f._wal.close()
            f._wal = None
            f.storage.op_writer = None
        f._release_flock()
        f._open = False
        self.f = Fragment(self.path, "i", "f", "standard", 0, max_opn=25)
        self.f.open()

    @precondition(lambda self: len(self.model) > 0)
    @rule()
    def check_some_row(self):
        r = next(iter(self.model))[0]
        want = sum(1 for (rr, _c) in self.model if rr == r)
        assert self.f.row_count(r) == want

    @invariant()
    def total_count_matches(self):
        assert self.f.count() == len(self.model)
        self.f.storage.check()


TestFragmentModel = FragmentMachine.TestCase
TestFragmentModel.settings = settings(
    max_examples=25, stateful_step_count=40, deadline=None
)


# -- checksum stability (replica anti-entropy rests on it) -------------------
#
# Fragment.checksum() must be a pure function of the LOGICAL BIT SET:
# the replica digest protocol (replica/digest.py) compares checksums
# across groups that built the same bits through different paths —
# different write orders, scalar vs batched writes, patch vs wholesale
# rebuild (write_to/read_from), set-then-clear detours — and declares
# divergence on any mismatch.  A path-dependent checksum would turn
# every resync into a false divergence.

from hypothesis import given  # noqa: E402

_BITS = st.lists(
    st.tuples(_ROW, _COL), min_size=1, max_size=50, unique=True
)


def _fresh_fragment(tmpdir, name):
    f = Fragment(os.path.join(tmpdir, name), "i", "f", "standard", 0)
    f.open()
    return f


@settings(max_examples=20, deadline=None)
@given(bits=_BITS, seed=st.integers(0, 2**32 - 1))
def test_checksum_stable_across_write_orders(bits, seed):
    """Same logical bits via (a) insertion order, (b) a shuffled order,
    (c) one bulk import must produce identical whole-fragment digests."""
    import random as _random
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp()
    try:
        a = _fresh_fragment(tmp, "a")
        for r, c in bits:
            a.set_bit(r, c)
        shuffled = list(bits)
        _random.Random(seed).shuffle(shuffled)
        b = _fresh_fragment(tmp, "b")
        for r, c in shuffled:
            b.set_bit(r, c)
        c_frag = _fresh_fragment(tmp, "c")
        c_frag.import_bits(
            np.asarray([x[0] for x in bits], dtype=np.uint64),
            np.asarray([x[1] for x in bits], dtype=np.uint64),
        )
        assert a.checksum() == b.checksum() == c_frag.checksum()
        for f in (a, b, c_frag):
            f.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


@settings(max_examples=20, deadline=None)
@given(bits=_BITS, extra=st.tuples(_ROW, _COL))
def test_checksum_stable_across_repair_and_replay_paths(bits, extra):
    """The write -> repair -> replay lifecycle: a fragment restored
    wholesale from another's serialized payload (the resync stream
    path), then written further, digests identically to the original
    taking the same writes through its patch path; a set+clear detour
    leaves the digest unchanged."""
    import io
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp()
    try:
        a = _fresh_fragment(tmp, "a")
        for r, c in bits:
            a.set_bit(r, c)
        buf = io.BytesIO()
        a.write_to(buf)
        b = _fresh_fragment(tmp, "b")
        b.read_from(buf.getvalue())
        assert a.checksum() == b.checksum()
        # Diverge-and-return: a detour through extra bits on one side
        # only must cancel out of the digest.
        r, c = extra
        had = a.storage.contains(int(r) * SLICE_WIDTH + int(c))
        a.set_bit(r, c)
        if not had:
            assert a.checksum() != b.checksum()
            a.clear_bit(r, c)
        assert a.checksum() == b.checksum()
        # Same further writes on both paths keep them digest-equal.
        for r2, c2 in bits[: len(bits) // 2]:
            a.clear_bit(r2, c2)
            b.clear_bit(r2, c2)
        assert a.checksum() == b.checksum()
        a.close()
        b.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def test_checksum_position_bound():
    """Identical relative bit patterns at DIFFERENT block ids must not
    collide: the block id participates in the whole-fragment hash (two
    groups disagreeing only on WHERE the rows sit would otherwise
    digest as equal and anti-entropy would never repair them)."""
    import shutil
    import tempfile

    from pilosa_tpu.core.fragment import HASH_BLOCK_SIZE

    tmp = tempfile.mkdtemp()
    try:
        a = _fresh_fragment(tmp, "a")
        a.set_bit(0, 5)
        b = _fresh_fragment(tmp, "b")
        b.set_bit(HASH_BLOCK_SIZE, 5)  # same offset inside block 1
        assert a.checksum() != b.checksum()
        a.close()
        b.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
