"""Cluster placement + config + broadcast tests (cluster_test.go analog)."""

import pytest

from pilosa_tpu import broadcast as bc
from pilosa_tpu.cluster import Cluster, Node, fnv1a64, jump_hash
from pilosa_tpu.config import Config


def make_cluster(n, replica_n=1):
    return Cluster(nodes=[Node(host=f"host{i}:10101") for i in range(n)], replica_n=replica_n)


def test_fnv1a64_known_vectors():
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_jump_hash_properties():
    # deterministic
    assert jump_hash(12345, 10) == jump_hash(12345, 10)
    # in range and uses all buckets over many keys
    buckets = {jump_hash(k, 8) for k in range(1000)}
    assert buckets == set(range(8))
    # monotone stability: growing n only moves keys INTO the new bucket
    for k in range(200):
        b5, b6 = jump_hash(k, 5), jump_hash(k, 6)
        assert b6 == b5 or b6 == 5


def test_partition_stability():
    c = make_cluster(3)
    # partition depends only on (index, slice), not on nodes
    p = c.partition("myindex", 7)
    assert 0 <= p < 256
    assert c.partition("myindex", 7) == p
    assert c.partition("other", 7) != p or True  # different index may differ


def test_fragment_nodes_and_replication():
    c = make_cluster(4, replica_n=2)
    nodes = c.fragment_nodes("i", 0)
    assert len(nodes) == 2
    assert nodes[0] is not nodes[1]
    # consecutive ring placement
    i0 = c.nodes.index(nodes[0])
    assert c.nodes[(i0 + 1) % 4] is nodes[1]
    # all slices covered, ownership deterministic
    assert c.owns_fragment(nodes[0].host, "i", 0)
    assert not c.owns_fragment("nobody:1", "i", 0)


def test_owns_slices_partition_of_work():
    c = make_cluster(3)
    max_slice = 29
    all_slices = []
    for node in c.nodes:
        all_slices += c.owns_slices("i", max_slice, node.host)
    assert sorted(all_slices) == list(range(max_slice + 1))


def test_slices_by_node_down_failover():
    c = make_cluster(3, replica_n=2)
    slices = list(range(12))
    by_node = c.slices_by_node("i", slices)
    assert sorted(s for ss in by_node.values() for s in ss) == slices
    # kill one node: its slices must move to replicas
    c.nodes[0].state = "DOWN"
    by_node2 = c.slices_by_node("i", slices, exclude_down=True)
    assert c.nodes[0] not in by_node2
    assert sorted(s for ss in by_node2.values() for s in ss) == slices


def test_broadcast_envelope_roundtrip():
    for msg, typ, want in [
        (bc.encode_create_slice("i", 5, True), bc.MESSAGE_TYPE_CREATE_SLICE, {"index": "i", "slice": 5, "isInverse": True}),
        (bc.encode_delete_index("x"), bc.MESSAGE_TYPE_DELETE_INDEX, {"index": "x"}),
        (bc.encode_delete_frame("x", "f"), bc.MESSAGE_TYPE_DELETE_FRAME, {"index": "x", "frame": "f"}),
    ]:
        t, payload = bc.decode_message(msg)
        assert t == typ
        for k, v in want.items():
            assert payload[k] == v
    t, payload = bc.decode_message(bc.encode_create_frame("i", "f", {"rowLabel": "r", "cacheSize": 9}))
    assert payload["meta"]["rowLabel"] == "r"
    assert payload["meta"]["cacheSize"] == 9
    with pytest.raises(ValueError):
        bc.decode_message(bytes([99]) + b"x")


def test_config_toml_env_precedence(tmp_path, monkeypatch):
    p = tmp_path / "cfg.toml"
    p.write_text(
        'data-dir = "/tmp/d"\nhost = "h:1"\n[cluster]\nreplicas = 2\nhosts = ["h:1", "h2:1"]\n'
        '[anti-entropy]\ninterval = "5m"\n'
    )
    cfg = Config.from_toml(str(p))
    assert cfg.data_dir == "/tmp/d"
    assert cfg.cluster.replica_n == 2
    assert cfg.anti_entropy_interval == 300.0
    cfg.apply_env({"PILOSA_HOST": "env:9", "PILOSA_CLUSTER_REPLICAS": "3"})
    assert cfg.host == "env:9"
    assert cfg.cluster.replica_n == 3
    # round-trip through to_toml parses again (config's tomllib alias
    # falls back to the tomli backport on Python < 3.11)
    from pilosa_tpu.config import tomllib

    cfg2 = Config.from_dict(tomllib.loads(cfg.to_toml()))
    assert cfg2.cluster.replica_n == 3
