"""Cost-based adaptive planner tests: lane selection (Planner), the
ledger it reads (CostLedger edges), adaptive budgets, predictive
pre-arming, and the executor-side contracts — most importantly that a
planner with an EMPTY ledger reproduces the static strategy ladder's
results exactly (lane None plans change nothing), which is what makes
enabling the planner by default safe.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.costs import CostLedger
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.pilosa import SLICE_WIDTH
from pilosa_tpu.planner import AdaptiveBudgets, PLAN_LANES, Planner, PreArmer
from pilosa_tpu.trace import fingerprint

FP = "aabbccdd"


def _fold(led, lane, ms, n=1, index="i", fp=FP):
    for _ in range(n):
        led.observe(index=index, frame="", fp=fp, lane=lane, ms=ms, wall_ts=0.0)


# -- Planner: decision cascade -------------------------------------------


def test_planner_static_until_confidence_gate():
    """With an empty ledger every non-explore consult is the static
    ladder (lane None), and confidence reflects the starved lane."""
    pl = Planner(CostLedger(), min_samples=3, explore_every=4)
    for i in range(1, 4):  # consults 1..3: no explore tick yet
        plan = pl.choose("i", FP)
        assert plan["lane"] is None and plan["src"] == "static"
        assert plan["confidence"] == 0.0
    # One lane fully sampled does NOT open the gate: confidence is
    # min over lanes.
    _fold(pl.ledger, "gram", 10.0, n=5)
    plan = pl.choose("i", FP)  # consult 4 — explore tick, see below
    plan = pl.choose("i", FP)  # consult 5
    assert plan["lane"] is None and plan["src"] == "static"


def test_planner_explore_tick_is_deterministic_and_starved_first():
    pl = Planner(CostLedger(), min_samples=3, explore_every=4)
    picks = [pl.choose("i", FP) for _ in range(8)]
    # Consults 4 and 8 are explore ticks; both lanes tied at 0 samples
    # breaks ties in PLAN_LANES order.
    assert [p["src"] for p in picks] == ["static"] * 3 + ["explore"] + ["static"] * 3 + ["explore"]
    assert picks[3]["lane"] == PLAN_LANES[0] == "gram"
    # Once gram has samples and rmgather has none, the tick samples the
    # starved lane.
    _fold(pl.ledger, "gram", 10.0, n=2)
    for _ in range(3):
        pl.choose("i", FP)
    plan = pl.choose("i", FP)  # consult 12
    assert plan["src"] == "explore" and plan["lane"] == "rmgather"


def test_planner_ledger_pick_and_hysteresis():
    led = CostLedger()
    pl = Planner(led, min_samples=3, hysteresis=0.15, explore_every=100)
    _fold(led, "gram", 10.0, n=3)
    _fold(led, "rmgather", 9.0, n=3)
    plan = pl.choose("i", FP)
    assert plan["src"] == "ledger" and plan["lane"] == "rmgather"
    assert plan["confidence"] == 0.5  # 3 samples / (2 * min_samples)
    # Challenger inside the hysteresis band keeps the incumbent: gram's
    # EWMA folds 10.0 -> 8.5, still above 9.0 * (1 - 0.15) = 7.65.
    _fold(led, "gram", 4.0)
    plan = pl.choose("i", FP)
    assert plan["lane"] == "rmgather"
    # Clearing the band takes over: 8.5 -> 6.375 < 7.65.
    _fold(led, "gram", 0.0)
    plan = pl.choose("i", FP)
    assert plan["src"] == "ledger" and plan["lane"] == "gram"


def test_planner_pin_forces_lane():
    pl = Planner(CostLedger(), pin="rmgather")
    plan = pl.choose("i", FP)
    assert plan == {"fp": FP, "lane": "rmgather", "src": "pinned", "confidence": 1.0}
    # An unknown pin is dropped, not honored.
    assert Planner(CostLedger(), pin="bogus").pin == ""


def test_planner_record_scores_decisions_and_folds_actual_lane():
    led = CostLedger()
    pl = Planner(led, min_samples=3)
    pl.choose("i", FP)  # create key state
    # Planner-made pick with no alternative evidence counts as a win.
    pl.record(index="i", fp=FP, lane="gram", ms=5.0,
              plan={"fp": FP, "lane": "gram", "src": "ledger"})
    # Static-ladder outcomes fold costs but are not scored.
    pl.record(index="i", fp=FP, lane="gram", ms=5.0,
              plan={"fp": FP, "lane": None, "src": "static"})
    # A pick that loses to the other lane's EWMA counts as a loss.
    _fold(led, "rmgather", 1.0)
    pl.record(index="i", fp=FP, lane="gram", ms=5.0,
              plan={"fp": FP, "lane": "gram", "src": "explore"})
    snap = pl.snapshot()
    (key,) = snap["keys"]
    assert key["wins"] == 1 and key["losses"] == 1
    # All three records folded under the lane that actually ran.
    assert led.peek(index="i", frame="", fp=FP, lane="gram")["n"] == 3
    # Junk lanes and empty fingerprints are ignored outright.
    pl.record(index="i", fp="", lane="gram", ms=1.0)
    pl.record(index="i", fp=FP, lane="native", ms=1.0)
    assert led.peek(index="i", frame="", fp=FP, lane="gram")["n"] == 3


def test_planner_snapshot_shape_and_keys_cap():
    pl = Planner(CostLedger(), keys_cap=4)
    for i in range(6):
        pl.choose("i", f"fp{i}")
    snap = pl.snapshot()
    assert snap["lanes"] == list(PLAN_LANES)
    assert {"min_samples", "hysteresis", "explore_every", "pin"} <= set(snap)
    assert len(snap["keys"]) == 4  # LRU-bounded decision state
    assert {k["fp"] for k in snap["keys"]} == {f"fp{i}" for i in range(2, 6)}
    for k in snap["keys"]:
        assert {"incumbent", "consults", "decided", "wins", "losses",
                "lanes", "confidence"} <= set(k)


def test_planner_plan_for_empty_body():
    pl = Planner(CostLedger())
    assert pl.plan_for("i", b"") is None
    plan = pl.plan_for("i", b"Count(...)")
    assert plan["fp"] == fingerprint(b"Count(...)")["fp"]


# -- CostLedger edges (satellite: eviction, determinism, concurrency) ----


def test_ledger_lru_eviction_under_fingerprint_churn():
    led = CostLedger(cap=8)
    for i in range(100):
        led.observe(index="i", fp=f"fp{i}", lane="gram", ms=1.0, wall_ts=0.0)
    assert len(led) == 8
    assert led.peek(index="i", fp="fp0", lane="gram") is None
    assert led.peek(index="i", fp="fp99", lane="gram") is not None
    # observe() bumps recency; peek() is a pure read and must NOT (the
    # planner consults every request and must not pin its keys hot).
    led.observe(index="i", fp="fp92", lane="gram", ms=1.0, wall_ts=0.0)
    led.peek(index="i", fp="fp93", lane="gram")
    for i in range(100, 106):
        led.observe(index="i", fp=f"fp{i}", lane="gram", ms=1.0, wall_ts=0.0)
    assert led.peek(index="i", fp="fp92", lane="gram") is not None
    assert led.peek(index="i", fp="fp93", lane="gram") is None
    assert led.peek(index="i", fp="fp99", lane="gram") is not None


def test_ledger_ewma_fold_deterministic_across_state_restore():
    obs = [
        (f"fp{i % 5}", PLAN_LANES[i % 2], 1.0 + 0.37 * i, 1000 * i)
        for i in range(40)
    ]
    a = CostLedger(cap=16, alpha=0.25)
    for fp, lane, ms, b in obs[:20]:
        a.observe(index="i", fp=fp, lane=lane, ms=ms, bytes_moved=b, wall_ts=1.0)
    b2 = CostLedger()
    b2.restore(a.state())
    assert b2.cap == 16 and b2.alpha == 0.25
    # Folding the same tail into the restored ledger yields
    # bit-identical state — EWMA folds carry no hidden host state.
    for fp, lane, ms, by in obs[20:]:
        a.observe(index="i", fp=fp, lane=lane, ms=ms, bytes_moved=by, wall_ts=2.0)
        b2.observe(index="i", fp=fp, lane=lane, ms=ms, bytes_moved=by, wall_ts=2.0)
    assert a.state() == b2.state()


def test_ledger_snapshot_consistent_under_concurrent_folds():
    led = CostLedger(cap=32)
    errors = []
    done = threading.Event()

    def folder(tid):
        try:
            for i in range(400):
                led.observe(index="i", fp=f"fp{tid}-{i % 40}", lane="gram",
                            ms=1.0 + (i % 7), wall_ts=0.0)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=folder, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    # Read every public surface while folds churn the LRU.
    for _ in range(200):
        snap = led.snapshot(limit=16)
        assert len(snap["entries"]) <= 16
        for e in snap["entries"]:
            assert e["n"] >= 1 and e["ewma_ms"] > 0
        assert len(led.entries()) <= 32
        st = led.state()
        assert len(st["entries"]) <= 32
    for t in threads:
        t.join()
    done.set()
    assert not errors
    assert len(led) <= 32


# -- AdaptiveBudgets: static-until-evidence, clamped derivations ---------


def test_budgets_static_while_ledger_empty():
    for ledger in (None, CostLedger()):
        b = AdaptiveBudgets(ledger, qcache_min_cost_ms=1.0,
                            catchup_drain_batch=64, resync_chunk_bytes=256 << 10)
        assert b.qcache_min_cost_ms() == 1.0
        assert b.catchup_drain_batch() == 64
        assert b.resync_chunk_bytes() == 256 << 10


def test_budgets_qcache_floor_p25_with_clamps():
    led = CostLedger()
    b = AdaptiveBudgets(led, qcache_min_cost_ms=1.0)
    for i, ms in enumerate([0.5, 0.8, 2.0, 3.0, 4.0, 5.0, 6.0]):
        led.observe(index="i", fp=f"f{i}", lane="gram", ms=ms, wall_ts=0.0)
    assert b.qcache_min_cost_ms() == 1.0  # 7 entries < minimum for a p25
    led.observe(index="i", fp="f7", lane="gram", ms=7.0, wall_ts=0.0)
    assert b.qcache_min_cost_ms() == 2.0  # sorted[len//4] of 8 entries
    # Clamp band: a uniformly expensive (or cheap) population can move
    # the floor at most 10x (0.1x) off the static default.
    for ms, want in [(1000.0, 10.0), (0.001, 0.1)]:
        led2 = CostLedger()
        b2 = AdaptiveBudgets(led2, qcache_min_cost_ms=1.0)
        for i in range(8):
            led2.observe(index="i", fp=f"f{i}", lane="gram", ms=ms, wall_ts=0.0)
        assert b2.qcache_min_cost_ms() == want


def test_budgets_catchup_batch_fits_half_the_locked_drain():
    cases = [
        (10.0, 250),    # 2500 ms budget / 10 ms per record
        (0.5, 1024),    # fit 5000 clamps to the max batch
        (1000.0, 16),   # fit 2 clamps to the min batch
    ]
    for ms, want in cases:
        b = AdaptiveBudgets(CostLedger(), catchup_drain_batch=64,
                            catchup_locked_drain_s=5.0)
        b.observe_transfer("catchup", ms=ms)
        assert b.catchup_drain_batch() == want


def test_budgets_resync_chunk_tracks_bandwidth():
    cases = [
        (100.0, 2_000_000, 1_000_000),   # 20 MB/s * 50 ms
        (10.0, 1 << 30, 4 << 20),        # fast link clamps to 4 MiB
        (1000.0, 100, 64 << 10),         # slow link clamps to 64 KiB
    ]
    for ms, moved, want in cases:
        b = AdaptiveBudgets(CostLedger(), resync_chunk_bytes=256 << 10)
        b.observe_transfer("resync", ms=ms, bytes_moved=moved)
        assert b.resync_chunk_bytes() == want
    # A transfer that moved no bytes leaves bandwidth (and the chunk
    # size) untouched.
    b = AdaptiveBudgets(CostLedger(), resync_chunk_bytes=256 << 10)
    b.observe_transfer("resync", ms=5.0)
    assert b.resync_chunk_bytes() == 256 << 10


# -- PreArmer: registration, invalidation, budgeted replay ---------------


def test_prearmer_shape_registry_cap_and_forget():
    pa = PreArmer(shapes_cap=2)
    for fr in ("a", "b", "c"):
        pa.note_shape("i", fr, lambda: None)
    with pa._cv:
        assert set(pa._shapes) == {("i", "b"), ("i", "c")}
    # Invalidating an unknown shape is a cheap no-op.
    pa.note_invalidate("i", "zzz")
    with pa._cv:
        assert not pa._pending
    pa.note_invalidate("i", "b")
    pa.forget("i", "b")
    with pa._cv:
        assert ("i", "b") not in pa._shapes and not pa._pending
    pa.note_shape("j", "x", lambda: None)
    pa.forget_index("i")
    with pa._cv:
        assert set(pa._shapes) == {("j", "x")}


def test_prearmer_replays_twice_and_survives_thunk_errors():
    calls = []
    done = threading.Event()

    def good():
        calls.append(1)
        if len(calls) >= 2:
            done.set()

    def bad():
        raise RuntimeError("frame dropped mid-flight")

    pa = PreArmer(budget_ms=100.0)
    pa.note_shape("i", "bad", bad)
    pa.note_shape("i", "good", good)
    pa.start()
    try:
        pa.note_invalidate("i", "bad")
        pa.note_invalidate("i", "good")
        # The Gram warms on the second touch: the replay runs TWICE, and
        # the failing thunk must not take down the worker.
        assert done.wait(10.0)
    finally:
        pa.close()
    assert len(calls) >= 2
    assert pa.stat_armed >= 1


# -- Executor contracts: static parity + plan application ----------------


def _executor_env(tmp_path, rows=5, bits=60):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    rng = np.random.default_rng(7)
    rids, cids = [], []
    for r in range(rows):
        for c in rng.choice(2 * SLICE_WIDTH, size=bits, replace=False):
            rids.append(r)
            cids.append(int(c))
    fr.import_bits(rids, cids)
    return h, Executor(h, engine="numpy")


# Batches of >=2 fused counts: the planner arbitrates the compiled
# fused lane's strategy families; singleton Counts ride the AST path,
# which it leaves alone.
def _pairs(*ab):
    return " ".join(
        f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for op, a, b in ab
    )


_QUERIES = [
    _pairs(("Intersect", 0, 1), ("Union", 1, 2)),
    _pairs(("Difference", 3, 4), ("Xor", 0, 3)),
    _pairs(("Intersect", 2, 4), ("Intersect", 0, 4)),
    _pairs(("Union", 0, 4), ("Difference", 1, 3), ("Xor", 2, 3)),
]


def test_empty_ledger_planner_reproduces_static_decisions(tmp_path):
    """The ISSUE's tier-1 smoke: an empty-ledger planner emits lane-None
    plans (below the explore cadence) and the executor treats them
    exactly like no plan at all — identical results, and the observed
    costs still fold back under the lane the static ladder ran."""
    h, e = _executor_env(tmp_path)
    try:
        baseline = [e.execute("i", q) for q in _QUERIES]
        led = CostLedger()
        pl = Planner(led, explore_every=1000)
        e.planner = pl
        for q, want in zip(_QUERIES, baseline):
            plan = pl.plan_for("i", q.encode())
            assert plan["lane"] is None and plan["src"] == "static"
            got = e.execute("i", q, opt=ExecOptions(plan=plan))
            assert got == want
        # Static-plan dispatches still feed the ledger (frame "" —
        # strategy choice is per request shape).
        folds = sum(ent["n"] for ent in led.entries())
        assert folds >= len(_QUERIES)
        assert all(ent["lane"] in PLAN_LANES and ent["frame"] == ""
                   for ent in led.entries())
    finally:
        h.close()


def test_forced_lane_plans_match_and_record_actual_lane(tmp_path):
    """Pinned plans force a strategy family; results stay identical and
    every dispatch folds back under the lane that ACTUALLY ran (an
    eligibility veto records the fallback, not the pick)."""
    h, e = _executor_env(tmp_path)
    try:
        baseline = [e.execute("i", q) for q in _QUERIES]
        for pin in PLAN_LANES:
            led = CostLedger()
            pl = Planner(led, pin=pin)
            e.planner = pl
            for q, want in zip(_QUERIES, baseline):
                plan = pl.plan_for("i", q.encode())
                assert plan["src"] == "pinned" and plan["lane"] == pin
                assert e.execute("i", q, opt=ExecOptions(plan=plan)) == want
            ents = led.entries()
            assert sum(ent["n"] for ent in ents) >= len(_QUERIES)
            assert all(ent["lane"] in PLAN_LANES for ent in ents)
            if pin == "gram":
                # The slice-major family is always feasible, so a gram
                # pin records as gram everywhere.
                assert {ent["lane"] for ent in ents} == {"gram"}
            # Pinned decisions are scored win/loss.
            snap = pl.snapshot()
            assert sum(k["wins"] + k["losses"] for k in snap["keys"]) >= len(_QUERIES)
    finally:
        h.close()


def test_door_loop_converges_on_ledger_decisions(tmp_path):
    """Front-door loop (plan_for -> execute) on one hot fingerprint:
    consults accumulate, folds land, and once every lane has evidence
    the decisions come from the ledger, not the static ladder."""
    h, e = _executor_env(tmp_path)
    try:
        led = CostLedger()
        pl = Planner(led, min_samples=2, explore_every=4)
        e.planner = pl
        q = _QUERIES[0]
        for _ in range(24):
            plan = pl.plan_for("i", q.encode())
            e.execute("i", q, opt=ExecOptions(plan=plan))
        snap = pl.snapshot()
        (key,) = snap["keys"]
        assert key["consults"] == 24
        assert sum(key["decided"].values()) == 24
        # The actually-run lane has converged evidence; if both lanes
        # gathered min_samples (host-dependent — a vetoed rmgather
        # explore folds as gram), ledger-src decisions must appear.
        lanes = key["lanes"]
        assert lanes and max(v["n"] for v in lanes.values()) >= 2
        if all(lanes.get(ln, {}).get("n", 0) >= 2 for ln in PLAN_LANES):
            assert key["decided"].get("ledger", 0) > 0
    finally:
        h.close()


def test_debug_planner_endpoint(tmp_path, monkeypatch):
    """/debug/planner (satellite d): the server consults its planner per
    query and serves the decision snapshot."""
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.client import Client
    from pilosa_tpu.server.server import Server

    monkeypatch.setenv("PILOSA_TPU_COSTS", "1")
    cfg = Config(data_dir=str(tmp_path / "dp"), host="127.0.0.1:0", engine="numpy")
    s = Server(cfg)
    s.open()
    try:
        assert s.planner is not None
        c = Client(s.host)
        c.create_index("dp")
        c.create_frame("dp", "f")
        c.execute_query("dp", 'SetBit(rowID=0, frame="f", columnID=1) '
                              'SetBit(rowID=1, frame="f", columnID=1)')
        q = ('Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
             'Count(Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))')
        for _ in range(3):
            resp = c.execute_query("dp", q)
            assert resp["results"] == [{"n": 1}, {"n": 1}]
        with urllib.request.urlopen(f"http://{s.host}/debug/planner", timeout=30) as r:
            snap = json.loads(r.read())
        assert snap["lanes"] == list(PLAN_LANES)
        want_fp = fingerprint(q.encode())["fp"]
        by_fp = {k["fp"]: k for k in snap["keys"]}
        assert by_fp[want_fp]["consults"] >= 3
        assert {"incumbent", "decided", "wins", "losses", "confidence"} <= set(by_fp[want_fp])
    finally:
        s.close()
