"""Subprocess serving-group worker for the replica-router bench/tests:
one full Server (numpy engine by default) = one replica group front
door, in its own process so groups scale across GILs the way real
groups scale across jobs.

Run: python tests/replica_group_worker.py <group-name> [engine]

Prints ``{"ready": true, "host": ..., "group": ...}`` once serving,
shuts down when a line arrives on stdin.  The qcache is DISABLED so
read phases measure real execution scaling, not cache hits
(PILOSA_TPU_QCACHE=1 in the environment turns it back on).
"""

import json
import os
import sys
import tempfile


def main() -> int:
    group = sys.argv[1] if len(sys.argv) > 1 else "g0"
    engine = sys.argv[2] if len(sys.argv) > 2 else "numpy"

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    qcache_on = os.environ.get("PILOSA_TPU_QCACHE", "").lower() in ("1", "true", "yes")
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(
            data_dir=d,
            host="127.0.0.1:0",
            engine=engine,
            stats="expvar",
            qcache_enabled=qcache_on,
            replica_group=group,
        )
        srv = Server(cfg)
        srv.open()
        print(json.dumps({"ready": True, "host": srv.host, "group": group}), flush=True)
        sys.stdin.readline()  # parent signals shutdown
        srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
