"""Subprocess serving-group worker for the replica-router bench/tests:
one full Server (numpy engine by default) = one replica group front
door, in its own process so groups scale across GILs the way real
groups scale across jobs.

Run: python tests/replica_group_worker.py <group-name[@epoch]> [engine]

Prints ``{"ready": true, "host": ..., "group": ...}`` once serving,
shuts down when a line arrives on stdin.  The qcache is DISABLED so
read phases measure real execution scaling, not cache hits
(PILOSA_TPU_QCACHE=1 in the environment turns it back on).

RESTARTABLE groups (the recovery bench / crash tests): set
``PILOSA_WORKER_DATA_DIR`` to pin the holder (and the persisted
applied-sequence mark) to a fixed directory — a re-spawned worker with
the same dir and a bumped ``name@epoch`` resumes from its on-disk
state and reports its applied sequence, so the router replays exactly
the missed WAL suffix.  Without the env a temp dir is used (the
original throw-away behavior).
"""

import json
import os
import sys
import tempfile


def _serve(data_dir: str, group: str, engine: str, qcache_on: bool) -> None:
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=data_dir,
        # PILOSA_WORKER_HOST pins the front-door address so a restarted
        # incarnation is reachable at the SAME base the router holds.
        host=os.environ.get("PILOSA_WORKER_HOST", "127.0.0.1:0"),
        engine=engine,
        stats="expvar",
        qcache_enabled=qcache_on,
        replica_group=group,
    )
    srv = Server(cfg)
    srv.open()
    print(json.dumps({"ready": True, "host": srv.host, "group": group}), flush=True)
    sys.stdin.readline()  # parent signals shutdown
    srv.close()


def main() -> int:
    group = sys.argv[1] if len(sys.argv) > 1 else "g0"
    engine = sys.argv[2] if len(sys.argv) > 2 else "numpy"

    qcache_on = os.environ.get("PILOSA_TPU_QCACHE", "").lower() in ("1", "true", "yes")
    pinned = os.environ.get("PILOSA_WORKER_DATA_DIR", "")
    if pinned:
        os.makedirs(pinned, exist_ok=True)
        _serve(pinned, group, engine, qcache_on)
    else:
        with tempfile.TemporaryDirectory() as d:
            _serve(d, group, engine, qcache_on)
    return 0


if __name__ == "__main__":
    sys.exit(main())
