"""Automated group resync + cross-group anti-entropy (PR 9).

The invariants pinned here:

- A group marked STALE (the WAL compacted past its lag) and a group
  started on a BLANK data dir both return to healthy ∧ caught_up ∧
  ¬stale with ZERO operator action: the probe (which now keeps
  visiting stale groups at probe-max-interval) drives a resync round —
  digest diff against a healthy donor, differing fragments streamed as
  serialized roaring payloads, applied-seq seeded under the sequencer
  lock, WAL catch-up for the final drain — and reads served by the
  rejoined group reflect every acked write.
- The fragment stream is chunked, CRC-framed, and RESUMABLE: a seeded
  fault killing the transfer mid-stream aborts the round, and the next
  round resumes from the staged offset instead of restarting.
- Donor death mid-stream and a fault before the seed-seq handoff abort
  safely and the retry converges (partial progress is kept).
- A deliberately-diverged fragment is detected by the anti-entropy
  sweep (``replica.divergence.<g>`` increments + one structured
  ``pilosa_tpu.divergence`` log line), repaired to digest equality
  from the MAJORITY copy.
- Digest determinism: same logical bits through different write paths
  produce identical digests (the deterministic twins of the hypothesis
  properties in test_fragment_stateful.py).
- Satellites: stale groups stay in the probe rotation; non-quorate
  write 503s carry jittered Retry-After; config promotion for
  [replica] anti-entropy-interval / resync-chunk-bytes.
"""

import io
import json
import logging
import os
import shutil
import socket
import tempfile
import time
import urllib.error
import urllib.request
import zlib

import pytest

from pilosa_tpu.config import Config
from pilosa_tpu.replica import GROUP_HEADER, ReplicaRouter
from pilosa_tpu.replica.digest import (
    diff_digests,
    fragment_path,
    holder_digest,
    majority_plan,
)
from pilosa_tpu.replica.faults import FaultInjector
from pilosa_tpu.replica.wal import WriteAheadLog
from pilosa_tpu.stats import ExpvarStatsClient


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Rig:
    """Three in-process group Servers on FIXED ports + a router whose
    resync knobs the test controls."""

    def __init__(self, tmp, wal=None, faults=None, probe_interval_s=0.05,
                 probe_max_interval_s=0.3, n=3, **router_kw):
        self.tmp = tmp
        self.ports = [_free_port() for _ in range(n)]
        self.servers = [self._spawn(i, 1) for i in range(n)]
        self.stats = ExpvarStatsClient()
        self.router = ReplicaRouter(
            [f"g{i}=127.0.0.1:{p}" for i, p in enumerate(self.ports)],
            probe_interval_s=probe_interval_s,
            probe_max_interval_s=probe_max_interval_s,
            wal=wal, faults=faults, stats=self.stats, **router_kw,
        ).serve()
        self.base = f"http://127.0.0.1:{self.router.port}"

    def _spawn(self, i: int, epoch: int):
        from pilosa_tpu.server.server import Server

        cfg = Config(
            data_dir=f"{self.tmp}/g{i}", host=f"127.0.0.1:{self.ports[i]}",
            engine="numpy", stats="expvar", qcache_enabled=False,
            replica_group=f"g{i}@{epoch}",
        )
        srv = Server(cfg)
        srv.open()
        return srv

    def restart(self, i: int, epoch: int, blank: bool = False):
        if blank:
            shutil.rmtree(f"{self.tmp}/g{i}", ignore_errors=True)
        self.servers[i] = self._spawn(i, epoch)

    def req(self, method, path, body=None, headers=None, timeout=30, port=None):
        base = self.base if port is None else f"http://127.0.0.1:{port}"
        rq = urllib.request.Request(base + path, data=body, method=method)
        for k, v in (headers or {}).items():
            rq.add_header(k, v)
        try:
            with urllib.request.urlopen(rq, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def query(self, q, headers=None):
        return self.req("POST", "/index/i/query", q.encode(), headers)

    def direct_count(self, i, q='Count(Bitmap(rowID=1, frame="f"))'):
        st, body, _ = self.req("POST", "/index/i/query", q.encode(),
                               port=self.ports[i])
        assert st == 200, body
        return json.loads(body)["results"][0]

    def direct_digest(self, i) -> dict:
        st, body, _ = self.req("GET", "/replica/digest", port=self.ports[i])
        assert st == 200, body
        return json.loads(body)

    def status(self) -> dict:
        return json.loads(self.req("GET", "/replica/status")[1])

    def group_status(self, name: str) -> dict:
        return next(g for g in self.status()["groups"] if g["name"] == name)

    def seed(self):
        assert self.req("POST", "/index/i", b"{}")[0] == 200
        assert self.req("POST", "/index/i/frame/f", b"{}")[0] == 200

    def wait_ready(self, name: str, timeout=20.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            g = self.group_status(name)
            if g["healthy"] and g["caughtUp"] and not g["stale"]:
                return g
            time.sleep(0.05)
        raise AssertionError(f"group {name} never rejoined: {self.group_status(name)}")

    def close(self):
        self.router.close()
        for s in self.servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — already dead
                pass


@pytest.fixture
def rig():
    with tempfile.TemporaryDirectory() as tmp:
        r = _Rig(tmp)
        try:
            yield r
        finally:
            r.close()


# -- digest protocol ----------------------------------------------------------


def test_holder_digest_deterministic_across_write_paths(tmp_path):
    """The same logical bits through set_bit order A, set_bit order B,
    and a bulk import digest identically — the deterministic twin of
    the hypothesis property (anti-entropy correctness rests on it)."""
    import numpy as np

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder

    bits = [(1, 3), (1, 77), (2, 9), (5, 200000), (1, 65536)]
    digests = []
    for k, order in enumerate((bits, bits[::-1], None)):
        h = Holder(str(tmp_path / f"h{k}"))
        h.open()
        idx = h.create_index("i")
        idx.create_frame("f", FrameOptions())
        if order is None:
            frag = (
                idx.frame("f").create_view_if_not_exists("standard")
                .create_fragment_if_not_exists(0)
            )
            frag.import_bits(
                np.asarray([b[0] for b in bits], dtype=np.uint64),
                np.asarray([b[1] for b in bits], dtype=np.uint64),
            )
        else:
            for r, c in order:
                idx.frame("f").set_bit("standard", r, c)
        digests.append(holder_digest(h))
        h.close()
    assert digests[0]["digest"] == digests[1]["digest"] == digests[2]["digest"]
    assert digests[0]["fragments"] == digests[1]["fragments"]
    assert list(digests[0]["fragments"]) == [fragment_path("i", "f", "standard", 0)]


def test_holder_digest_omits_empty_fragments(tmp_path):
    """'Never created' and 'cleared to zero bits' digest identically —
    clearing a divergent extra fragment must converge the digests."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder

    h1 = Holder(str(tmp_path / "a"))
    h1.open()
    h1.create_index("i").create_frame("f", FrameOptions())
    h2 = Holder(str(tmp_path / "b"))
    h2.open()
    h2.create_index("i").create_frame("f", FrameOptions())
    h2.index("i").frame("f").set_bit("standard", 1, 3)
    assert holder_digest(h1)["digest"] != holder_digest(h2)["digest"]
    h2.index("i").frame("f").clear_bit("standard", 1, 3)
    assert holder_digest(h1)["digest"] == holder_digest(h2)["digest"]
    assert holder_digest(h2)["fragments"] == {}
    h1.close()
    h2.close()


def test_diff_digests_plan():
    donor = {
        "schema": [{"name": "i", "frames": [{"name": "f"}, {"name": "g"}]}],
        "fragments": {"i/f/standard/0": "aa", "i/g/standard/1": "bb"},
    }
    laggard = {
        "schema": [
            {"name": "i", "frames": [{"name": "f"}, {"name": "dead"}]},
            {"name": "old", "frames": [{"name": "x"}]},
        ],
        "fragments": {
            "i/f/standard/0": "MISMATCH",
            "i/f/standard/7": "extra-in-live-frame",
            "i/dead/standard/0": "cc",
            "old/x/standard/0": "dd",
        },
    }
    plan = diff_digests(donor, laggard)
    # Differing + donor-missing fragments stream; extras inside frames
    # the donor keeps stream too (as clears); extras under dropped
    # indexes/frames are handled by the deletes instead.
    assert plan.stream == ["i/f/standard/0", "i/g/standard/1", "i/f/standard/7"]
    assert plan.drop_indexes == ["old"]
    assert plan.drop_frames == [("i", "dead")]


def test_majority_plan_winner_and_ties():
    digs = {
        "g0": {"fragments": {"i/f/standard/0": "aa", "i/f/standard/1": "xx"}},
        "g1": {"fragments": {"i/f/standard/0": "aa"}},
        "g2": {"fragments": {"i/f/standard/0": "zz", "i/f/standard/1": "xx"}},
    }
    plan = majority_plan(digs)
    # Path 0: majority 'aa' -> repair g2 from g0 (smallest holder).
    # Path 1: 'xx' on g0+g2 vs missing on g1 -> repair g1 from g0.
    assert plan.divergent == {"g2": ["i/f/standard/0"], "g1": ["i/f/standard/1"]}
    assert plan.donor == {"i/f/standard/0": "g0", "i/f/standard/1": "g0"}
    assert plan.first_path == "i/f/standard/0"
    # All-equal digests -> empty plan.
    same = {n: {"fragments": {"p": "aa"}} for n in ("g0", "g1")}
    assert majority_plan(same).divergent == {}
    # Majority LACKING the fragment wins: the holder gets a clear.
    lack = {
        "g0": {"fragments": {}},
        "g1": {"fragments": {"p": "aa"}},
        "g2": {"fragments": {}},
    }
    plan = majority_plan(lack)
    assert plan.divergent == {"g1": ["p"]} and plan.donor == {"p": "g0"}


def test_digest_endpoint_reports_applied_seq(rig):
    rig.seed()
    rig.query('SetBit(rowID=1, frame="f", columnID=3)')
    dig = rig.direct_digest(0)
    assert dig["appliedSeq"] >= 1
    assert "i/f/standard/0" in dig["fragments"]
    assert [x["name"] for x in dig["schema"]] == ["i"]
    # All three groups applied the same writes: identical digests.
    assert dig["digest"] == rig.direct_digest(1)["digest"] == rig.direct_digest(2)["digest"]


# -- import-roaring endpoint --------------------------------------------------


def test_import_roaring_crc_mismatch_and_overrun(rig):
    rig.seed()
    data = b"not-a-roaring-payload-but-crc-checked-first"
    total = len(data)
    bad_crc = zlib.crc32(data) ^ 1
    base = (f"/fragment/import-roaring?index=i&frame=f&view=standard&slice=0"
            f"&total={total}&crc={bad_crc}")
    st, body, _ = rig.req("POST", base + "&off=0", data, port=rig.ports[0])
    assert st == 409 and b"crc mismatch" in body
    # The failed transfer left no staging behind.
    st, body, _ = rig.req("POST", base + "&probe=1", b"", port=rig.ports[0])
    assert st == 200 and json.loads(body)["staged"] == 0
    # A chunk overrunning the declared total is refused.
    good = zlib.crc32(data)
    base = (f"/fragment/import-roaring?index=i&frame=f&view=standard&slice=0"
            f"&total=4&crc={good}")
    st, body, _ = rig.req("POST", base + "&off=0", data, port=rig.ports[0])
    assert st == 409 and b"overruns" in body


def test_import_roaring_clear_and_idempotent_apply(rig):
    rig.seed()
    assert rig.query('SetBit(rowID=1, frame="f", columnID=3)')[0] == 200
    assert rig.direct_count(0) == 1
    # total=0 clears the fragment.
    base = ("/fragment/import-roaring?index=i&frame=f&view=standard&slice=0"
            "&total=0&crc=0")
    st, body, _ = rig.req("POST", base + "&off=0", b"", port=rig.ports[0])
    assert st == 200 and json.loads(body)["applied"] is True
    assert rig.direct_count(0) == 0
    # Applying the same payload twice converges to the same bytes.
    st, data, _ = rig.req(
        "GET", "/fragment/data?index=i&frame=f&view=standard&slice=0",
        port=rig.ports[1])
    assert st == 200
    total, crc = len(data), zlib.crc32(data)
    base = (f"/fragment/import-roaring?index=i&frame=f&view=standard&slice=0"
            f"&total={total}&crc={crc}")
    for _ in range(2):
        st, body, _ = rig.req(
            "POST", base + "&off=0", data, port=rig.ports[0],
            headers={"Content-Type": "application/octet-stream"})
        assert st == 200 and json.loads(body)["applied"] is True
    assert rig.direct_count(0) == 1
    assert rig.direct_digest(0)["digest"] == rig.direct_digest(1)["digest"]


def test_import_roaring_creates_missing_path(rig):
    """The blank-group path: index/frame/view/fragment are created on
    demand by the import lane."""
    buf = io.BytesIO()
    from pilosa_tpu import roaring

    bm = roaring.Bitmap([5])
    bm.write_to(buf)
    data = buf.getvalue()
    base = (f"/fragment/import-roaring?index=fresh&frame=nf&view=standard"
            f"&slice=0&total={len(data)}&crc={zlib.crc32(data)}")
    st, body, _ = rig.req("POST", base + "&off=0", data, port=rig.ports[0],
                          headers={"Content-Type": "application/octet-stream"})
    assert st == 200 and json.loads(body)["applied"] is True
    st, body, _ = rig.req("POST", "/index/fresh/query",
                          b'Count(Bitmap(rowID=0, frame="nf"))',
                          port=rig.ports[0])
    assert st == 200 and json.loads(body)["results"] == [1]


# -- the acceptance scenarios -------------------------------------------------


def _spread_writes(rig, n, start=0, per_write=1):
    for k in range(start, start + n):
        q = " ".join(
            f'SetBit(rowID={1 + (k % 3)}, frame="f", columnID={k * per_write + j})'
            for j in range(per_write)
        )
        st, body, _ = rig.query(q)
        assert st == 200, (k, body)


def test_blank_group_self_heals(rig):
    """THE blank half of the acceptance scenario: a group restarted on
    a WIPED data dir (applied_seq=0 over a non-empty sequence space)
    is resynced by fragment stream + seed + catch-up, with zero
    operator action, and serves reads reflecting every acked write."""
    rig.seed()
    _spread_writes(rig, 12)
    rig.servers[2].close()
    _spread_writes(rig, 6, start=12)  # writes the blank group must NOT lose
    rig.restart(2, epoch=2, blank=True)
    g2 = rig.wait_ready("g2")
    assert g2["appliedSeq"] == rig.status()["wal"]["lastSeq"]
    # Every acked write is readable from the rejoined group directly.
    want = [rig.direct_count(0, f'Count(Bitmap(rowID={r}, frame="f"))')
            for r in (1, 2, 3)]
    got = [rig.direct_count(2, f'Count(Bitmap(rowID={r}, frame="f"))')
          for r in (1, 2, 3)]
    assert got == want and sum(want) == 18
    # Byte-identical: digests agree everywhere.
    assert (rig.direct_digest(0)["digest"] == rig.direct_digest(1)["digest"]
            == rig.direct_digest(2)["digest"])
    snap = rig.stats.snapshot()
    assert snap.get("replica.resync.g2", 0) >= 1
    assert snap.get("replica.resync_fragments", 0) >= 1
    assert snap.get("replica.resync_bytes", 0) > 0
    # And reads route to it again.
    served = set()
    for _ in range(9):
        st, _b, hdrs = rig.query('Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200
        served.add(hdrs.get(GROUP_HEADER, "").split("@")[0])
    assert "g2" in served


def test_stale_group_self_heals(tmp_path):
    """THE stale half: a group whose lag pinned the WAL past
    wal-max-bytes goes stale (the log compacts past it), stays in the
    probe rotation at probe-max-interval, and is resynced back to
    healthy ∧ caught_up ∧ ¬stale with zero operator action."""
    wal = WriteAheadLog(str(tmp_path / "r.wal"), max_bytes=70_000)
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, wal=wal)
        try:
            rig.seed()
            _spread_writes(rig, 3)
            rig.servers[2].close()
            # Big write bodies blow the WAL past its bound while g2 is
            # down: compaction can't advance past g2's lag -> stale.
            _spread_writes(rig, 40, start=3, per_write=50)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if rig.group_status("g2")["stale"]:
                    break
                time.sleep(0.05)
            assert rig.group_status("g2")["stale"], rig.status()
            assert rig.stats.snapshot().get("replica.stale.g2", 0) >= 1
            # The stale group's missed records are (at least partly)
            # compacted away: replay alone cannot rescue it.
            rig.restart(2, epoch=2)
            g2 = rig.wait_ready("g2")
            assert not g2["stale"] and g2["appliedSeq"] == rig.status()["wal"]["lastSeq"]
            want = rig.direct_count(0, 'Count(Bitmap(rowID=1, frame="f"))')
            assert rig.direct_count(2, 'Count(Bitmap(rowID=1, frame="f"))') == want
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
            snap = rig.stats.snapshot()
            assert snap.get("replica.resync.g2", 0) >= 1
        finally:
            rig.close()


def test_torn_transfer_resumes_mid_fragment(tmp_path):
    """A seeded fault kills the chunk stream mid-fragment: the round
    aborts, the next round RESUMES from the staged offset (proven by
    replica.resync_bytes < the fragment's full size), and the group
    still converges."""
    faults = FaultInjector.from_spec("resync.chunk/g2:drop@4")
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, faults=faults, resync_chunk_bytes=64)
        try:
            rig.seed()
            _spread_writes(rig, 10, per_write=8)  # a multi-chunk fragment
            rig.servers[2].close()
            rig.query('SetBit(rowID=1, frame="f", columnID=999)')
            st, data, _ = rig.req(
                "GET", "/fragment/data?index=i&frame=f&view=standard&slice=0",
                port=rig.ports[0])
            assert st == 200 and len(data) > 4 * 64  # > 4 chunks
            rig.restart(2, epoch=2, blank=True)
            rig.wait_ready("g2")
            snap = rig.stats.snapshot()
            assert snap.get("replica.resync_abort", 0) >= 1  # round 1 died
            # The successful round pushed only the remainder: resumed,
            # not restarted.
            assert 0 < snap.get("replica.resync_bytes", 0) < len(data)
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
        finally:
            rig.close()


def test_donor_death_mid_stream_retries(tmp_path):
    """The donor's fragment fetch dies on the first round; the retry
    picks up and converges (drop@1 fires exactly once)."""
    faults = FaultInjector.from_spec("resync.fetch/g0:drop@1")
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, faults=faults)
        try:
            rig.seed()
            _spread_writes(rig, 8)
            rig.servers[2].close()
            rig.query('SetBit(rowID=1, frame="f", columnID=500)')
            rig.restart(2, epoch=2, blank=True)
            rig.wait_ready("g2")
            snap = rig.stats.snapshot()
            assert snap.get("replica.resync_abort", 0) >= 1
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
        finally:
            rig.close()


def test_fault_before_seed_retries_and_converges(tmp_path):
    """Crash-before-seed ordering: the stream completes but the round
    dies before the applied-seq handoff.  Nothing is lost — the next
    round finds the fragments already equal (digest diff empty),
    seeds, and the group rejoins fully caught up."""
    faults = FaultInjector.from_spec("resync.seed/g2:drop@1")
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, faults=faults)
        try:
            rig.seed()
            _spread_writes(rig, 8)
            rig.servers[2].close()
            rig.query('SetBit(rowID=1, frame="f", columnID=501)')
            rig.restart(2, epoch=2, blank=True)
            g2 = rig.wait_ready("g2")
            snap = rig.stats.snapshot()
            assert snap.get("replica.resync_abort", 0) >= 1
            assert snap.get("replica.resync_rounds", 0) >= 2
            assert g2["appliedSeq"] == rig.status()["wal"]["lastSeq"]
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
        finally:
            rig.close()


def test_no_failed_writes_during_resync(tmp_path):
    """Writes keep committing while a blank group resyncs — the stream
    runs outside the sequencer lock except for the bounded seed."""
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp)
        try:
            rig.seed()
            _spread_writes(rig, 10, per_write=4)
            rig.servers[2].close()
            rig.restart(2, epoch=2, blank=True)
            # Write continuously until the group rejoins.
            failed, k = 0, 0
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                st, _b, _h = rig.query(
                    f'SetBit(rowID=9, frame="f", columnID={k})')
                k += 1
                if st != 200:
                    failed += 1
                g2 = rig.group_status("g2")
                if g2["healthy"] and g2["caughtUp"] and not g2["stale"]:
                    break
            else:
                raise AssertionError("g2 never rejoined while writing")
            assert failed == 0 and k > 0
            # The rejoined group holds every write acked during resync.
            assert rig.direct_count(
                2, 'Count(Bitmap(rowID=9, frame="f"))') == k
        finally:
            rig.close()


def test_mixed_4xx_write_marks_suspect_and_resyncs(tmp_path):
    """A group answering 4xx to a write a sibling APPLIED is content-
    divergent (a blank restart 404s the index every sibling holds) —
    PR 7 silently counted that 'deterministic' and advanced its
    applied mark.  It is now marked SUSPECT, pulled from rotation, and
    digest-verified by the probe: mismatch drives a resync round."""
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp)
        try:
            rig.seed()
            _spread_writes(rig, 5)
            # Blank-restart g2 QUIETLY: the router still believes it is
            # healthy and caught up, so the next write fans to it and
            # gets 400 index-not-found while g0/g1 answer 200.
            rig.servers[2].close()
            rig.restart(2, epoch=2, blank=True)
            st, _b, _h = rig.query('SetBit(rowID=1, frame="f", columnID=50)')
            assert st == 200  # majority applied: the write commits
            snap = rig.stats.snapshot()
            assert snap.get("replica.suspect.g2", 0) >= 1
            rig.wait_ready("g2")
            snap = rig.stats.snapshot()
            assert snap.get("replica.divergence.g2", 0) >= 1
            assert snap.get("replica.resync.g2", 0) >= 1
            assert not rig.group_status("g2")["suspect"]
            want = rig.direct_count(0, 'Count(Bitmap(rowID=1, frame="f"))')
            assert rig.direct_count(2, 'Count(Bitmap(rowID=1, frame="f"))') == want
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
        finally:
            rig.close()


def test_retried_create_clears_suspect_without_resync(rig):
    """The benign mixed-4xx case: an idempotent client retry of a
    create answers 409 on groups that already applied it and 200 on
    one that missed it.  The 409 groups go suspect, the digest check
    finds them EQUAL to the donor, and the flag clears with no resync
    round (no fragment ever moved)."""
    rig.seed()
    # g0 already holds f2 (e.g. the surviving half of a partially
    # applied create the client is about to retry).
    assert rig.req("POST", "/index/i/frame/f2", b"{}", port=rig.ports[0])[0] == 200
    # The routed (re)create: g0 answers 409, g1/g2 answer 200 — mixed,
    # so g0 goes suspect even though it is the one that was RIGHT.
    st, _b, _h = rig.req("POST", "/index/i/frame/f2", b"{}")
    assert st == 200
    snap = rig.stats.snapshot()
    assert snap.get("replica.suspect.g0", 0) >= 1
    rig.wait_ready("g0")
    snap = rig.stats.snapshot()
    assert snap.get("replica.suspect_cleared", 0) >= 1
    assert snap.get("replica.resync_fragments", 0) == 0  # nothing streamed
    for name in ("g0", "g1", "g2"):
        assert not rig.group_status(name)["suspect"]


# -- anti-entropy -------------------------------------------------------------


def test_anti_entropy_detects_and_repairs_divergence(rig, caplog):
    """A deliberately-diverged fragment (a write slipped into one group
    behind the router's back) is detected by the sweep
    (replica.divergence.<g> increments, one structured divergence log
    line) and repaired to digest equality from the majority copy."""
    rig.seed()
    _spread_writes(rig, 6)
    # Sneak a divergent bit straight into g1 (bypassing the router).
    st, _b, _h = rig.req("POST", "/index/i/query",
                         b'SetBit(rowID=1, frame="f", columnID=77777)',
                         port=rig.ports[1])
    assert st == 200
    want = rig.direct_count(0, 'Count(Bitmap(rowID=1, frame="f"))')
    assert rig.direct_count(1, 'Count(Bitmap(rowID=1, frame="f"))') == want + 1
    with caplog.at_level(logging.WARNING, logger="pilosa_tpu.divergence"):
        rig.router._anti_entropy_once()
    snap = rig.stats.snapshot()
    assert snap.get("replica.divergence.g1", 0) == 1
    assert snap.get("replica.divergence_repaired", 0) >= 1
    assert snap.get("replica.antientropy_rounds", 0) == 1
    # Structured log line names the first differing fragment path.
    rec = next(r for r in caplog.records if r.name == "pilosa_tpu.divergence")
    payload = json.loads(rec.getMessage().split(" ", 1)[1])
    assert payload["groups"] == ["g1"]
    assert payload["first_path"] == "i/f/standard/0"
    # Repaired to the majority copy: the sneaked bit is gone and all
    # digests agree again.
    assert rig.direct_count(1, 'Count(Bitmap(rowID=1, frame="f"))') == want
    assert (rig.direct_digest(0)["digest"] == rig.direct_digest(1)["digest"]
            == rig.direct_digest(2)["digest"])
    # A second sweep is clean: no new divergence counted.
    rig.router._anti_entropy_once()
    snap = rig.stats.snapshot()
    assert snap.get("replica.divergence.g1", 0) == 1
    assert snap.get("replica.antientropy_rounds", 0) == 2


def test_anti_entropy_interval_starts_background_loop(tmp_path):
    """With [replica] anti-entropy-interval set the router runs the
    sweep in the background (jittered) — divergence self-heals with no
    operator call either."""
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, anti_entropy_interval_s=0.2)
        try:
            rig.seed()
            _spread_writes(rig, 3)
            st, _b, _h = rig.req("POST", "/index/i/query",
                                 b'SetBit(rowID=2, frame="f", columnID=88888)',
                                 port=rig.ports[2])
            assert st == 200
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if rig.stats.snapshot().get("replica.divergence.g2", 0) >= 1:
                    break
                time.sleep(0.05)
            snap = rig.stats.snapshot()
            assert snap.get("replica.divergence.g2", 0) >= 1
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if (rig.direct_digest(2)["digest"]
                        == rig.direct_digest(0)["digest"]):
                    break
                time.sleep(0.05)
            assert (rig.direct_digest(2)["digest"]
                    == rig.direct_digest(0)["digest"])
        finally:
            rig.close()


# -- satellites ---------------------------------------------------------------


def test_stale_group_stays_in_probe_rotation():
    """PR 7 dropped stale groups from the probe loop forever; they now
    keep being probed at probe-max-interval, so resync (and a
    hand-resynced group) has a live door back in."""
    router = ReplicaRouter(
        ["g0=127.0.0.1:1"], probe_interval_s=0.05, probe_max_interval_s=0.2,
        stats=ExpvarStatsClient(),
    )
    g = router.groups[0]
    g.healthy = False
    g.stale = True
    g.probe_at = 0.0
    g.probe_delay = 0.0
    router._probe_once()  # unreachable -> backoff; but it WAS probed
    assert g.probe_delay > 0  # pre-PR: stale groups never entered `due`
    assert g.probe_delay <= router.probe_max_interval_s
    router.close()


def test_going_stale_arms_probe_at_max_interval(tmp_path):
    """Marking a group stale schedules its next probe at the max
    interval (not the tight unhealthy cadence, and not never)."""
    wal = WriteAheadLog(str(tmp_path / "w.wal"), max_bytes=1024, fsync=False)
    router = ReplicaRouter(
        ["g0=127.0.0.1:1", "g1=127.0.0.1:2"],
        probe_max_interval_s=7.5, wal=wal, stats=ExpvarStatsClient(),
    )
    g0, g1 = router.groups
    for k in range(40):
        # Past the 64 KiB compaction floor AND the 1 KiB bound.
        seq = wal.append("POST", "/index/i/query", b"x" * 2048)
        g0.applied_seq = seq  # g0 keeps up; g1 stuck at 0
    router._maybe_compact()
    assert g1.stale and not g0.stale
    assert g1.probe_delay == router.probe_max_interval_s
    assert g1.probe_at > time.monotonic()
    router.close()


def test_nonquorate_write_retry_after_is_jittered():
    """The 503 a non-quorate write gets carries a JITTERED Retry-After
    (decorrelated, mirroring the client retry budget) so a client herd
    doesn't retry in lockstep against a recovering cluster."""
    router = ReplicaRouter(["g0=127.0.0.1:1"], stats=ExpvarStatsClient())
    router.groups[0].healthy = False  # not quorate
    seen = set()
    for _ in range(12):
        status, _ct, _body, extra = router.handle(
            "POST", "/index/i/query",
            b'SetBit(rowID=1, frame="f", columnID=1)', {})
        assert status == 503
        ra = float(extra["Retry-After"])
        assert 0.45 <= ra <= 1.55  # uniform(0.5x, 1.5x) of the 1.0 hint
        seen.add(ra)
    assert len(seen) > 1  # not a fixed value
    router.close()


def test_resync_needed_and_covered_rules(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "w.wal"), fsync=False)
    router = ReplicaRouter(
        ["g0=127.0.0.1:1", "g1=127.0.0.1:2"], wal=wal,
        stats=ExpvarStatsClient(),
    )
    rs = router.resync
    g = router.groups[1]
    assert not rs.needed(g)  # empty log: nothing to converge
    for _ in range(10):
        wal.append("POST", "/p", b"x")
    g.applied_seq = 0
    assert rs.needed(g)  # blank over a non-empty sequence space
    g.applied_seq = 4
    assert rs.covered(g) and not rs.needed(g)  # replay suffices
    wal.compact(6)  # records 1..6 gone
    assert not rs.covered(g) and rs.needed(g)  # gap no longer covered
    g.applied_seq = 6
    assert rs.covered(g)
    g.stale = True
    assert rs.needed(g)  # stale always resyncs
    router.close()


def test_config_promotion_resync(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        "[replica]\n"
        'anti-entropy-interval = "90s"\n'
        "resync-chunk-bytes = 1024\n"
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.replica_anti_entropy_interval == 90.0
    assert cfg.replica_resync_chunk_bytes == 1024
    cfg.apply_env({
        "PILOSA_TPU_REPLICA_ANTI_ENTROPY_INTERVAL": "5.5",
        "PILOSA_TPU_REPLICA_RESYNC_CHUNK_BYTES": "2048",
    })
    assert cfg.replica_anti_entropy_interval == 5.5
    assert cfg.replica_resync_chunk_bytes == 2048
    # Defaults: sweep off, chunk 256 KiB.
    d = Config()
    assert d.replica_anti_entropy_interval == 0.0
    assert d.replica_resync_chunk_bytes == 256 << 10


def test_router_from_config_wires_resync(tmp_path):
    from pilosa_tpu.replica.router import router_from_config

    cfg = Config(replica_groups=["g0=127.0.0.1:1"])
    cfg.replica_anti_entropy_interval = 3.0
    cfg.replica_resync_chunk_bytes = 4096
    router = router_from_config(cfg, stats=ExpvarStatsClient())
    assert router.anti_entropy_interval_s == 3.0
    assert router.resync.chunk_bytes == 4096
    router.close()


def test_resync_floor_pins_compaction(tmp_path):
    """An in-flight resync round floors the compaction watermark at its
    seed sequence — the handoff suffix must stay replayable even though
    the stale laggard is excluded from the usual min-applied rule."""
    wal = WriteAheadLog(str(tmp_path / "w.wal"), max_bytes=1 << 14, fsync=False)
    router = ReplicaRouter(
        ["g0=127.0.0.1:1", "g1=127.0.0.1:2"], wal=wal,
        stats=ExpvarStatsClient(),
    )
    g0, g1 = router.groups
    g1.stale = True  # excluded from `tracked`
    for _ in range(300):
        seq = wal.append("POST", "/p", b"y" * 512)
        g0.applied_seq = seq
    with router._mu:
        router._resync_floor["g1"] = 100
    router._maybe_compact()
    assert wal.first_seq == 101  # floored at the seed, not g0's head
    with router._mu:
        del router._resync_floor["g1"]
    router._maybe_compact()
    assert wal.first_seq == 0 or wal.first_seq > 300 - 1  # head-only now
    router.close()
