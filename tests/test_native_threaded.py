"""ThreadSanitizer leg of the free-threading readiness gate.

Builds the TSAN flavor of the native library (``make -C native tsan`` →
``libpilosa_native-tsan.so``) and runs tests/_tsan_harness.py in a
SUBPROCESS against it: ``PILOSA_TPU_NATIVE_LIB`` points the ctypes
bridge at the TSAN build and ``LD_PRELOAD`` puts the TSAN runtime first
(plus ``libstdc++`` so interceptors resolve before anything else
loads).  The harness drives the armed-table write lane, the
``pn_serve_pairs`` serving lane, streaming-ingest decode, and the
roaring kernels from genuinely concurrent threads — ctypes releases
the GIL, so the calls truly overlap inside the .so.

Two legs prove the gate cuts both ways:

- **clean** — per-fragment threads (every thread owns its buffers, the
  contract fragment._mu enforces in the real stack) must produce ZERO
  TSAN reports.
- **seeded race** — the same driver with sharing deliberately enabled
  (two threads, one armed table, a barrier so the native calls overlap)
  MUST produce a ``WARNING: ThreadSanitizer: data race`` report.  This
  fixture proves the leg can actually see a race; without it a silent
  mis-preload would pass the clean leg while sanitizing nothing.

Mirrors the ASAN leg's environmental contract: no toolchain / no TSAN
runtime / no TSAN-capable kernel → SKIP with the reason logged, never
an environmental failure.  ``PILOSA_TPU_NO_TSAN_LEG=1`` opts out.
"""

import os
import shutil
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_REPO, "native")
_TSAN_SO = os.path.join(_NATIVE, "libpilosa_native-tsan.so")
_HARNESS = os.path.join(_REPO, "tests", "_tsan_harness.py")

# TSAN aborts the whole process on some container/kernel configs
# (ASLR-heavy mappings) before main() runs; that is environmental.
_TSAN_FATAL = "FATAL: ThreadSanitizer"


def _skip(reason: str) -> None:
    sys.stderr.write(f"\n[test_native_threaded] skipping: {reason}\n")
    pytest.skip(reason)


def _resolve_runtime(lib: str) -> str:
    """Real path of a gcc runtime library (``libtsan.so`` prints as a
    linker-script/symlink path; LD_PRELOAD needs the actual DSO)."""
    out = subprocess.run(
        ["g++", f"-print-file-name={lib}"], capture_output=True, text=True,
        timeout=30,
    )
    path = out.stdout.strip()
    if not path or path == lib or not os.path.exists(path):
        return ""
    return os.path.realpath(path)


def _tsan_env() -> dict:
    """Build the TSAN .so + subprocess env, skipping (reason logged) on
    any environmental miss — shared preamble of both legs."""
    if os.environ.get("PILOSA_TPU_NO_TSAN_LEG"):
        _skip("PILOSA_TPU_NO_TSAN_LEG set")
    if os.environ.get("PILOSA_TPU_NO_NATIVE"):
        _skip("PILOSA_TPU_NO_NATIVE set; nothing native to sanitize")
    missing = [t for t in ("make", "g++") if shutil.which(t) is None]
    if missing:
        _skip(f"toolchain missing: {', '.join(missing)}")

    build = subprocess.run(
        ["make", "-C", _NATIVE, "tsan"],
        capture_output=True, text=True, timeout=240,
    )
    if build.returncode != 0 or not os.path.exists(_TSAN_SO):
        _skip(
            "make tsan failed (no TSAN-capable toolchain?): "
            + (build.stderr or build.stdout)[-400:]
        )

    tsan_rt = _resolve_runtime("libtsan.so")
    stdcxx_rt = _resolve_runtime("libstdc++.so.6")
    if not tsan_rt or not stdcxx_rt:
        _skip("libtsan/libstdc++ runtime not resolvable for LD_PRELOAD")

    env = dict(os.environ)
    env.update(
        {
            "PILOSA_TPU_NATIVE_LIB": _TSAN_SO,
            "PILOSA_TPU_NO_TSAN_LEG": "1",
            "LD_PRELOAD": f"{tsan_rt} {stdcxx_rt}",
            # halt_on_error off: the seeded-race leg wants the harness
            # to finish so the report count is deterministic; a clean
            # run still exits 0, a racy one exits 66.
            "TSAN_OPTIONS": "halt_on_error=0 exitcode=66",
        }
    )
    # The harness never imports jax, but keep any inherited platform
    # pinning consistent with the rest of tier-1.
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _run_harness(env: dict, *args: str) -> subprocess.CompletedProcess:
    res = subprocess.run(
        [sys.executable, _HARNESS, *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    out = (res.stdout or "") + (res.stderr or "")
    if _TSAN_FATAL in out:
        _skip("TSAN runtime unsupported here: " + out.splitlines()[0][-200:])
    return res


def test_concurrent_kernels_clean_under_tsan():
    """Per-fragment threads (zero sharing) over the write lane,
    serve_pairs, ingest decode, and roaring kernels: no TSAN report."""
    env = _tsan_env()

    # Preamble: prove the subprocess really serves from the TSAN .so —
    # a silent fallback to the Python lanes would pass while
    # sanitizing nothing.
    probe = subprocess.run(
        [
            sys.executable, "-c",
            "import sys; sys.path.insert(0, '.');"
            "from pilosa_tpu import native; p = native.loaded_path(); "
            f"assert p == {_TSAN_SO!r}, f'loaded {{p}}'; print('tsan-lib-ok')",
        ],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO,
    )
    out = (probe.stdout or "") + (probe.stderr or "")
    if _TSAN_FATAL in out:
        _skip("TSAN runtime unsupported here: " + out.splitlines()[0][-200:])
    assert probe.returncode == 0 and "tsan-lib-ok" in probe.stdout, (
        "TSAN .so did not load in the subprocess:\n"
        + probe.stdout[-800:] + probe.stderr[-1600:]
    )

    res = _run_harness(env, "--mode", "clean", "--threads", "4",
                       "--rounds", "8")
    out = (res.stdout or "") + (res.stderr or "")
    if res.returncode != 0 or "WARNING: ThreadSanitizer" in out:
        pytest.fail(
            "TSAN reported under the per-fragment (no sharing) contract "
            f"(exit {res.returncode}):\n" + out[-5000:],
            pytrace=False,
        )
    assert "tsan-harness-ok" in res.stdout


def test_seeded_shared_fragment_race_detected():
    """The known-race fixture: sharing one armed table across threads
    MUST produce a TSAN data-race report — proves the leg has teeth."""
    env = _tsan_env()
    res = _run_harness(env, "--mode", "shared", "--rounds", "25")
    out = (res.stdout or "") + (res.stderr or "")
    assert "WARNING: ThreadSanitizer: data race" in out, (
        "seeded shared-fragment race was NOT detected "
        f"(exit {res.returncode}) — the TSAN leg is blind:\n" + out[-3000:]
    )
