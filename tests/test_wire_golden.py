"""Cross-implementation wire tests: decode official-protobuf golden bytes
exactly, and re-encode byte-for-byte (tests/wire_golden.py provenance).

This is the reference's asm-vs-Go idiom applied to the codec: the
hand-rolled proto3 writer/reader vs the official library's output for
every message in internal/public.proto + internal/private.proto,
including the silent-divergence corners (packed repeated with zero
entries, zero-value omission, negative int64, empty messages, map
entries, unset submessages).
"""

import pytest

from pilosa_tpu import broadcast, wire
from pilosa_tpu.core.cache import Pair

from wire_golden import GOLDEN


# ---- Attr / AttrMap -------------------------------------------------------

@pytest.mark.parametrize(
    "name,key,value",
    [
        ("attr_string", "name", "alice"),
        ("attr_int_neg", "x", -3),
        ("attr_bool_false_zero_omitted", "flag", False),
        ("attr_float", "f", 1.5),
    ],
)
def test_attr_golden(name, key, value):
    raw = GOLDEN[name]
    assert wire.decode_attr(raw) == (key, value)
    assert wire.encode_attr(key, value) == raw


def test_attr_map_golden():
    raw = GOLDEN["attrmap"]
    assert wire.decode_attr_map(raw) == {"a": 7, "b": "z"}
    assert wire.encode_attr_map({"a": 7, "b": "z"}) == raw


# ---- Pair / Bit / ColumnAttrSet ------------------------------------------

@pytest.mark.parametrize(
    "name,key,count",
    [("pair", 10, 42), ("pair_zero_key", 0, 5), ("pair_zero_count", 9, 0)],
)
def test_pair_golden(name, key, count):
    raw = GOLDEN[name]
    assert wire.decode_pair(raw) == (key, count)
    assert wire.encode_pair(key, count) == raw


def test_bit_golden():
    raw = GOLDEN["bit"]
    assert wire.decode_bit(raw) == {"rowID": 3, "columnID": 1 << 40, "timestamp": -1}
    assert wire.encode_bit(3, 1 << 40, -1) == raw


def test_column_attr_set_golden():
    raw = GOLDEN["column_attr_set"]
    assert wire.decode_column_attr_set(raw) == (77, {"n": 1})
    assert wire.encode_column_attr_set(77, {"n": 1}) == raw


# ---- Bitmap ---------------------------------------------------------------

def test_bitmap_golden():
    raw = GOLDEN["bitmap_packed"]
    bits, attrs = wire.decode_bitmap(raw)
    assert bits == [0, 1, 300, 1 << 63] and attrs == {}
    assert wire.encode_bitmap([0, 1, 300, 1 << 63]) == raw
    assert GOLDEN["bitmap_empty"] == b""
    assert wire.encode_bitmap([]) == b""
    assert wire.decode_bitmap(b"") == ([], {})


# ---- QueryRequest / QueryResult / QueryResponse ---------------------------

def test_query_request_golden():
    raw = GOLDEN["query_request"]
    assert wire.decode_query_request(raw) == {
        "query": "Count(Bitmap(rowID=1))",
        "slices": [0, 1, 5],
        "column_attrs": True,
        "quantum": "YMD",
        "remote": True,
    }
    assert (
        wire.encode_query_request(
            "Count(Bitmap(rowID=1))", [0, 1, 5], column_attrs=True, quantum="YMD", remote=True
        )
        == raw
    )
    minimal = GOLDEN["query_request_minimal"]
    q = 'SetBit(id=1, frame="f", col=2)'
    assert wire.decode_query_request(minimal)["query"] == q
    assert wire.encode_query_request(q) == minimal


from pilosa_tpu.executor import QueryBitmap


class _RawBitmap(QueryBitmap):
    """QueryBitmap stand-in with explicit global bit positions."""

    def __init__(self, bits, attrs=None):
        super().__init__({}, attrs or {})
        self._bits = bits

    def bits(self):
        return self._bits


def test_query_result_golden():
    assert wire.decode_query_result(GOLDEN["query_result_bitmap"]) == {
        "bitmap": {"bits": [2, 9], "attrs": {}}
    }
    assert wire.decode_query_result(GOLDEN["query_result_n"]) == {"n": 123}
    assert wire.decode_query_result(GOLDEN["query_result_pairs"]) == {
        "pairs": [{"id": 1, "count": 2}, {"id": 0, "count": 1}]
    }
    assert wire.decode_query_result(GOLDEN["query_result_changed"]) == {"changed": True}
    # byte-identical re-encode through the executor-result encoder
    import pilosa_tpu.wire as w

    assert w.encode_query_result(_RawBitmap([2, 9])) == GOLDEN["query_result_bitmap"]
    assert w.encode_query_result(123) == GOLDEN["query_result_n"]
    assert (
        w.encode_query_result([Pair(1, 2), Pair(0, 1)]) == GOLDEN["query_result_pairs"]
    )
    assert w.encode_query_result(True) == GOLDEN["query_result_changed"]


def test_query_response_golden():
    raw = GOLDEN["query_response"]
    got = wire.decode_query_response(raw)
    assert got["err"] == ""
    assert len(got["results"]) == 4
    assert got["columnAttrSets"] == [{"id": 5, "attrs": {"k": "v"}}]
    assert (
        wire.encode_query_response(
            results=[_RawBitmap([2, 9]), 123, [Pair(1, 2), Pair(0, 1)], True],
            column_attr_sets=[(5, {"k": "v"})],
        )
        == raw
    )
    err_raw = GOLDEN["query_response_err"]
    assert wire.decode_query_response(err_raw)["err"] == "index not found"
    assert wire.encode_query_response(err="index not found") == err_raw


# ---- ImportRequest / ImportResponse ---------------------------------------

def test_import_request_golden():
    raw = GOLDEN["import_request"]
    assert wire.decode_import_request(raw) == {
        "index": "i",
        "frame": "f",
        "slice": 2,
        "rowIDs": [1, 0, 2],
        "columnIDs": [3, 4, 0],
        "timestamps": [0, -5, 1500000000],
    }
    assert (
        wire.encode_import_request("i", "f", 2, [1, 0, 2], [3, 4, 0], [0, -5, 1500000000])
        == raw
    )


def test_import_response_golden():
    assert wire.decode_import_response(GOLDEN["import_response"]) == "nope"
    assert wire.encode_import_response("nope") == GOLDEN["import_response"]
    assert GOLDEN["import_response_empty"] == b""
    assert wire.encode_import_response() == b""
    assert wire.decode_import_response(b"") == ""


# ---- Metas ----------------------------------------------------------------

def test_meta_golden():
    raw = GOLDEN["index_meta"]
    assert wire.decode_index_meta(raw) == {"columnLabel": "columnID", "timeQuantum": "YMDH"}
    assert wire.encode_index_meta("columnID", "YMDH") == raw
    raw = GOLDEN["frame_meta"]
    assert wire.decode_frame_meta(raw) == {
        "rowLabel": "rowID",
        "inverseEnabled": True,
        "cacheType": "ranked",
        "cacheSize": 50000,
        "timeQuantum": "YMD",
    }
    assert wire.encode_frame_meta("rowID", True, "ranked", 50000, "YMD") == raw
    assert GOLDEN["frame_meta_defaults"] == b""
    assert wire.encode_frame_meta("", False, "", 0, "") == b""


# ---- Block data / Cache / MaxSlices ---------------------------------------

def test_block_data_golden():
    raw = GOLDEN["block_data_request"]
    assert wire.decode_block_data_request(raw) == {
        "index": "i", "frame": "f", "view": "standard", "slice": 3, "block": 7,
    }
    assert wire.encode_block_data_request("i", "f", "standard", 3, 7) == raw
    raw = GOLDEN["block_data_response"]
    assert wire.decode_block_data_response(raw) == ([0, 1, 1], [5, 0, 9])
    assert wire.encode_block_data_response([0, 1, 1], [5, 0, 9]) == raw


def test_cache_golden():
    assert wire.decode_cache(GOLDEN["cache"]) == [3, 0, 11]
    assert wire.encode_cache([3, 0, 11]) == GOLDEN["cache"]
    assert GOLDEN["cache_empty"] == b""
    assert wire.encode_cache([]) == b""


def test_max_slices_golden():
    raw = GOLDEN["max_slices"]
    assert wire.decode_max_slices_response(raw) == {"idx": 4, "a": 0}
    # zero map values are EMITTED (map entries always carry both fields);
    # deterministic order = sorted by key.
    assert wire.encode_max_slices_response({"idx": 4, "a": 0}) == raw


# ---- Broadcast envelope messages ------------------------------------------

def test_broadcast_messages_golden():
    for name, enc, typ, want in [
        ("create_slice", broadcast.encode_create_slice("i", 9, True),
         broadcast.MESSAGE_TYPE_CREATE_SLICE, {"index": "i", "slice": 9, "isInverse": True}),
        ("create_slice_zero", broadcast.encode_create_slice("i", 0),
         broadcast.MESSAGE_TYPE_CREATE_SLICE, {"index": "i"}),
        ("delete_index", broadcast.encode_delete_index("i"),
         broadcast.MESSAGE_TYPE_DELETE_INDEX, {"index": "i"}),
        ("create_index", broadcast.encode_create_index("i", "c", "Y"),
         broadcast.MESSAGE_TYPE_CREATE_INDEX,
         {"index": "i", "meta": {"columnLabel": "c", "timeQuantum": "Y"}}),
        ("create_frame",
         broadcast.encode_create_frame("i", "f", {"rowLabel": "r", "cacheType": "lru", "cacheSize": 100}),
         broadcast.MESSAGE_TYPE_CREATE_FRAME,
         {"index": "i", "frame": "f",
          "meta": {"rowLabel": "r", "inverseEnabled": False, "cacheType": "lru",
                   "cacheSize": 100, "timeQuantum": ""}}),
        ("delete_frame", broadcast.encode_delete_frame("i", "f"),
         broadcast.MESSAGE_TYPE_DELETE_FRAME, {"index": "i", "frame": "f"}),
    ]:
        assert enc[1:] == GOLDEN[name], name  # payload = official bytes
        got_typ, got = broadcast.decode_message(enc)
        assert got_typ == typ, name
        assert got == want, name


# ---- Index / NodeStatus / ClusterStatus -----------------------------------

_IDX1 = {
    "name": "i1",
    "meta": {"columnLabel": "col", "timeQuantum": ""},
    "maxSlice": 3,
    "frames": [
        {"name": "f1",
         "meta": {"rowLabel": "r", "inverseEnabled": False, "cacheType": "ranked",
                  "cacheSize": 1000, "timeQuantum": ""}}
    ],
    "slices": [0, 1, 3],
}


def test_index_golden():
    assert wire._decode_index_msg(GOLDEN["index_msg"]) == _IDX1


def test_node_status_golden():
    raw = GOLDEN["node_status"]
    got = wire.decode_node_status(raw)
    assert got == {
        "host": "h1:10101",
        "state": "UP",
        "indexes": [_IDX1, {"name": "i2", "maxSlice": 0, "frames": [], "slices": []}],
    }
    # re-encode byte-for-byte (packed Slices, unset metas omitted)
    assert (
        wire.encode_node_status(
            "h1:10101", "UP", [_IDX1, {"name": "i2"}]
        )
        == raw
    )


def test_cluster_status_golden():
    raw = GOLDEN["cluster_status"]
    nodes = wire.decode_cluster_status(raw)
    assert [(n["host"], n["state"]) for n in nodes] == [("a", "UP"), ("b", "DOWN")]
    assert (
        wire.encode_cluster_status(
            [{"host": "a", "state": "UP"}, {"host": "b", "state": "DOWN"}]
        )
        == raw
    )
