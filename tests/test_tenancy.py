"""Multi-tenant isolation (pilosa_tpu/tenancy/, ROADMAP item 5).

Covers: the single tenant-resolution seam (header > map > index name >
default) and its config parsers; weighted fair-share admission inside
the QoS class doors (a hostile tenant sheds at its share while a polite
tenant keeps clearing the SAME door); per-tenant qcache byte quotas
(self-first reclamation — one tenant's store flood never flushes
another's working set); the per-tenant ingest bandwidth pacer (token
buckets, weighted shares, idle reclaim); the cost-ledger tenant
dimension (5-tuple keys, tenant-agnostic peek fallback, legacy snapshot
restore); the ``[tenancy]`` config section + env overrides; and the
/debug/tenants endpoint end to end through the HTTP server — including
the isolation-OFF contract (no TenancyState, pre-tenancy behavior).
"""

import json
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from pilosa_tpu import tenancy
from pilosa_tpu.config import Config
from pilosa_tpu.qos import CLASS_READ, CLASS_WRITE, AdmissionController, ShedError

# -- resolution seam --------------------------------------------------------


def test_index_of():
    assert tenancy.index_of("/index/foo/query") == "foo"
    assert tenancy.index_of("/index/foo") == "foo"
    assert tenancy.index_of("/status") == ""
    assert tenancy.index_of("") == ""


def test_resolve_precedence():
    # Header beats everything (handler dicts are lowercased).
    assert (
        tenancy.resolve(
            "/index/i/query",
            {"x-pilosa-tenant": "acme"},
            index_map={"i": "gold"},
        )
        == "acme"
    )
    # Map beats the index name.
    assert tenancy.resolve("/index/i/query", {}, index_map={"i": "gold"}) == "gold"
    # Index name beats the default.
    assert tenancy.resolve("/index/i/query", {}) == "i"
    # Admin routes with no index fall to the default.
    assert tenancy.resolve("/status", {}) == tenancy.DEFAULT_TENANT
    # Whitespace-only headers are absent, not a tenant named "  ".
    assert tenancy.resolve("/index/i/query", {"x-pilosa-tenant": "  "}) == "i"


def test_parse_helpers():
    assert tenancy.parse_weights("gold=4, free=1") == {"gold": 4.0, "free": 1.0}
    assert tenancy.parse_weights("") == {}
    assert tenancy.parse_weights("bad,x=notanumber") == {}
    # Weights are floored away from zero: a zero weight would divide
    # the shares by zero, not exclude the tenant.
    assert tenancy.parse_weights("z=0")["z"] == pytest.approx(1e-3)
    assert tenancy.parse_map("a=gold, b=free") == {"a": "gold", "b": "free"}
    assert tenancy.parse_map("") == {}
    # Bare fraction: one default share for every tenant.
    assert tenancy.parse_shares("0.5") == (0.5, {})
    assert tenancy.parse_shares("2.0") == (1.0, {})  # clamped
    d, per = tenancy.parse_shares("gold=0.75,free=0.1")
    assert d == 0.0 and per == {"gold": 0.75, "free": 0.1}
    assert tenancy.parse_shares("") == (0.0, {})


def test_tenancy_state_resolution():
    st = tenancy.TenancyState(
        weights="gold=4", index_map="i=gold", qcache_share="0.5"
    )
    assert st.resolve("/index/i/query", {}) == "gold"
    assert st.resolve_for_index("i", {}) == "gold"
    assert st.resolve_for_index("i", {"x-pilosa-tenant": "acme"}) == "acme"
    assert st.tenant_of_index("other") == "other"
    assert st.tenant_of_index("") == tenancy.DEFAULT_TENANT
    assert st.qcache_quota("anyone", 1000) == 500
    # 0.0 share = unquoted.
    st2 = tenancy.TenancyState(qcache_share="gold=0.5")
    assert st2.qcache_quota("free", 1000) == 0
    assert st2.qcache_quota("gold", 1000) == 500


# -- weighted fair-share admission ------------------------------------------


def _door(depth=2, queue_wait_ms=40.0, **kw):
    st = tenancy.TenancyState(**kw)
    adm = AdmissionController(
        depths={CLASS_READ: depth},
        queue_wait_ms=queue_wait_ms,
        retry_after_ms=100.0,
        tenancy=st,
    )
    return adm, st


def test_fair_share_work_conserving_alone():
    """A tenant ALONE at the door gets the whole depth — tenancy on
    with one tenant present costs no throughput."""
    adm, _ = _door(depth=3)
    for _ in range(3):
        adm.acquire(CLASS_READ, tenant="hostile")
    # Slot 4: over depth, waits, then sheds.
    with pytest.raises(ShedError):
        adm.acquire(CLASS_READ, tenant="hostile")
    for _ in range(3):
        adm.release(CLASS_READ, tenant="hostile")


def test_fair_share_presence_hysteresis():
    """A tenant's share survives the instant between its closed-loop
    requests: a flooder cannot seize the whole door during a momentary
    gap — the departed tenant's share is reclaimed only PRESENCE_S
    after its last door activity."""
    clk = _Clock()
    fs = tenancy.FairShare(weights={"polite": 7, "hostile": 1}, clock=clk)
    fs.note_admit(CLASS_READ, "polite")
    fs.note_release(CLASS_READ, "polite")
    # No polite inflight or waiting — but inside the presence window
    # the flooder still sees polite's share standing.
    assert fs.cap(CLASS_READ, "hostile", 8) == 1
    clk.t += tenancy.FairShare.PRESENCE_S / 2
    assert fs.cap(CLASS_READ, "hostile", 8) == 1
    # Past the horizon the polite tenant is gone: work conservation
    # hands the flooder the whole depth.
    clk.t += tenancy.FairShare.PRESENCE_S
    assert fs.cap(CLASS_READ, "hostile", 8) == 8


def test_fair_share_hostile_sheds_polite_clears():
    """The isolation property at the unit scale: with the door FULL of
    hostile inflight, a polite tenant's request still clears on the next
    release — the freed slot goes to the under-share tenant, never back
    to the flooder."""
    adm, _ = _door(depth=2, queue_wait_ms=2000.0)
    adm.acquire(CLASS_READ, tenant="hostile")
    adm.acquire(CLASS_READ, tenant="hostile")

    admitted = []

    def polite():
        adm.acquire(CLASS_READ, tenant="polite")
        admitted.append(True)

    def hostile_waiter():
        try:
            adm.acquire(CLASS_READ, tenant="hostile")
            admitted.append("hostile!")
        except ShedError:
            pass

    tp = threading.Thread(target=polite)
    th = threading.Thread(target=hostile_waiter)
    tp.start()
    th.start()
    # Both parked in the wait lane (visible in the snapshot) before the
    # release decides who gets the slot.
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        snap = adm.tenants_snapshot()
        if (
            snap.get("polite", {}).get("waiting", {}).get(CLASS_READ)
            and snap.get("hostile", {}).get("waiting", {}).get(CLASS_READ)
        ):
            break
        time.sleep(0.005)
    # One hostile slot frees: present = {hostile, polite}, so the
    # hostile cap is now 1 and its remaining inflight (1) fills it —
    # only the polite waiter is eligible for the freed slot.
    adm.release(CLASS_READ, tenant="hostile")
    tp.join(timeout=5)
    th.join(timeout=5)
    assert admitted == [True]

    snap = adm.tenants_snapshot()
    assert snap["polite"]["shed"] == 0 and snap["polite"]["admitted"] == 1
    assert snap["hostile"]["shed"] == 1 and snap["hostile"]["admitted"] == 2
    adm.release(CLASS_READ, tenant="hostile")
    adm.release(CLASS_READ, tenant="polite")


def test_fair_share_weights_split_share():
    """weights gold=3 free=1 over depth 4: gold's cap is 3, free's 1 —
    and debt grows per-admit at 1/w, so equal debt means
    weight-proportional admission."""
    adm, st = _door(depth=4, weights="gold=3,free=1")
    fair = st.fair
    adm.acquire(CLASS_READ, tenant="gold")
    adm.acquire(CLASS_READ, tenant="free")
    assert fair.cap(CLASS_READ, "gold", 4) == 3
    assert fair.cap(CLASS_READ, "free", 4) == 1
    # free is AT its cap: its next request waits/sheds, gold's clears.
    adm.acquire(CLASS_READ, tenant="gold")
    with pytest.raises(ShedError):
        adm.acquire(CLASS_READ, tenant="free")
    snap = adm.tenants_snapshot()
    assert snap["gold"]["debt"] == pytest.approx(2 / 3.0, abs=1e-3)
    assert snap["free"]["debt"] == pytest.approx(1.0)
    for _ in range(2):
        adm.release(CLASS_READ, tenant="gold")
    adm.release(CLASS_READ, tenant="free")


def test_fair_share_unbounded_class_accounts_only():
    """depth <= 0 stays unbounded with tenancy on — the accounting
    rides along but nothing sheds (the pre-QoS contract)."""
    adm, _ = _door(depth=0)
    for _ in range(16):
        adm.acquire(CLASS_READ, tenant="t")
    snap = adm.tenants_snapshot()
    assert snap["t"]["admitted"] == 16 and snap["t"]["shed"] == 0
    for _ in range(16):
        adm.release(CLASS_READ, tenant="t")


def test_tenancy_off_door_unchanged():
    """tenant=None (isolation off) takes the pre-tenancy body: no
    per-tenant state is ever created."""
    adm = AdmissionController(depths={CLASS_READ: 1}, queue_wait_ms=20.0)
    adm.acquire(CLASS_READ)
    with pytest.raises(ShedError):
        adm.acquire(CLASS_READ)
    adm.release(CLASS_READ)
    assert adm.tenants_snapshot() == {}


# -- per-tenant ingest bandwidth pacing -------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_pacer_tokens_and_retry_after():
    clk = _Clock()
    p = tenancy.BandwidthPacer(1000, clock=clk)
    # A fresh bucket starts full (burst_s * rate = 2000 bytes).
    assert p.admit("a", 1500) == 0.0
    # 1000 more: 500 tokens left -> retry-after (1000-500)/1000 = 0.5s.
    wait = p.admit("a", 1000)
    assert wait == pytest.approx(0.5, abs=0.05)
    clk.t += wait
    assert p.admit("a", 1000) == 0.0
    assert "a" in p.snapshot()


def test_pacer_share_rebalances_and_idle_reclaims():
    clk = _Clock()
    p = tenancy.BandwidthPacer(1000, clock=clk)
    # Drain a's bucket while it is ALONE: full rate (1000 B/s).
    assert p.admit("a", 2000) == 0.0
    assert p.admit("a", 1000) == pytest.approx(1.0, abs=0.05)
    # b shows up: equal weights halve a's refill rate.
    p.admit("b", 1)
    assert p.admit("a", 1000) == pytest.approx(2.0, abs=0.1)
    # b idle past the window: its share returns to a.
    clk.t += tenancy.BandwidthPacer.IDLE_S + 1
    assert p.admit("a", 1000) == 0.0  # refilled at >= half rate for 11s
    assert "b" not in p.snapshot()


def test_pacer_single_chunk_always_eventually_clears():
    clk = _Clock()
    p = tenancy.BandwidthPacer(100, burst_s=0.5, clock=clk)
    # A chunk far above rate*burst still fits the cap floor.
    assert p.admit("a", 5000) == 0.0


# -- per-tenant qcache byte quotas ------------------------------------------


@pytest.fixture()
def qc_env(tmp_path):
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.qcache import QueryCache

    h = Holder(str(tmp_path / "d"))
    h.open()
    for name in ("i", "j"):
        h.create_index(name).create_frame("f", FrameOptions())
        fr = h.index(name).frame("f")
        for r in range(16):
            fr.set_bit("standard", r, r)
    st = tenancy.TenancyState(qcache_share="0.5")
    qc = QueryCache(min_cost_ms=0.0, tenancy=st)
    ex = Executor(h, engine="numpy", qcache=qc)
    yield h, ex, qc
    h.close()


def test_qcache_quota_self_reclaim_spares_neighbor(qc_env):
    """Tenant i floods the cache: its own LRU entries reclaim at its
    50% byte share while tenant j's resident entry survives untouched —
    then j still HITS."""
    h, ex, qc = qc_env
    q_j = 'Count(Bitmap(rowID=0, frame="f"))'
    assert ex.execute("j", q_j) == [1]  # j's working set: one entry
    # Size the budget so only a few entries fit: measure one entry.
    entry_bytes = qc.bytes - qc.tenant_bytes_snapshot().get("i", 0)
    assert entry_bytes > 0
    qc.max_bytes = entry_bytes * 4  # quota: 2 entries per tenant
    for r in range(12):
        ex.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))')
    snap = qc.tenant_bytes_snapshot()
    assert snap["i"] <= qc.max_bytes // 2
    # j's entry never paid for i's flood.
    assert snap["j"] == entry_bytes
    hits0 = qc.hits
    assert ex.execute("j", q_j) == [1]
    assert qc.hits == hits0 + 1
    assert qc.evictions > 0


def test_qcache_purge_and_clear_return_tenant_bytes(qc_env):
    h, ex, qc = qc_env
    ex.execute("i", 'Count(Bitmap(rowID=0, frame="f"))')
    ex.execute("j", 'Count(Bitmap(rowID=0, frame="f"))')
    assert set(qc.tenant_bytes_snapshot()) == {"i", "j"}
    qc.purge_index("i")
    assert set(qc.tenant_bytes_snapshot()) == {"j"}
    qc.clear()
    assert qc.tenant_bytes_snapshot() == {}


def test_qcache_no_tenancy_no_tenant_accounting(tmp_path):
    """Isolation off: entries carry no tenant and the byte map stays
    empty — the pre-tenancy cache, byte for byte."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.qcache import QueryCache

    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    h.index("i").frame("f").set_bit("standard", 0, 1)
    qc = QueryCache(min_cost_ms=0.0)
    ex = Executor(h, engine="numpy", qcache=qc)
    assert ex.execute("i", 'Count(Bitmap(rowID=0, frame="f"))') == [1]
    assert qc.tenant_bytes_snapshot() == {}
    h.close()


# -- cost-ledger tenant dimension -------------------------------------------


def test_costs_five_tuple_keys_and_peek_fallback():
    from pilosa_tpu.costs import CostLedger

    led = CostLedger()
    led.observe(tenant="gold", index="i", frame="f", fp="fp",
                lane="exec", ms=10.0)
    # Exact peek with the tenant.
    e = led.peek(tenant="gold", index="i", frame="f", fp="fp", lane="exec")
    assert e is not None and e["ewma_ms"] == pytest.approx(10.0)
    # Tenant-agnostic peek (the planner's call shape) falls back to the
    # MRU tenant for the same (index, frame, fp, lane).
    e = led.peek(index="i", frame="f", fp="fp", lane="exec")
    assert e is not None and e["ewma_ms"] == pytest.approx(10.0)
    # A different tenant, same 4-tuple: separate entries, fallback
    # follows recency.
    led.observe(tenant="free", index="i", frame="f", fp="fp",
                lane="exec", ms=30.0)
    e = led.peek(index="i", frame="f", fp="fp", lane="exec")
    assert e["ewma_ms"] == pytest.approx(30.0)
    rows = led.entries()
    assert {r["tenant"] for r in rows} == {"gold", "free"}
    by = led.by_tenant()
    assert by["gold"]["entries"] == 1 and by["free"]["entries"] == 1
    # /debug/costs keeps emitting index/frame/fp/lane and now tenant.
    snap = led.snapshot()
    assert {r["tenant"] for r in snap["entries"]} == {"gold", "free"}
    assert all(r["index"] == "i" for r in snap["entries"])


class _FakeSpan:
    def __init__(self, name="root", tags=None):
        self.name = name
        self.tags = tags or {}
        self.children = []
        self.ms = 0.0


class _FakeTrace:
    def __init__(self, tags):
        self.root = _FakeSpan(tags=tags)
        self.wall_ts = 1000.0


def test_costs_fold_separates_tenant_from_index():
    """The PR-13 conflation fix: a trace tagged with BOTH tenant and
    index folds into a key carrying each in its own dimension."""
    from pilosa_tpu.costs import CostLedger

    led = CostLedger()
    led.fold(_FakeTrace({"tenant": "gold", "index": "i", "frame": "f",
                         "lane": "exec"}), 5.0)
    rows = led.entries()
    assert rows[0]["tenant"] == "gold" and rows[0]["index"] == "i"
    # Embedders that only tagged "tenant" (the pre-tenancy handler wrote
    # the index name there) keep their index keying.
    led.fold(_FakeTrace({"tenant": "solo", "frame": "f", "lane": "exec"}),
             5.0)
    rows = {(r["tenant"], r["index"]) for r in led.entries()}
    assert ("solo", "solo") in rows


def test_costs_restore_legacy_four_tuple_snapshot():
    from pilosa_tpu.costs import CostLedger

    led = CostLedger()
    led.observe(index="i", frame="f", fp="fp", lane="exec", ms=7.0)
    st = led.state()
    # Age the state to the pre-tenancy 4-tuple key shape.
    for row in st["entries"]:
        assert row[0][0] == ""
        row[0] = row[0][1:]
    led2 = CostLedger()
    led2.restore(st)
    e = led2.peek(index="i", frame="f", fp="fp", lane="exec")
    assert e is not None and e["ewma_ms"] == pytest.approx(7.0)


# -- config section ---------------------------------------------------------


def test_config_tenancy_section_and_env(monkeypatch):
    cfg = Config.from_dict({
        "tenancy": {
            "enabled": True,
            "weights": "gold=4,free=1",
            "default-weight": 2.0,
            "map": "i=gold",
            "qcache-share": "0.5",
            "ingest-bytes-per-s": 1 << 20,
        }
    })
    assert cfg.tenancy_enabled and cfg.tenancy_weights == "gold=4,free=1"
    assert cfg.tenancy_default_weight == 2.0
    assert cfg.tenancy_map == "i=gold"
    assert cfg.tenancy_qcache_share == "0.5"
    assert cfg.tenancy_ingest_bytes_per_s == 1 << 20
    st = tenancy.from_config(cfg)
    assert st is not None and st.weights == {"gold": 4.0, "free": 1.0}
    assert st.pacer is not None

    # Env wins over TOML; disabled builds no state at all.
    monkeypatch.setenv("PILOSA_TPU_TENANCY", "0")
    assert tenancy.from_config(Config.from_dict({
        "tenancy": {"enabled": True},
    }).apply_env()) is None
    monkeypatch.setenv("PILOSA_TPU_TENANCY", "1")
    monkeypatch.setenv("PILOSA_TPU_TENANCY_WEIGHTS", "a=9")
    st = tenancy.from_config(Config().apply_env())
    assert st is not None and st.weights == {"a": 9.0}


def test_from_config_default_off():
    assert tenancy.from_config(Config()) is None


# -- /debug/tenants through the server --------------------------------------


def _make_server(tmp_path, **cfg_kwargs):
    from pilosa_tpu.server.server import Server

    cfg = Config(data_dir=str(tmp_path / "s"), host="127.0.0.1:0",
                 engine="numpy", **cfg_kwargs)
    s = Server(cfg)
    s.open()
    return s


def _http(host, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://{host}{path}", data=body, method=method
    )
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_debug_tenants_endpoint(tmp_path):
    srv = _make_server(
        tmp_path,
        tenancy_enabled=True,
        tenancy_weights="gold=4",
        tenancy_map="i=gold",
    )
    try:
        _http(srv.host, "POST", "/index/i")
        _http(srv.host, "POST", "/index/i/frame/f")
        _http(srv.host, "POST", "/index/i/query",
              b'SetBit(rowID=1, frame="f", columnID=3)')
        # A read billed to the mapped tenant, one to a header override.
        _http(srv.host, "POST", "/index/i/query",
              b'Count(Bitmap(rowID=1, frame="f"))')
        _http(srv.host, "POST", "/index/i/query",
              b'Count(Bitmap(rowID=1, frame="f"))',
              headers={"X-Pilosa-Tenant": "acme"})
        st, _, payload = _http(srv.host, "GET", "/debug/tenants")
        out = json.loads(payload)
        assert st == 200 and out["enabled"] is True
        assert out["tenants"]["gold"]["weight"] == 4.0
        assert out["tenants"]["gold"]["admitted"] >= 2
        assert out["tenants"]["acme"]["admitted"] == 1
        # Per-tenant latency series landed in /debug/vars too.
        _, _, vars_payload = _http(srv.host, "GET", "/debug/vars")
        vars_snap = json.loads(vars_payload)
        assert any(k.startswith("tenancy.latency_ms.gold") for k in vars_snap)
    finally:
        srv.close()


def test_debug_tenants_endpoint_off(tmp_path):
    srv = _make_server(tmp_path)
    try:
        st, _, payload = _http(srv.host, "GET", "/debug/tenants")
        out = json.loads(payload)
        assert st == 200 and out == {"enabled": False, "tenants": {}}
    finally:
        srv.close()


def test_ingest_door_pacer_sheds_429_with_retry_after(tmp_path):
    """A chunk past the tenant's bandwidth share answers 429 +
    Retry-After BEFORE staging; honoring the hint clears it."""
    from pilosa_tpu import ingest as ingest_mod
    import numpy as np

    srv = _make_server(
        tmp_path,
        tenancy_enabled=True,
        tenancy_ingest_bytes_per_s=2048,
    )
    try:
        _http(srv.host, "POST", "/index/i")
        _http(srv.host, "POST", "/index/i/frame/f")
        rows = np.arange(600, dtype=np.uint64) % 8
        cols = np.arange(600, dtype=np.uint64)
        half = 300
        frames = [
            ingest_mod.encode_packed(rows[:half], cols[:half]),
            ingest_mod.encode_packed(rows[half:], cols[half:]),
        ]
        total = sum(len(f) for f in frames)
        crc = 0
        for f in frames:
            crc = zlib.crc32(f, crc)
        # First chunk rides the initial burst; the second overdraws the
        # 2 KiB/s bucket (each chunk is ~4.8 KB).
        url = (
            f"/index/i/frame/f/ingest?off=0&total={total}"
            f"&crc={crc}&ccrc={zlib.crc32(frames[0])}"
        )
        st, _, _ = _http(srv.host, "POST", url, frames[0])
        assert st == 200
        url2 = (
            f"/index/i/frame/f/ingest?off={len(frames[0])}&total={total}"
            f"&crc={crc}&ccrc={zlib.crc32(frames[1])}"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            _http(srv.host, "POST", url2, frames[1])
        assert e.value.code == 429
        retry = float(e.value.headers["Retry-After"])
        assert retry > 0
        time.sleep(min(retry, 5.0))
        st, _, payload = _http(srv.host, "POST", url2, frames[1])
        assert st == 200 and json.loads(payload)["done"]
    finally:
        srv.close()


def test_tenancy_off_query_path_unchanged(tmp_path):
    """Isolation OFF end to end: queries serve, no tenancy.* series
    appear, and traces keep the PR-13 tenant=index attribution."""
    srv = _make_server(tmp_path)
    try:
        _http(srv.host, "POST", "/index/i")
        _http(srv.host, "POST", "/index/i/frame/f")
        _http(srv.host, "POST", "/index/i/query",
              b'SetBit(rowID=1, frame="f", columnID=3)')
        st, _, payload = _http(srv.host, "POST", "/index/i/query",
                               b'Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200 and json.loads(payload)["results"] == [1]
        _, _, vars_payload = _http(srv.host, "GET", "/debug/vars")
        assert not any(
            k.startswith("tenancy.") for k in json.loads(vars_payload)
        )
    finally:
        srv.close()
