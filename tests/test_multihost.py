"""Multi-host mesh tests: REAL multi-process jax.distributed jobs.

The analog of the reference's two-real-servers tests
(server/server_test.go MustRunMain + TestMain_SendReceiveMessage), but
for the TPU-native data plane: two OS processes join one jax.distributed
job over a gloo CPU backend, each contributes only its own slice shards,
and the sharded kernels produce globally-correct results through
cross-process collectives.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh():
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    # The workers pin their own platform/device config (init_multihost);
    # strip the suite's CPU pin so the worker exercises the production
    # init path, and drop PYTHONPATH so a TPU-plugin site dir can't grab
    # the job's devices.
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO  # repo only: a TPU-plugin site dir must not grab devices
    env["XLA_FLAGS"] = ""  # workers set their own device count

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            cwd=REPO,
            env=env,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out (coordinator barrier hang?)")
        assert p.returncode == 0, f"worker failed:\nstdout={out}\nstderr={err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        assert o["global_devices"] == 4
        assert o["local_devices"] == 2
        assert o["count_ok"], o
        assert o["union_ok"], o
        # Full PQL executor in SPMD lockstep over the global mesh agrees
        # with the numpy engine on every process.
        assert o["exec_ok"], o
        # TopN candidate scoring runs the ENGINE scorer (shard_map'd
        # all-slice counts) on the 2-process mesh, with host parity.
        assert o["topn_parity_ok"], o
        assert o["topn_scorer_engaged"], o
        assert o["topn_scorer_ok"], o
    # Both processes computed the SAME global count from disjoint shards.
    assert by_pid[0]["count"] == by_pid[1]["count"]
    assert by_pid[0]["exec_results"] == by_pid[1]["exec_results"]
    # Slice ownership is disjoint and covers the stack.
    assert sorted(by_pid[0]["owned"] + by_pid[1]["owned"]) == list(range(8))


class _LockstepJob:
    """Shared harness for lockstep-service tests: spawns n ranks of
    tests/lockstep_worker.py, drains stdout, keeps stderr in temp files
    surfaced on failure, and collects the final per-rank JSON."""

    def __init__(self, n_ranks: int, env_extra=None):
        import tempfile
        import threading

        self.n = n_ranks
        self.coord, self.control, self.http = _free_port(), _free_port(), _free_port()
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["PYTHONPATH"] = REPO
        env["XLA_FLAGS"] = ""
        env.update(env_extra or {})
        worker = os.path.join(REPO, "tests", "lockstep_worker.py")
        self.errfiles = [tempfile.NamedTemporaryFile("w+", delete=False) for _ in range(n_ranks)]
        self.procs = [
            subprocess.Popen(
                [sys.executable, worker, f"127.0.0.1:{self.coord}", str(n_ranks),
                 str(pid), str(self.control), str(self.http)],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=self.errfiles[pid],
                cwd=REPO,
                env=env,
                text=True,
            )
            for pid in range(n_ranks)
        ]
        self.out_lines = [[] for _ in range(n_ranks)]
        self.drainers = [
            threading.Thread(target=self._drain, args=(i,), daemon=True)
            for i in range(n_ranks)
        ]
        for t in self.drainers:
            t.start()

    def _drain(self, i):
        for line in self.procs[i].stdout:
            self.out_lines[i].append(line)

    def stderr_tail(self, i):
        self.errfiles[i].flush()
        with open(self.errfiles[i].name) as f:
            return f.read()[-2000:]

    def _all_stderr(self):
        return "\n".join(f"rank {i}: {self.stderr_tail(i)}" for i in range(self.n))

    def wait_ready(self, timeout=150):
        import time as _time

        t0 = _time.monotonic()
        while not self.out_lines[0] and _time.monotonic() - t0 < timeout:
            if any(p.poll() is not None for p in self.procs):
                pytest.fail(f"a rank died at startup:\n{self._all_stderr()}")
            _time.sleep(0.1)
        assert self.out_lines[0], f"rank 0 never became ready:\n{self._all_stderr()}"
        assert json.loads(self.out_lines[0][0]).get("ready"), self.out_lines[0][0]

    def query(self, q, timeout=60, headers=None):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http}/index/g/query",
            data=q.encode(),
            method="POST",
        )
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def shutdown_and_collect(self):
        self.procs[0].stdin.write("\n")
        self.procs[0].stdin.flush()
        outs = []
        for i, p in enumerate(self.procs):
            p.wait(timeout=120)
            self.drainers[i].join(timeout=30)
            assert p.returncode == 0, (
                f"rank {i} failed:\nstdout={''.join(self.out_lines[i])}\n"
                f"stderr={self.stderr_tail(i)}"
            )
            outs.append(json.loads(self.out_lines[i][-1]))
        return outs

    def cleanup(self):
        """Always runs (finally): kills any rank still alive (a no-op
        after a clean shutdown) and removes the stderr temp files."""
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for f in self.errfiles:
            f.close()
            os.unlink(f.name)


def test_lockstep_query_service():
    """Full lockstep SERVICE: rank 0 serves HTTP, workers replay every
    request over the control plane, device work runs SPMD over the
    2-process global mesh, and writes replicate to every rank's holder."""
    import urllib.error
    import urllib.request

    job = _LockstepJob(2)
    try:
        job.wait_ready()
        # Reads: counts over the replicated seed data (4 slices x 2 bits).
        out = job.query('Count(Bitmap(rowID=0, frame="f")) '
                        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))')
        assert out["results"] == [8, 4]  # row bits; shared col 500 per slice
        # Writes: served once over HTTP, replayed on the worker rank.
        out = job.query('SetBit(rowID=0, frame="f", columnID=77) '
                        'SetBit(rowID=0, frame="f", columnID=78, timestamp="2017-03-02T00:00")')
        assert out["results"] == [True, True]
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [10]
        # TopN with a src bitmap through the SERVICE: candidate scoring
        # rides the multi-process engine scorer (shard_map + allgather)
        # on every rank, in lockstep.
        out = job.query('TopN(Bitmap(rowID=0, frame="f"), frame="f", n=2)')
        pairs = out["results"][0]
        assert pairs and pairs[0]["id"] == 0 and pairs[0]["count"] == 10
        # Error path: rank 0 reports, workers stay in lockstep.
        req = urllib.request.Request(
            f"http://127.0.0.1:{job.http}/index/g/query",
            data=b'Bitmap(rowID=1, frame="nope")',
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [10]

        outs = job.shutdown_and_collect()
    finally:
        # finally (not except Exception): pytest.fail raises a
        # BaseException subclass, and ranks blocked on the coordinator
        # barrier must never outlive the test.
        job.cleanup()
    by_pid = {o["pid"]: o for o in outs}
    # Both ranks converged: seed 8 bits + 2 served writes.
    assert by_pid[0]["probe"] == by_pid[1]["probe"] == 10
    # The timestamped write landed in both ranks' time views.
    assert by_pid[0]["range_probe"] == by_pid[1]["range_probe"] == 1


def test_lockstep_fail_stop_on_dead_worker(tmp_path):
    """A broken control connection degrades the service: the failing
    request errors and every subsequent request is refused (replicas can
    no longer be guaranteed identical)."""
    import socket as socket_mod

    import pytest as _pytest

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService
    from pilosa_tpu.pilosa import PilosaError

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("g")
    idx.create_frame("f", FrameOptions())
    idx.frame("f").set_bit("standard", 1, 3)
    svc = LockstepService(h, control_addr=("127.0.0.1", 0))
    # Healthy single-rank service answers.
    assert svc._execute("g", 'Count(Bitmap(rowID=1, frame="f"))') == [1]
    # Inject a dead worker connection: the next request must degrade.
    a, b = socket_mod.socketpair()
    b.close()
    svc._workers.append(a)
    with _pytest.raises(PilosaError, match="degraded"):
        svc._execute("g", 'Count(Bitmap(rowID=1, frame="f"))')
    with _pytest.raises(PilosaError, match="degraded"):
        svc._execute("g", 'Count(Bitmap(rowID=1, frame="f"))')
    a.close()
    h.close()


def test_lockstep_three_ranks():
    """Three-rank lockstep job: two workers ack and replay, reads shard
    over 6 virtual devices, writes replicate everywhere."""
    job = _LockstepJob(3)
    # Workers seed max(4, 2*nprocs) = 6 slices x 2 bits/row (the slice
    # axis stays divisible by the 6-device global mesh).
    try:
        job.wait_ready()
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [12]
        assert job.query('SetBit(rowID=0, frame="f", columnID=321)')["results"] == [True]
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [13]
        outs = job.shutdown_and_collect()
    finally:
        # finally (not except Exception): pytest.fail raises a
        # BaseException subclass, and ranks blocked on the coordinator
        # barrier must never outlive the test.
        job.cleanup()
    assert {o["probe"] for o in outs} == {13}  # all three ranks converged


def test_lockstep_pipelined_concurrent_clients():
    """Concurrent HTTP clients against the pipelined lockstep service:
    N requests in flight on the control plane, execution still one total
    order on every rank — results correct, replicated writes convergent."""
    from concurrent.futures import ThreadPoolExecutor

    svc = _LockstepJob(2)
    try:
        svc.wait_ready()
        q_read = 'Count(Bitmap(rowID=0, frame="f"))'
        base = svc.query(q_read)["results"][0]
        # 40 interleaved reads + writes from 6 concurrent clients.
        wcols = list(range(700, 720))
        jobs = [q_read] * 20 + [
            f'SetBit(rowID=0, frame="f", columnID={c})' for c in wcols
        ]
        import random

        random.Random(3).shuffle(jobs)
        with ThreadPoolExecutor(6) as pool:
            outs = list(pool.map(svc.query, jobs))
        for q, o in zip(jobs, outs):
            assert "results" in o, (q, o)
        # All writes landed exactly once.
        after = svc.query(q_read)["results"][0]
        assert after == base + len(wcols)
        outs = svc.shutdown_and_collect()
        # Every rank's replicated holder converged to the same state.
        assert outs[0]["probe"] == outs[1]["probe"] == after
    finally:
        svc.cleanup()


def test_lockstep_four_ranks_replica_mesh():
    """Four-rank lockstep job (8 global devices): reads and replicated
    writes converge on every rank, and the post-run collective probe
    runs a (4, 2) slice x replica ReplicaMesh computation over the
    GLOBAL mesh whose counts must equal each rank's local ground truth
    (cluster.go:220-240 ReplicaN analog at job scale)."""
    job = _LockstepJob(4)
    # Workers seed max(4, 2*nprocs) = 8 slices x 2 bits/row.
    try:
        job.wait_ready(timeout=240)
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [16]
        assert job.query('SetBit(rowID=0, frame="f", columnID=444)')["results"] == [True]
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [17]
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    assert {o["probe"] for o in outs} == {17}  # all four ranks converged
    # The (4,2) replica-mesh collective ran on every rank and agreed.
    rp = {o["replica_probe"] for o in outs}
    assert len(rp) == 1 and rp.pop() > 0


def test_lockstep_batch_error_isolation():
    """Request coalescing must ISOLATE per-request errors: a stream of
    interleaved bad requests (unknown frame — a deterministic
    PilosaError) and good reads/writes from concurrent clients gets
    coalesced into batch replay entries, and every bad request errors on
    its own while its batch siblings succeed, ranks stay in lockstep,
    and the service keeps serving afterwards."""
    import urllib.error
    from concurrent.futures import ThreadPoolExecutor

    job = _LockstepJob(2)
    try:
        job.wait_ready()
        q_read = 'Count(Bitmap(rowID=0, frame="f"))'
        base = job.query(q_read)["results"][0]

        def run(q):
            try:
                return ("ok", job.query(q)["results"])
            except urllib.error.HTTPError as e:
                return ("err", e.code)

        wcols = list(range(800, 810))
        jobs = (
            [q_read] * 10
            + ['Bitmap(rowID=1, frame="nope")'] * 10
            + [f'SetBit(rowID=0, frame="f", columnID={c})' for c in wcols]
        )
        import random

        random.Random(7).shuffle(jobs)
        with ThreadPoolExecutor(8) as pool:
            outs = list(pool.map(run, jobs))
        # Every bad request got ITS OWN 400; every good one succeeded.
        by_q = list(zip(jobs, outs))
        assert all(o == ("err", 400) for q, o in by_q if "nope" in q)
        assert all(o[0] == "ok" for q, o in by_q if "nope" not in q), by_q
        # The service is still healthy and the writes all landed once.
        after = job.query(q_read)["results"][0]
        assert after == base + len(wcols)
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    # Both ranks skipped the bad requests identically and converged.
    assert outs[0]["probe"] == outs[1]["probe"] == after


def test_lockstep_coalescing_batches_requests():
    """With coalescing forced to batches of one
    (PILOSA_TPU_LOCKSTEP_COALESCE=1) the service must behave exactly like
    the per-request replay — the env knob is the A/B lever the
    lockstep_coalesce bench uses."""
    job = _LockstepJob(2, env_extra={"PILOSA_TPU_LOCKSTEP_COALESCE": "1"})
    try:
        job.wait_ready()
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [8]
        assert job.query('SetBit(rowID=0, frame="f", columnID=345)')["results"] == [True]
        assert job.query('Count(Bitmap(rowID=0, frame="f"))')["results"] == [9]
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    assert {o["probe"] for o in outs} == {9}


def test_lockstep_expired_deadline_dropped_identically():
    """An EXPIRED request (X-Pilosa-Deadline-Ms: 0) must be dropped
    identically on every rank: rank 0 marks it expired ONCE at ship
    time, the flag rides the batch entry, and every rank skips it
    before execution — the client gets a 504, batch siblings (reads and
    writes from concurrent clients) are unaffected, and the replicated
    holders stay convergent (the expired write landed on NO rank)."""
    import urllib.error
    from concurrent.futures import ThreadPoolExecutor

    job = _LockstepJob(2)
    try:
        job.wait_ready()
        q_read = 'Count(Bitmap(rowID=0, frame="f"))'
        base = job.query(q_read)["results"][0]

        def run(args):
            q, hdrs = args
            try:
                return ("ok", job.query(q, headers=hdrs)["results"])
            except urllib.error.HTTPError as e:
                return ("err", e.code)

        expired_hdr = {"X-Pilosa-Deadline-Ms": "0"}
        wcols = list(range(600, 610))
        jobs = (
            [(q_read, None)] * 10
            # Expired WRITES: dropped on every rank or the replicas
            # diverge (a rank that applied one would count extra bits).
            + [(f'SetBit(rowID=0, frame="f", columnID={c})', expired_hdr)
               for c in range(650, 655)]
            + [(f'SetBit(rowID=0, frame="f", columnID={c})', None) for c in wcols]
            # A generous deadline must behave like no deadline at all.
            + [(q_read, {"X-Pilosa-Deadline-Ms": "60000"})] * 5
        )
        import random

        random.Random(11).shuffle(jobs)
        with ThreadPoolExecutor(8) as pool:
            outs = list(pool.map(run, jobs))
        for (q, hdrs), o in zip(jobs, outs):
            if hdrs and hdrs.get("X-Pilosa-Deadline-Ms") == "0":
                assert o == ("err", 504), (q, o)
            else:
                assert o[0] == "ok", (q, o)
        # Only the live writes landed — on BOTH ranks identically.
        after = job.query(q_read)["results"][0]
        assert after == base + len(wcols)
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    assert outs[0]["probe"] == outs[1]["probe"] == after
    # Every rank dropped the same expired requests (workers count drops
    # at replay; rank 0 counts them in _run_batch).
    assert outs[0]["expired"] == outs[1]["expired"] == 5


def test_lockstep_qcache_identical_hit_miss_on_all_ranks():
    """Query result cache under lockstep (PILOSA_TPU_QCACHE=1): hit and
    miss decisions must be IDENTICAL on every rank — they are pure
    functions of replicated state (the request strings ride the batch
    wire, writes replay in the total order, and the service forces the
    rank-local wall-clock admission floor to 0) — so a cache hit skips
    the executor (and its collectives) on EVERY rank at once, never on
    some.  Read-your-writes: a replayed write bumps the same fragment
    generations everywhere, so the next read misses identically and
    reflects the write."""
    job = _LockstepJob(2, env_extra={"PILOSA_TPU_QCACHE": "1"})
    try:
        job.wait_ready()
        q = 'Count(Bitmap(rowID=0, frame="f"))'
        assert job.query(q)["results"] == [8]   # miss, stored
        assert job.query(q)["results"] == [8]   # hit
        assert job.query(q)["results"] == [8]   # hit
        # A write through the service: replayed on every rank, bumps the
        # touched fragment's generation everywhere.
        assert job.query('SetBit(rowID=0, frame="f", columnID=77)')["results"] == [True]
        # Read-your-writes: the next read misses (identically) and
        # serves the post-write count; the one after hits the new entry.
        assert job.query(q)["results"] == [9]   # miss, stored
        assert job.query(q)["results"] == [9]   # hit
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    by_pid = {o["pid"]: o for o in outs}
    # Every rank made the same decisions: 3 hits, 2 misses, 2 stores.
    for k, want in (("qcache_hits", 3), ("qcache_misses", 2), ("qcache_stores", 2)):
        assert by_pid[0][k] == by_pid[1][k] == want, (k, outs)
    # Replicated holders stayed convergent through cached serving.
    assert by_pid[0]["probe"] == by_pid[1]["probe"] == 9


def test_lockstep_trace_sampling_decided_on_rank0():
    """Request tracing under lockstep (PILOSA_TPU_TRACE_SAMPLE_RATE=1):
    the sampling decision is made ONCE on rank 0 at ship time and rides
    the batch wire entry as a per-request ``trace`` flag — every rank
    counts the SAME flags (never its own RNG), so the ranks agree on
    exactly which requests were sampled.  Rank 0 records each traced
    request's phases (queue/ship/execute — ship covers the worker
    fan-out + receipt-ack barrier) into its tracer ring; workers record
    nothing (tracing never changes execution)."""
    job = _LockstepJob(2, env_extra={"PILOSA_TPU_TRACE_SAMPLE_RATE": "1"})
    try:
        job.wait_ready()
        q = 'Count(Bitmap(rowID=0, frame="f"))'
        n = 6
        for i in range(n - 1):
            assert job.query(q)["results"] == [8]
        # The force-header path composes: still one ship-time decision.
        assert job.query(q, headers={"X-Pilosa-Trace": "1"})["results"] == [8]
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    by_pid = {o["pid"]: o for o in outs}
    # Every rank observed the same sampling decisions off the wire.
    assert by_pid[0]["traced"] == by_pid[1]["traced"] == n
    # Only rank 0 recorded spans, with the lockstep phases present.
    assert by_pid[0]["trace_ring"] == n
    assert by_pid[1]["trace_ring"] == 0
    assert {"lockstep.queue", "lockstep.ship", "lockstep.execute"} <= set(
        by_pid[0]["trace_phases"]
    )


def test_lockstep_tenant_resolved_on_rank0():
    """Multi-tenant accounting under lockstep: the tenant is resolved
    ONCE on rank 0 at ship time (X-Pilosa-Tenant header, else the
    [tenancy] map, else the index name) and rides the batch wire entry
    — every rank tallies the SAME per-tenant counts off the wire, never
    re-resolving locally.  An expired request still bills its tenant
    (the expired flag and the tenant ride the same entry), so per-tenant
    expired counts agree across ranks too."""
    import urllib.error

    job = _LockstepJob(
        2, env_extra={"PILOSA_TPU_TENANCY_MAP": "g=gold"}
    )
    try:
        job.wait_ready()
        q = 'Count(Bitmap(rowID=0, frame="f"))'
        # Header wins over the map: these bill "acme".
        for _ in range(3):
            assert job.query(q, headers={"X-Pilosa-Tenant": "acme"})[
                "results"
            ] == [8]
        # No header: the map sends index "g" to tenant "gold".
        for _ in range(4):
            assert job.query(q)["results"] == [8]
        # An expired acme request: dropped on every rank AND billed to
        # acme on every rank — flag and tenant ride the same entry.
        try:
            job.query(
                q,
                headers={
                    "X-Pilosa-Tenant": "acme",
                    "X-Pilosa-Deadline-Ms": "0",
                },
            )
            raise AssertionError("expired request should 504")
        except urllib.error.HTTPError as e:
            assert e.code == 504
        outs = job.shutdown_and_collect()
    finally:
        job.cleanup()
    by_pid = {o["pid"]: o for o in outs}
    # Both ranks tallied identical per-tenant counts off the wire.
    assert by_pid[0]["tenants"] == by_pid[1]["tenants"], outs
    assert by_pid[0]["tenants"] == {
        "acme": {"requests": 4, "expired": 1},
        "gold": {"requests": 4, "expired": 0},
    }, outs


def test_replica_router_over_two_lockstep_groups():
    """Replica serving groups at full depth: TWO 2-rank lockstep jobs
    (groups g0/g1, identities via PILOSA_TPU_REPLICA_GROUP) behind one
    ReplicaRouter.  Writes through the router fan to BOTH groups (each
    group replays them on every rank — generation vectors advance
    identically everywhere); reads spread across groups and see every
    acked write; killing one group's WORKER rank degrades that group
    (its control plane fail-stops), the router fails reads over to the
    survivor and refuses writes 503 until the set is quorate."""
    import urllib.error

    from pilosa_tpu.replica import GROUP_HEADER, ReplicaRouter
    from pilosa_tpu.stats import ExpvarStatsClient

    g0 = _LockstepJob(2, env_extra={"PILOSA_TPU_REPLICA_GROUP": "g0@1"})
    g1 = _LockstepJob(2, env_extra={"PILOSA_TPU_REPLICA_GROUP": "g1@1"})
    router = None
    try:
        g0.wait_ready()
        g1.wait_ready()
        stats = ExpvarStatsClient()
        router = ReplicaRouter(
            [f"g0=127.0.0.1:{g0.http}", f"g1=127.0.0.1:{g1.http}"],
            probe_interval_s=0.2, stats=stats,
        ).serve()

        def via_router(q, timeout=60):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/index/g/query",
                data=q.encode(), method="POST",
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read()), resp.headers.get(GROUP_HEADER)

        q_read = 'Count(Bitmap(rowID=0, frame="f"))'
        # Both groups seeded identically (8 slices x 1 bit for row 0... the
        # worker seeds 2 bits/row over max(4, 2*nprocs)=4 slices): read
        # through the router agrees with a direct read on either group.
        want = g0.query(q_read)["results"]
        assert g1.query(q_read)["results"] == want
        out, grp = via_router(q_read)
        assert out["results"] == want and grp in ("g0@1", "g1@1")

        # A write through the router lands on BOTH groups (and, inside
        # each group, replays on every rank over the control plane).
        out, grp = via_router('SetBit(rowID=0, frame="f", columnID=901)')
        assert out["results"] == [True] and grp == "all"
        after = want[0] + 1
        assert g0.query(q_read)["results"] == [after]
        assert g1.query(q_read)["results"] == [after]
        # Cross-group read-your-writes: immediate router reads see it on
        # whichever group serves (round-robin spreads the ties).
        served = set()
        for _ in range(4):
            out, grp = via_router(q_read)
            assert out["results"] == [after]
            served.add(grp)
        assert served == {"g0@1", "g1@1"}

        # Kill g1's WORKER rank: g1's control plane fail-stops on the
        # next shipped entry, the router marks it unhealthy and keeps
        # reads serving from g0.
        g1.procs[1].kill()
        ok_reads = 0
        for _ in range(12):
            try:
                out, grp = via_router(q_read, timeout=30)
            except (urllib.error.HTTPError, urllib.error.URLError, OSError):
                continue  # the probe that tripped the degrade
            assert out["results"] == [after]
            ok_reads += 1
        assert ok_reads >= 8, "reads stopped serving after one group died"
        g1_state = router.groups[1]
        assert not g1_state.healthy
        # Writes refuse while non-quorate — g0 is NOT advanced past g1.
        try:
            via_router('SetBit(rowID=0, frame="f", columnID=902)', timeout=30)
            assert False, "write acked against a non-quorate group set"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        assert g0.query(q_read)["results"] == [after]
        assert stats.snapshot().get("replica.failover", 0) >= 1

        outs = g0.shutdown_and_collect()
        # g0's ranks converged on the routed writes.
        assert {o["probe"] for o in outs} == {after}
    finally:
        if router is not None:
            router.close()
        g0.cleanup()
        g1.cleanup()


def test_lockstep_worker_death_mid_stream():
    """A worker rank SIGKILLed MID-REQUEST-STREAM: the in-flight or next
    request errors, every subsequent request is refused (the service
    cannot guarantee replica convergence anymore — fail-stop,
    executor.go:1147-1159's failure handling at the lockstep layer), and
    rank 0 itself stays alive and responsive to the refusal."""
    import urllib.error

    job = _LockstepJob(2)
    try:
        job.wait_ready()
        q = 'Count(Bitmap(rowID=0, frame="f"))'
        base = job.query(q)["results"][0]
        assert base > 0
        # Kill the worker rank mid-stream (CPU gloo job — no TPU grant
        # to leak), then keep issuing requests until the degrade bites.
        job.procs[1].kill()
        failed = False
        for _ in range(20):
            try:
                job.query(
                    f'SetBit(rowID=0, frame="f", columnID={900 + _})', timeout=30
                )
            except (urllib.error.HTTPError, urllib.error.URLError, OSError):
                failed = True
                break
        assert failed, "service kept acking writes after a replica died"
        # Fail-stop: every subsequent request is refused.
        for _ in range(3):
            try:
                job.query(q, timeout=30)
                assert False, "degraded service answered a read"
            except (urllib.error.HTTPError, urllib.error.URLError, OSError):
                pass
        assert job.procs[0].poll() is None, "rank 0 died with the worker"
    finally:
        job.cleanup()
