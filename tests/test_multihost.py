"""Multi-host mesh tests: REAL multi-process jax.distributed jobs.

The analog of the reference's two-real-servers tests
(server/server_test.go MustRunMain + TestMain_SendReceiveMessage), but
for the TPU-native data plane: two OS processes join one jax.distributed
job over a gloo CPU backend, each contributes only its own slice shards,
and the sharded kernels produce globally-correct results through
cross-process collectives.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh():
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    # The workers pin their own platform/device config (init_multihost);
    # strip the suite's CPU pin so the worker exercises the production
    # init path, and drop PYTHONPATH so a TPU-plugin site dir can't grab
    # the job's devices.
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO  # repo only: a TPU-plugin site dir must not grab devices
    env["XLA_FLAGS"] = ""  # workers set their own device count

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            cwd=REPO,
            env=env,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out (coordinator barrier hang?)")
        assert p.returncode == 0, f"worker failed:\nstdout={out}\nstderr={err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        assert o["global_devices"] == 4
        assert o["local_devices"] == 2
        assert o["count_ok"], o
        assert o["union_ok"], o
        # Full PQL executor in SPMD lockstep over the global mesh agrees
        # with the numpy engine on every process.
        assert o["exec_ok"], o
    # Both processes computed the SAME global count from disjoint shards.
    assert by_pid[0]["count"] == by_pid[1]["count"]
    assert by_pid[0]["exec_results"] == by_pid[1]["exec_results"]
    # Slice ownership is disjoint and covers the stack.
    assert sorted(by_pid[0]["owned"] + by_pid[1]["owned"]) == list(range(8))
