"""Unit tests for the paged device row pool (rowpool.py).

The pool is the round-2 replacement for the fixed row-matrix cache: rows
page in on demand, LRU rows page out, capacity doubles up to a budget.
Ground truth is a plain dict of host rows; the pool must agree under
arbitrary interleavings of acquire / mutation (generation bumps).
"""

from __future__ import annotations

import numpy as np
import pytest

from pilosa_tpu.engine import NumpyEngine
from pilosa_tpu.rowpool import DeviceRowPool, chunk_queries, pool_capacity

W = 16  # small word count: pool logic is W-agnostic


def make_pool(n_slices=2, cap_max=8, rows=None, fetch_log=None):
    rows = rows if rows is not None else {}

    def fetch(row_ids, slice_idxs):
        if fetch_log is not None:
            fetch_log.append((tuple(row_ids), tuple(slice_idxs)))
        block = np.zeros((len(slice_idxs), len(row_ids), W), dtype=np.uint32)
        for bi, si in enumerate(slice_idxs):
            for k, r in enumerate(row_ids):
                block[bi, k] = rows.get((si, r), np.zeros(W, np.uint32))
        return block

    return DeviceRowPool(NumpyEngine(), n_slices, W, fetch, cap_max=cap_max), rows


def fill_rows(rng, n_slices, row_ids):
    return {
        (si, r): rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
        for si in range(n_slices)
        for r in row_ids
    }


def check(pool, rows, want, gens):
    id_pos, matrix, box = pool.acquire(want, gens)
    for r in want:
        for si in range(pool.n_slices):
            np.testing.assert_array_equal(
                matrix[si, id_pos[r]], rows.get((si, r), np.zeros(W, np.uint32))
            )
    return id_pos, matrix, box


def test_grow_and_hit():
    rng = np.random.default_rng(1)
    rows = fill_rows(rng, 2, range(10))
    pool, _ = make_pool(rows=rows, cap_max=16)
    g = (1, 1)
    check(pool, rows, [0, 1], g)
    assert pool.cap == 2
    check(pool, rows, [2, 3, 4], g)
    assert pool.cap == 8  # doubled past 5 -> pow2
    # Pure hit: box persists, hits climb.
    _, _, box = pool.acquire([0, 4], g)
    hits = box["hits"]
    _, _, box2 = pool.acquire([1, 2], g)
    assert box2 is box and box2["hits"] == hits + 1
    assert pool.stat_evictions == 0


def test_eviction_lru_order():
    rng = np.random.default_rng(2)
    rows = fill_rows(rng, 2, range(20))
    pool, _ = make_pool(rows=rows, cap_max=4)
    g = (1, 1)
    check(pool, rows, [0, 1, 2, 3], g)
    check(pool, rows, [0, 1], g)  # refresh 0,1 in LRU
    check(pool, rows, [4], g)  # evicts 2 (least recent)
    assert 2 not in pool.slot_of and 4 in pool.slot_of
    assert pool.stat_evictions == 1
    # Evicted row pages back in correctly.
    check(pool, rows, [2], g)
    assert 3 not in pool.slot_of  # 3 was next-least-recent
    # The request's own rows are never chosen as victims.
    check(pool, rows, [5, 6, 7, 8], g)
    assert all(r in pool.slot_of for r in (5, 6, 7, 8))


def test_acquire_too_large_raises():
    pool, _ = make_pool(cap_max=4)
    with pytest.raises(ValueError, match="chunk the batch"):
        pool.acquire(list(range(5)), (1, 1))


def test_snapshot_isolation_across_eviction():
    """A reader's (id_pos, matrix) snapshot stays valid after later
    acquires evict its rows (functional updates: new array each time)."""
    rng = np.random.default_rng(3)
    rows = fill_rows(rng, 2, range(8))
    pool, _ = make_pool(rows=rows, cap_max=4)
    g = (1, 1)
    id_pos, matrix, _ = pool.acquire([0, 1, 2, 3], g)
    snap = {r: (id_pos[r], np.array([matrix[si, id_pos[r]] for si in range(2)])) for r in (0, 1)}
    pool.acquire([4, 5, 6], g)  # evicts some of 0..3
    for r, (slot, want_rows) in snap.items():
        for si in range(2):
            np.testing.assert_array_equal(matrix[si, slot], want_rows[si])


def test_stale_slice_plane_refresh():
    rng = np.random.default_rng(4)
    rows = fill_rows(rng, 3, range(6))
    pool, live = make_pool(n_slices=3, rows=rows, cap_max=8)
    g1 = (1, 1, 1)
    check(pool, rows, [0, 1, 2], g1)
    box1 = pool.box
    # Mutate slice 1's data for rows 0 and 5; bump slice 1's generation.
    live[(1, 0)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    g2 = (1, 2, 1)
    id_pos, matrix, box2 = check(pool, rows, [0, 1], g2)
    assert box2 is not box1  # content changed -> fresh box (Gram dies)
    # Unchanged slices kept their planes; changed slice reflects new data.
    np.testing.assert_array_equal(matrix[1, id_pos[0]], live[(1, 0)])
    assert pool.stat_resets == 0


def test_stale_refresh_over_budget_resets(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_POOL_REFRESH_BYTES", "8")  # force reset
    rng = np.random.default_rng(5)
    rows = fill_rows(rng, 2, range(6))
    pool, live = make_pool(rows=rows, cap_max=8)
    check(pool, rows, [0, 1, 2], (1, 1))
    live[(0, 1)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    check(pool, rows, [0, 1, 2], (2, 1))
    assert pool.stat_resets == 1  # repopulated on demand, still correct


def test_box_id_pos_is_full_resident_snapshot():
    rng = np.random.default_rng(6)
    rows = fill_rows(rng, 2, range(6))
    pool, _ = make_pool(rows=rows, cap_max=8)
    g = (1, 1)
    pool.acquire([0, 1, 2], g)
    id_pos, _, box = pool.acquire([1], g)
    assert set(id_pos) == {0, 1, 2}  # full resident set, not just want
    assert box["n_used"] == 3


def test_fifty_thousand_rows_page_through_small_pool():
    """Rank-cache scale (DefaultCacheSize=50000, frame.go:33-40): 50k
    distinct rows stream through a 512-slot pool; counts stay exact."""
    pool, rows = make_pool(n_slices=1, cap_max=512)
    # Virtual rows: row r has word pattern r (cheap, deterministic).
    def fetch(row_ids, slice_idxs):
        block = np.zeros((len(slice_idxs), len(row_ids), W), dtype=np.uint32)
        for k, r in enumerate(row_ids):
            block[:, k, :] = np.uint32(r)
        return block

    pool.fetch = fetch
    g = (1,)
    rng = np.random.default_rng(7)
    seen = 0
    for _ in range(100):
        want = sorted(set(rng.integers(0, 50000, size=256).tolist()))
        id_pos, matrix, _ = pool.acquire(want, g)
        sample = want[:: max(1, len(want) // 8)]
        for r in sample:
            assert int(matrix[0, id_pos[r], 0]) == r
        seen += len(want)
    assert pool.cap <= 512
    assert pool.stat_evictions > 20000  # genuinely paged, not grown


def test_chunk_queries():
    qs = [(0, 1), (1, 2), (3, 4), (5, 6), (0, 5)]
    chunks = chunk_queries(qs, lambda q: q, 4)
    assert [len(c) for c in chunks] == [2, 2, 1]
    assert sum(chunks, []) == qs  # order preserved
    with pytest.raises(ValueError):
        chunk_queries([(0, 1, 2)], lambda q: q, 2)
    assert chunk_queries([], lambda q: q, 4) == []


def test_pool_capacity_budget():
    assert pool_capacity(16, 32768, budget_bytes=2 << 30) == 1024
    assert pool_capacity(1024, 32768, budget_bytes=2 << 30) == 16


def test_chunk_queries_oversize_ok():
    qs = [(0, 1), tuple(range(10)), (2, 3)]
    chunks = chunk_queries(qs, lambda q: q, 4, oversize_ok=True)
    assert chunks == [[(0, 1)], [tuple(range(10))], [(2, 3)]]


def _np_gram(pool, rows, resident, n):
    """Ground-truth AND-count Gram over the pool's slot assignment."""
    from pilosa_tpu.roaring import _popcount_words

    g = np.zeros((n, n), dtype=np.int64)
    slot = {r: pool.slot_of[r] for r in resident}
    for a in resident:
        for b in resident:
            c = 0
            for si in range(pool.n_slices):
                wa = rows.get((si, a), np.zeros(W, np.uint32))
                wb = rows.get((si, b), np.zeros(W, np.uint32))
                c += _popcount_words(wa & wb)
            g[slot[a], slot[b]] = c
    return g


def test_acquire_dirty_rows_repairs_in_place():
    """The PATCH lane: a generation bump with a known dirty-row set
    rewrites only those rows' planes, keeps the box (and its Gram/glut)
    alive, and rank-k-updates the Gram to exact counts."""
    rng = np.random.default_rng(7)
    rows = fill_rows(rng, 2, range(4))
    pool, live = make_pool(n_slices=2, rows=rows, cap_max=8)
    id_pos, _, box1 = pool.acquire([0, 1, 2, 3], (1, 1))
    # Seed a warm Gram + glut the way the executor does (bucket = pow2(4)).
    gram = _np_gram(pool, live, [0, 1, 2, 3], 4)
    box1["gram"] = gram
    rs = np.array(sorted(id_pos), dtype=np.int64)
    ps = np.fromiter((id_pos[int(v)] for v in rs), dtype=np.int32, count=len(rs))
    box1["gram_lut"] = (rs, np.ascontiguousarray(gram), ps)
    # Mutate row 2 on slice 1 only; bump slice 1's generation.
    live[(1, 2)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    id_pos2, matrix, box2 = pool.acquire([0, 1], (1, 2), dirty_rows={2})
    assert box2 is box1, "box must survive the patch lane"
    assert pool.stat_repairs == 1 and pool.stat_resets == 0
    # Matrix reflects the new row data; untouched rows kept their planes.
    np.testing.assert_array_equal(matrix[1, id_pos2[2]], live[(1, 2)])
    np.testing.assert_array_equal(matrix[0, id_pos2[0]], live[(0, 0)])
    # The repaired Gram matches a from-scratch recount, and the glut's
    # count table was swapped to it (copy-on-write: the old array is not
    # mutated).
    want = _np_gram(pool, live, [0, 1, 2, 3], 4)
    np.testing.assert_array_equal(box2["gram"], want)
    np.testing.assert_array_equal(box2["gram_lut"][1], want)
    assert box2["gram"] is not gram


def test_acquire_dirty_rows_per_slice_granularity():
    """Per-(row, slice) patch: a {slice: rows} dirty mapping re-fetches
    ONLY the planes actually written — row 2 for slice 1 and row 3 for
    slice 2, not the cross product — and the rank-k Gram repair still
    lands on exact counts."""
    rng = np.random.default_rng(11)
    rows = fill_rows(rng, 3, range(4))
    log: list = []
    pool, live = make_pool(n_slices=3, rows=rows, cap_max=8, fetch_log=log)
    id_pos, _, box1 = pool.acquire([0, 1, 2, 3], (1, 1, 1))
    gram = _np_gram(pool, live, [0, 1, 2, 3], 4)
    box1["gram"] = gram
    rs = np.array(sorted(id_pos), dtype=np.int64)
    ps = np.fromiter((id_pos[int(v)] for v in rs), dtype=np.int32, count=len(rs))
    box1["gram_lut"] = (rs, np.ascontiguousarray(gram), ps)
    # Row 2 written in slice 1; row 3 written in slice 2.
    live[(1, 2)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    live[(2, 3)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    log.clear()
    id_pos2, matrix, box2 = pool.acquire(
        [0, 1], (1, 2, 2), dirty_rows={1: {2}, 2: {3}}
    )
    assert box2 is box1 and pool.stat_repairs == 1 and pool.stat_resets == 0
    # Exactly the two written planes were fetched (in either group order).
    assert sorted(log) == [((2,), (1,)), ((3,), (2,))]
    assert pool.stat_patch_planes == 2
    np.testing.assert_array_equal(matrix[1, id_pos2[2]], live[(1, 2)])
    np.testing.assert_array_equal(matrix[2, id_pos2[3]], live[(2, 3)])
    # Unwritten planes of the dirty rows are untouched.
    np.testing.assert_array_equal(matrix[0, id_pos2[2]], live[(0, 2)])
    want = _np_gram(pool, live, [0, 1, 2, 3], 4)
    np.testing.assert_array_equal(box2["gram"], want)
    np.testing.assert_array_equal(box2["gram_lut"][1], want)


def test_acquire_dirty_dict_slices_share_fetch():
    """Stale slices dirtied with the SAME row set batch into one fetch
    (one transfer per distinct row group, not per slice)."""
    rng = np.random.default_rng(12)
    rows = fill_rows(rng, 3, range(4))
    log: list = []
    pool, live = make_pool(n_slices=3, rows=rows, cap_max=8, fetch_log=log)
    pool.acquire([0, 1, 2], (1, 1, 1))
    live[(0, 1)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    live[(2, 1)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    log.clear()
    id_pos, matrix, _ = pool.acquire([0, 1], (2, 1, 2), dirty_rows={0: {1}, 2: {1}})
    assert log == [((1,), (0, 2))]  # one grouped fetch for both slices
    np.testing.assert_array_equal(matrix[0, id_pos[1]], live[(0, 1)])
    np.testing.assert_array_equal(matrix[2, id_pos[1]], live[(2, 1)])


def test_gram_update_rows_delta_matches_full_recompute():
    """The per-(row, slice) delta form of gram_update_rows (old matrix +
    written slice planes) must agree exactly with the full recompute, on
    the numpy engine and on jax (which pads the restricted slice axis
    with a clean slice)."""
    rng = np.random.default_rng(13)
    S, R = 8, 4
    old = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    new = old.copy()
    dirty_slots = [1, 3]
    dirty_slices = [2, 5]
    for sl in dirty_slots:
        for si in dirty_slices:
            new[si, sl] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)

    from pilosa_tpu.roaring import _popcount_words

    def np_gram(m):
        g = np.zeros((R, R), dtype=np.int64)
        for a in range(R):
            for b in range(R):
                g[a, b] = sum(
                    _popcount_words(m[si, a] & m[si, b]) for si in range(S)
                )
        return g

    gram_old = np_gram(old)
    want = np_gram(new)
    eng = NumpyEngine()
    got = eng.gram_update_rows(
        new, gram_old, dirty_slots, old_matrix=old, slice_idxs=dirty_slices
    )
    np.testing.assert_array_equal(got, want)
    # Full-recompute form agrees too (no delta args).
    np.testing.assert_array_equal(
        eng.gram_update_rows(new, gram_old, dirty_slots), want
    )

    from pilosa_tpu.engine import JaxEngine

    jeng = JaxEngine()
    got_j = jeng.gram_update_rows(
        jeng.matrix(new), gram_old, dirty_slots,
        old_matrix=jeng.matrix(old), slice_idxs=dirty_slices,
    )
    np.testing.assert_array_equal(got_j, want)


def test_gram_update_rows_delta_all_slices_dirty_falls_back():
    """Every slice dirty -> no clean pad slice / no restriction win: both
    engines take the full-recompute path and stay exact."""
    rng = np.random.default_rng(14)
    S, R = 2, 3
    old = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    new = old.copy()
    new[:, 1] = rng.integers(0, 1 << 32, size=(S, W), dtype=np.uint32)

    from pilosa_tpu.roaring import _popcount_words

    def np_gram(m):
        g = np.zeros((R, R), dtype=np.int64)
        for a in range(R):
            for b in range(R):
                g[a, b] = sum(
                    _popcount_words(m[si, a] & m[si, b]) for si in range(S)
                )
        return g

    want = np_gram(new)
    eng = NumpyEngine()
    got = eng.gram_update_rows(
        new, np_gram(old), [1], old_matrix=old, slice_idxs=[0, 1]
    )
    np.testing.assert_array_equal(got, want)


def test_acquire_dirty_rows_nonresident_keeps_box():
    """Writes to rows the pool does not hold need no matrix or Gram work
    at all — the box survives untouched."""
    rng = np.random.default_rng(8)
    rows = fill_rows(rng, 2, range(6))
    pool, live = make_pool(n_slices=2, rows=rows, cap_max=4)
    _, _, box1 = pool.acquire([0, 1], (1, 1))
    live[(0, 5)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    _, _, box2 = pool.acquire([0, 1], (2, 1), dirty_rows={5})
    assert box2 is box1 and pool.stat_repairs == 1


def test_acquire_without_dirty_rows_still_resets_box():
    """No delta information -> the conservative full refresh + box reset
    (the pre-repair behavior) is unchanged."""
    rng = np.random.default_rng(9)
    rows = fill_rows(rng, 2, range(4))
    pool, live = make_pool(n_slices=2, rows=rows, cap_max=8)
    _, _, box1 = pool.acquire([0, 1], (1, 1))
    live[(0, 0)] = rng.integers(0, 1 << 32, size=W, dtype=np.uint32)
    id_pos, matrix, box2 = pool.acquire([0, 1], (2, 1))
    assert box2 is not box1 and pool.stat_repairs == 0
    np.testing.assert_array_equal(matrix[0, id_pos[0]], live[(0, 0)])
