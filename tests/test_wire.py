"""Wire codec tests against golden bytes from the official protobuf library.

The golden constants below were produced by compiling the same schema
(field numbers/types from the reference's internal/*.proto) with protoc
and serializing with google.protobuf — so agreement here means real
reference clients can talk to us.
"""

import numpy as np
import pytest

from pilosa_tpu import wire
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.executor import QueryBitmap
from pilosa_tpu.ops.bitwise import pack_positions

QREQ = bytes.fromhex(
    "0a16436f756e74284269746d617028726f7749443d312929120300010518012203594d442801"
)
BITMAP = bytes.fromhex(
    "0a0801ac028080808020120c0a0661637469766510032801120f0a046e616d6510011a05616c696365"
)
QRESP = bytes.fromhex(
    "122b0a290a0801ac028080808020120c0a0661637469766510032801120f0a046e616d6510011a05616c69"
    "63651202102a120c1a04080710641a0408081032120220011a14080912100a0178100220fdffffffffffff"
    "ffff01"
)
IMPORT = bytes.fromhex("0a01691201661802220201022a02030432060080dea0cb05")
MAXSLICES = bytes.fromhex("0a070a0369647810040a050a01611000")


def test_query_request_golden():
    got = wire.encode_query_request(
        "Count(Bitmap(rowID=1))", slices=[0, 1, 5], column_attrs=True, quantum="YMD", remote=True
    )
    assert got == QREQ
    back = wire.decode_query_request(QREQ)
    assert back == {
        "query": "Count(Bitmap(rowID=1))",
        "slices": [0, 1, 5],
        "column_attrs": True,
        "quantum": "YMD",
        "remote": True,
    }


def test_bitmap_golden():
    got = wire.encode_bitmap([1, 300, 1 << 33], {"active": True, "name": "alice"})
    assert got == BITMAP
    bits, attrs = wire.decode_bitmap(BITMAP)
    assert bits == [1, 300, 1 << 33]
    assert attrs == {"active": True, "name": "alice"}


def test_query_response_golden():
    seg = {0: pack_positions(np.array([1, 300], dtype=np.uint64))}
    bm = QueryBitmap(seg, {"active": True, "name": "alice"})
    # Build the equivalent response with our types (bits 1,300,2^33: use raw encode)
    results = [
        _RawBitmap([1, 300, 1 << 33], {"active": True, "name": "alice"}),
        42,
        [Pair(7, 100), Pair(8, 50)],
        True,
    ]
    got = wire.encode_query_response(
        results=results, column_attr_sets=[(9, {"x": -3})]
    )
    assert got == QRESP
    back = wire.decode_query_response(QRESP)
    assert back["err"] == ""
    assert back["results"][0]["bitmap"]["bits"] == [1, 300, 1 << 33]
    assert back["results"][1]["n"] == 42
    assert back["results"][2]["pairs"] == [{"id": 7, "count": 100}, {"id": 8, "count": 50}]
    assert back["results"][3]["changed"] is True
    assert back["columnAttrSets"] == [{"id": 9, "attrs": {"x": -3}}]


class _RawBitmap(QueryBitmap):
    """QueryBitmap stand-in with explicit global bit values (for testing
    values beyond one slice)."""

    def __init__(self, bits, attrs):
        super().__init__({}, attrs)
        self._bits = bits

    def bits(self):
        return self._bits


def test_import_request_golden():
    got = wire.encode_import_request("i", "f", 2, [1, 2], [3, 4], [0, 1500000000])
    assert got == IMPORT
    back = wire.decode_import_request(IMPORT)
    assert back == {
        "index": "i",
        "frame": "f",
        "slice": 2,
        "rowIDs": [1, 2],
        "columnIDs": [3, 4],
        "timestamps": [0, 1500000000],
    }


def test_max_slices_golden():
    # Map entry order on the wire is unspecified — this constant carries
    # insertion order; the encoder now emits DETERMINISTIC (sorted-key)
    # order like both official encoders, asserted in test_wire_golden.
    assert wire.decode_max_slices_response(MAXSLICES) == {"idx": 4, "a": 0}
    got = wire.encode_max_slices_response({"idx": 4, "a": 0})
    assert wire.decode_max_slices_response(got) == {"idx": 4, "a": 0}


def test_negative_int_attr_roundtrip():
    raw = wire.encode_attr("n", -123456789)
    assert wire.decode_attr(raw) == ("n", -123456789)


def test_float_attr_roundtrip():
    raw = wire.encode_attr("f", 2.75)
    assert wire.decode_attr(raw) == ("f", 2.75)


def test_frame_meta_roundtrip():
    raw = wire.encode_frame_meta("rid", True, "ranked", 1000, "YMDH")
    assert wire.decode_frame_meta(raw) == {
        "rowLabel": "rid",
        "inverseEnabled": True,
        "cacheType": "ranked",
        "cacheSize": 1000,
        "timeQuantum": "YMDH",
    }


def test_block_data_roundtrip():
    raw = wire.encode_block_data_response([1, 2, 3], [9, 8, 7])
    assert wire.decode_block_data_response(raw) == ([1, 2, 3], [9, 8, 7])
    req = wire.encode_block_data_request("i", "f", "standard", 3, 12)
    assert wire.decode_block_data_request(req) == {
        "index": "i",
        "frame": "f",
        "view": "standard",
        "slice": 3,
        "block": 12,
    }


def test_truncation_rejected():
    with pytest.raises(ValueError):
        list(wire.iter_fields(QREQ[:-3]))


def test_import_request_negative_timestamps_large_batch():
    # >= native threshold values incl. negative int64 timestamps must
    # round-trip (regression: native uint64 conversion overflow).
    n = 100
    rows = list(range(n))
    cols = list(range(n))
    ts = [-5] * n
    raw = wire.encode_import_request("i", "f", 0, rows, cols, ts)
    back = wire.decode_import_request(raw)
    assert back["timestamps"] == ts
    assert back["rowIDs"] == rows


def test_wire_decode_fuzz_never_crashes():
    """Random/truncated bytes into every decoder must raise cleanly
    (ValueError/IndexError-family), never hang or hard-crash."""
    import random

    from pilosa_tpu import wire

    decoders = [
        wire.decode_query_request,
        wire.decode_query_response,
        wire.decode_import_request,
        wire.decode_node_status,
    ]
    rng = random.Random(99)
    # structured-ish prefixes: valid messages truncated/corrupted
    seeds = [
        wire.encode_query_request("Count(Bitmap(rowID=1))", slices=[0, 1], remote=True),
        wire.encode_node_status("h:1", "UP", [{"name": "i", "meta": {}, "maxSlice": 1, "frames": []}]),
    ]
    cases = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 60))) for _ in range(300)]
    for s in seeds:
        for _ in range(100):
            cut = rng.randrange(0, len(s) + 1)
            mutated = bytearray(s[:cut])
            if mutated and rng.random() < 0.5:
                mutated[rng.randrange(len(mutated))] ^= 1 << rng.randrange(8)
            cases.append(bytes(mutated))
    for data in cases:
        for dec in decoders:
            try:
                dec(data)
            except Exception as e:
                assert not isinstance(e, (SystemExit, MemoryError)), (dec, data[:20])
