"""Native write request lane (pn_write_batch): differential equivalence
against the Python lanes, structural-fallback coverage, and serving
continuity across the snapshot swap.

The lane's contract: for any canonical all-SetBit/ClearBit request body
it must be INDISTINGUISHABLE from the general Python path — identical
per-call changed results, identical logical storage bytes, a WAL whose
replay converges to the identical fragment, and an advanced write
generation — while anything outside the canonical shape falls back with
the general path's exact errors.
"""

import io
import os
import tempfile

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.pilosa import ErrTooManyWrites, PilosaError
from pilosa_tpu.stats import ExpvarStatsClient

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no compiler)"
)


def _build(tmp, env=None, stats=None, **kw):
    """Fresh holder + executor; env tweaks land BEFORE the executor's
    lazy env-gate reads."""
    for k in ("PILOSA_TPU_NO_WRITELANE", "PILOSA_TPU_NO_FASTWRITE"):
        os.environ.pop(k, None)
    os.environ.update(env or {})
    h = Holder(tmp, stats=stats)
    h.open()
    h.create_index("i").create_frame("f", FrameOptions())
    ex = Executor(h, engine="numpy", qcache=None, **kw)
    return h, ex


def _cleanup_env():
    for k in ("PILOSA_TPU_NO_WRITELANE", "PILOSA_TPU_NO_FASTWRITE"):
        os.environ.pop(k, None)


def _gen_stream(seed: int, n: int = 300):
    """Seeded mixed write stream: singletons, batches, clears, dups."""
    rng = np.random.default_rng(seed)
    queries = []
    i = 0
    while i < n:
        b = int(rng.choice([1, 1, 1, 2, 8, 64]))
        calls = []
        for _ in range(b):
            r = int(rng.integers(0, 40))
            c = int(rng.integers(0, 1 << 20))
            t = "SetBit" if rng.random() < 0.75 else "ClearBit"
            calls.append(f'{t}(rowID={r}, frame="f", columnID={c})')
            i += 1
        queries.append("".join(calls))
    return queries


def _run_stream(tmp, queries, env):
    h, ex = _build(tmp, env=env)
    try:
        results = [ex.execute("i", q) for q in queries]
        frag = h.fragment("i", "f", "standard", 0)
        buf = io.BytesIO()
        frag.write_to(buf)
        gen = frag.generation
        data_path = frag.path
    finally:
        h.close()
        _cleanup_env()
    # Reopen from disk: snapshot + WAL replay must converge to the same
    # storage whichever lane wrote it (crash-recovery equivalence).
    h2 = Holder(tmp)
    h2.open()
    try:
        frag2 = h2.fragment("i", "f", "standard", 0)
        buf2 = io.BytesIO()
        frag2.write_to(buf2)
    finally:
        h2.close()
    with open(data_path, "rb") as f:
        file_bytes = f.read()
    return results, buf.getvalue(), buf2.getvalue(), gen, file_bytes


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_differential_native_vs_python_lanes(seed):
    """Identical seeded streams through the native lane and the general
    Python lane: identical results, identical logical storage, and
    disk-replay convergence; both lanes advanced the generation."""
    queries = _gen_stream(seed)
    with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
        res_n, bytes_n, replay_n, gen_n, _file_n = _run_stream(
            d1, queries, {"PILOSA_TPU_NO_FASTWRITE": "1"}
        )
        res_p, bytes_p, replay_p, gen_p, _file_p = _run_stream(
            d2, queries,
            {"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"},
        )
    assert res_n == res_p
    assert bytes_n == bytes_p, "live storage bytes diverged"
    assert replay_n == replay_p == bytes_p, "disk replay diverged"
    # Both lanes advanced generations past creation (exact counts are
    # lane-specific: the native lane bumps once per batch).
    assert gen_n > 0 and gen_p > 0


def test_wal_frames_replay_equivalent():
    """Parsing each lane's on-disk file (snapshot body + checksummed
    WAL op frames, replayed by from_bytes) converges to identical
    storage.  Append ORDER may legitimately differ for all-set batches
    (call order in the native lane, sorted-vectorized in the Python
    batch path) — replay equivalence is the durable contract."""
    from pilosa_tpu.roaring import Bitmap

    queries = _gen_stream(9, n=200)
    with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
        _, _, _, _, file_n = _run_stream(d1, queries, {"PILOSA_TPU_NO_FASTWRITE": "1"})
        _, _, _, _, file_p = _run_stream(
            d2, queries,
            {"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"},
        )

    def replayed(data):
        return Bitmap.from_bytes(data).to_array().tolist()

    assert replayed(file_n) == replayed(file_p)


if HAVE_HYPOTHESIS:

    @settings(max_examples=20, deadline=None)
    @given(st.integers(min_value=0, max_value=10_000))
    def test_differential_hypothesis(seed):
        queries = _gen_stream(seed, n=60)
        with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
            res_n, bytes_n, _, _, _ = _run_stream(
                d1, queries, {"PILOSA_TPU_NO_FASTWRITE": "1"}
            )
            res_p, bytes_p, _, _, _ = _run_stream(
                d2, queries,
                {"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"},
            )
        assert res_n == res_p and bytes_n == bytes_p


def test_native_lane_engages_and_counts():
    """Canonical batches actually ride the native crossing (counters
    prove it — a silently-falling-back lane would still pass the
    differential tests)."""
    stats = ExpvarStatsClient()
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(d, env={"PILOSA_TPU_NO_FASTWRITE": "1"}, stats=stats)
        try:
            # First batch first-touches containers (scalar lane), the
            # repeat batch hits the armed table (native apply).
            body = "".join(
                f'SetBit(rowID=1, frame="f", columnID={c})' for c in range(64)
            )
            ex.execute("i", body)
            body2 = "".join(
                f'SetBit(rowID=1, frame="f", columnID={c + 64})' for c in range(64)
            )
            ex.execute("i", body2)
        finally:
            h.close()
            _cleanup_env()
    snap = stats.snapshot()
    native_n = sum(v for k, v in snap.items() if k.startswith("writelane.native_batches"))
    assert native_n >= 1, snap


def test_mixed_set_clear_batch_order_preserved():
    """In-batch SetBit-then-ClearBit of the SAME bit must land cleared
    (call order), and the reverse set — through the native lane."""
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(d, env={"PILOSA_TPU_NO_FASTWRITE": "1"})
        try:
            # Seed the container so the batch applies natively.
            ex.execute("i", 'SetBit(rowID=1, frame="f", columnID=10)'
                            'SetBit(rowID=1, frame="f", columnID=11)')
            res = ex.execute(
                "i",
                'SetBit(rowID=1, frame="f", columnID=5)'
                'ClearBit(rowID=1, frame="f", columnID=5)'
                'ClearBit(rowID=1, frame="f", columnID=10)'
                'SetBit(rowID=1, frame="f", columnID=10)',
            )
            assert res == [True, True, True, True]
            out = ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
            assert out == [2]  # 10 (re-set) and 11; 5 cleared
        finally:
            h.close()
            _cleanup_env()


def test_non_canonical_bodies_keep_general_errors():
    """Anything outside the canonical shape falls back and raises the
    general path's exact error (same type and message with the lane on
    or off)."""
    bad = [
        'SetBit(rowID=1, frame="nope", columnID=2)',   # unknown frame
        'SetBit(colID=1, frame="f", rowID=2)',         # wrong labels
        'SetBit(rowID=1, frame="f")',                  # missing arg
        'SetBit(rowID=1, frame="f", columnID=2, timestamp="x")',
    ]
    def errors(env):
        out = []
        with tempfile.TemporaryDirectory() as d:
            h, ex = _build(d, env=env)
            try:
                for q in bad:
                    try:
                        ex.execute("i", q)
                        out.append(None)
                    except Exception as e:  # noqa: BLE001 — compared below
                        out.append((type(e).__name__, str(e)))
            finally:
                h.close()
                _cleanup_env()
        return out

    assert errors({"PILOSA_TPU_NO_FASTWRITE": "1"}) == errors(
        {"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"}
    )


def test_max_writes_enforced_before_any_mutation():
    """An over-limit batch raises ErrTooManyWrites WITHOUT applying any
    prefix — the lane must check before the crossing."""
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(
            d, env={"PILOSA_TPU_NO_FASTWRITE": "1"}, max_writes_per_request=4
        )
        try:
            body = "".join(
                f'SetBit(rowID=1, frame="f", columnID={c})' for c in range(8)
            )
            with pytest.raises(ErrTooManyWrites):
                ex.execute("i", body)
            assert ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))') == [0]
        finally:
            h.close()
            _cleanup_env()


def test_foreign_write_invalidates_armed_table():
    """A write OUTSIDE the lane (direct frame mutation) restructures
    containers; the armed table must revalidate, never serve stale
    buffer addresses."""
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(d, env={"PILOSA_TPU_NO_FASTWRITE": "1"})
        try:
            ex.execute("i", 'SetBit(rowID=1, frame="f", columnID=1)'
                            'SetBit(rowID=1, frame="f", columnID=2)')
            fr = h.frame("i", "f")
            for c in range(100, 160):
                fr.set_bit("standard", 1, c)  # foreign writer
            res = ex.execute(
                "i",
                'SetBit(rowID=1, frame="f", columnID=3)'
                'SetBit(rowID=1, frame="f", columnID=100)',  # dup of foreign
            )
            assert res == [True, False]
            assert ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))') == [63]
        finally:
            h.close()
            _cleanup_env()


def test_snapshot_swap_serving_continuity():
    """Snapshot re-attach parity under the write lane: a write burst
    through the native lane crosses the fragment's snapshot trigger —
    storage is rewritten, the mmap re-attaches to the NEW file, the
    armed table is dropped — and both writes and reads keep serving
    correctly across the swap (the lane re-arms on the fresh storage)."""
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(d, env={"PILOSA_TPU_NO_FASTWRITE": "1"})
        try:
            ex.execute("i", 'SetBit(rowID=1, frame="f", columnID=0)')
            frag = h.fragment("i", "f", "standard", 0)
            frag.max_opn = 40  # explicit trigger: honored exactly
            frag._opn_trigger = 0  # drop the cached pre-change trigger
            storage_before = frag.storage
            expect = {0}
            c = 1
            for _ in range(30):
                body = "".join(
                    f'SetBit(rowID=1, frame="f", columnID={c + j})'
                    for j in range(8)
                )
                expect.update(range(c, c + 8))
                c += 8
                ex.execute("i", body)
                out = ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
                assert out == [len(expect)]  # serving continuity per burst
            assert frag.storage is not storage_before, "snapshot never swapped"
            if frag._mmap_enabled():
                assert frag._storage_map is not None, "mmap not re-attached"
            # Post-swap: the lane re-armed and still applies natively.
            res = ex.execute(
                "i",
                'SetBit(rowID=1, frame="f", columnID=5)'  # dup
                f'SetBit(rowID=1, frame="f", columnID={c})',
            )
            assert res == [False, True]
            assert ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))') == [
                len(expect) + 1
            ]
        finally:
            h.close()
            _cleanup_env()


def test_env_gate_disables_lane():
    """PILOSA_TPU_NO_WRITELANE=1 keeps everything on the Python lanes
    (no native batch counters)."""
    stats = ExpvarStatsClient()
    with tempfile.TemporaryDirectory() as d:
        h, ex = _build(
            d,
            env={"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"},
            stats=stats,
        )
        try:
            body = "".join(
                f'SetBit(rowID=1, frame="f", columnID={cc})' for cc in range(32)
            )
            ex.execute("i", body)
            ex.execute("i", body)
        finally:
            h.close()
            _cleanup_env()
    assert not any("writelane." in k for k in stats.snapshot()), stats.snapshot()
