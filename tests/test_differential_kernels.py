"""Generated differential fuzz over every kernel/strategy lane.

CPU form of the asm-vs-Go idiom (roaring/assembly_test.go): the Pallas
kernels run in interpret mode here; ``python tpu_selftest.py`` runs the
SAME generated cases against the real Mosaic lowering on a chip.
"""

import pytest

from pilosa_tpu.ops import diffcheck


@pytest.mark.parametrize("seed", [11, 12])
def test_all_lanes_vs_numpy(seed):
    failures = diffcheck.run_lanes(seed=seed, cases_per_lane=12, interpret=True)
    assert not failures, "\n".join(failures)


def test_lane_coverage_is_complete():
    """Every strategy lane reachable from ops/dispatch.py + engine.py has
    a generated-case lane in the harness (VERDICT r3 item 3): pair ops x
    {fused, tiled, resident, slice-major gather, row-major gather, gram
    identities, dispatch 3D/4D/gram}, multi-fold x layouts, TopN scorer,
    count1, Gram builder tiers."""
    lanes = diffcheck.lane_names()
    for op in ("and", "or", "xor", "andnot"):
        for fam in ("count2", "resident", "gather", "rmgather",
                    "gram_pairs", "dispatch", "dispatch4", "dispatch_gram"):
            assert f"{fam}:{op}" in lanes
    for mop in ("and", "or", "andnot"):
        for k in (2, 4):
            assert f"multi:{mop}:k{k}" in lanes
            assert f"rmmulti:{mop}:k{k}" in lanes
    assert {"count1", "topn", "gram_oneshot", "gram_scan", "gram_chunked"} <= lanes
    # 2 seeds x 12 cases = 24 generated cases per lane family >= 20.
