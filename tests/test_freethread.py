"""Free-threading readiness: true-concurrency stress gates.

The dynamic half of the generation-3 analysis pair (the static half is
the GIL-dependence analyzer in analysis/rules.py; the native half is
the TSAN leg in test_native_threaded.py).  Every test in this module
runs under ``PILOSA_TPU_LOCK_CHECK=1`` — the conftest gate enables the
lockset race detector and FAILS the test on any recorded violation —
so the assertions here are twofold: the Python-visible invariants hold
under genuine thread interleaving, AND no guarded field was ever
written with an empty candidate lockset while it happened.

Gates:

- a multi-threaded HTTP hammer against a real server — reads + writes
  + streaming ingest + ``/metrics`` scrapes concurrently, with STRICT
  ``parse_exposition`` on every scrape (a torn registry iteration
  renders garbage or raises ``RuntimeError: dict changed size``);
- concurrent ``/metrics`` render vs. live stats mutation without a
  server in the loop (the satellite-3 unit shape);
- concurrent qcache store/evict/purge churn;
- the ``lockcheck.named_global`` seam: LRU bounds, bypass rules, the
  PQL parse memo riding it, and the detector catching a writer that
  subverts the seam's lock.
"""

import json
import threading
import traceback
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import metrics
from pilosa_tpu.analysis import lockcheck
from pilosa_tpu.stats import ExpvarStatsClient


def _join_all(threads, errors):
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, "worker errors:\n" + "\n".join(errors)


def _catching(fn, errors):
    def run():
        try:
            fn()
        except Exception:
            errors.append(traceback.format_exc())

    return run


# -- the hammer: one real server, all four traffic kinds at once -----------


def test_server_hammer_reads_writes_ingest_metrics(tmp_path):
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.client import Client
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=str(tmp_path / "hammer"), host="127.0.0.1:0",
        engine="numpy", stats="expvar", qcache_enabled=True,
    )
    s = Server(cfg)
    s.open()
    errors: list = []
    try:
        c = Client(s.host)
        c.create_index("i")
        c.create_frame("i", "f")
        # Warm the parse memo through the named-global seam so the
        # /metrics scrape below has non-zero gauges to publish.
        c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=1)')

        def writer():
            wc = Client(s.host)
            for rnd in range(15):
                body = "".join(
                    f'SetBit(rowID={r}, frame="f", columnID={rnd * 64 + j})'
                    for r in range(4) for j in range(16)
                )
                wc.execute_query("i", body)

        def reader():
            rc = Client(s.host)
            for rnd in range(30):
                r = rc.execute_query(
                    "i", f'Count(Bitmap(rowID={rnd % 4}, frame="f"))'
                )
                assert "results" in r

        def ingester():
            ic = Client(s.host)
            rng = np.random.default_rng(7)
            for _ in range(4):
                rows = rng.integers(0, 8, size=2000).astype(np.uint64)
                cols = rng.integers(0, 1 << 16, size=2000).astype(np.uint64)
                out = ic.ingest_stream("i", "f", rows, cols,
                                       chunk_pairs=512)
                assert out["done"]

        def scraper():
            for _ in range(25):
                with urllib.request.urlopen(
                    f"http://{s.host}/metrics", timeout=30
                ) as r:
                    text = r.read().decode("utf-8")
                # STRICT: any torn snapshot (dict-changed-size, a half
                # rendered family, a bad label) raises here.
                fams = metrics.parse_exposition(text)
                assert "pilosa_analysis_globals_registered" in fams, (
                    "named-global gauges missing from /metrics"
                )
                with urllib.request.urlopen(
                    f"http://{s.host}/debug/vars", timeout=30
                ) as r:
                    json.loads(r.read())

        threads = [
            threading.Thread(target=_catching(fn, errors), name=name)
            for name, fn in (
                ("ft-writer", writer), ("ft-reader-1", reader),
                ("ft-reader-2", reader), ("ft-ingester", ingester),
                ("ft-scraper", scraper),
            )
        ]
        _join_all(threads, errors)
    finally:
        s.close()


# -- satellite 3: /metrics render vs. live mutation, no server -------------


def test_concurrent_metrics_render_vs_stats_mutation():
    """metrics.render iterates every registry map while mutators add
    NEW series (structural dict growth) and bump existing ones; every
    snapshot must stay a valid exposition and never raise."""
    stats = ExpvarStatsClient()
    stop = threading.Event()
    errors: list = []

    def mutator(k: int):
        i = 0
        while not stop.is_set():
            stats.count(f"ft.m{k}.c{i % 97}")
            stats.gauge(f"ft.m{k}.g{i % 89}", i)
            stats.histogram(f"ft.m{k}.h{i % 13}", float(i % 7))
            stats.with_tags(f"shard:{i % 11}").count(f"ft.m{k}.tagged")
            i += 1

    def renderer():
        try:
            for _ in range(60):
                text = metrics.render(stats)
                metrics.parse_exposition(text)  # strict, every snapshot
        finally:
            stop.set()

    threads = [
        threading.Thread(target=_catching(lambda k=k: mutator(k), errors))
        for k in range(3)
    ]
    threads.append(threading.Thread(target=_catching(renderer, errors)))
    _join_all(threads, errors)
    # One final quiescent render parses and contains the mutated series.
    fams = metrics.parse_exposition(metrics.render(stats))
    assert any(name.startswith("pilosa_ft_m0_c") for name in fams)


def test_concurrent_qcache_store_evict_purge(tmp_path):
    """qcache store/evict churn from several threads with concurrent
    purges: byte accounting and the LRU stay consistent, and every
    ``_guarded_by_`` field write holds qcache._mu."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.qcache import QueryCache

    h = Holder(str(tmp_path / "qc"))
    h.open()
    errors: list = []
    try:
        h.create_index("i").create_frame("f", FrameOptions())
        qc = QueryCache(max_bytes=1 << 12, min_cost_ms=0.0)

        def storer(k: int):
            for i in range(120):
                q = f'Count(Bitmap(rowID={k * 200 + i}, frame="f"))'
                results, pending = qc.lookup(h, "i", q, (0,))
                if pending is not None:
                    qc.commit(h, pending, [{"n": i}] * 8)
                elif results is not None:
                    assert results[0]["n"] >= 0

        def purger():
            for i in range(40):
                if i % 8 == 7:
                    qc.clear()
                else:
                    qc.purge_frame("i", "f")
                len(qc)

        threads = [
            threading.Thread(target=_catching(lambda k=k: storer(k), errors))
            for k in range(3)
        ]
        threads.append(threading.Thread(target=_catching(purger, errors)))
        _join_all(threads, errors)
        with qc._mu:
            assert qc.bytes >= 0
            assert qc.stores >= 1
            assert qc.bytes <= qc.max_bytes
    finally:
        h.close()


# -- the named-global seam -------------------------------------------------


def test_named_global_lru_bounds_and_bypass():
    ng = lockcheck.named_global("ft.test.lru", max_entries=3)
    assert lockcheck.named_global("ft.test.lru") is ng  # idempotent
    ng.clear()
    ng.put("a", 1)
    ng.put("b", 2)
    ng.put("c", 3)
    assert ng.get("a") == 1  # MRU move: order is now b, c, a
    ng.put("d", 4)  # evicts b (the LRU)
    assert len(ng) == 3
    assert "b" not in ng and "a" in ng and "d" in ng
    snap = ng.stats_snapshot()
    assert snap["hits"] >= 1 and snap["evictions"] >= 1

    big = lockcheck.named_global("ft.test.keylen", max_entries=8,
                                 max_key_len=4)
    big.clear()
    big.put("toolongkey", 1)  # over the key bound: bypassed, not stored
    assert len(big) == 0 and big.get("toolongkey") is None
    big.put("ok", 2)
    assert big.get("ok") == 2


def test_parse_memo_rides_the_seam_concurrently():
    """parse_cached through the named-global seam from several threads:
    identical sources share one Query object, the registry sees the
    memo, and the checker observes only locked mutations."""
    from pilosa_tpu.pql import parser

    assert "pql.parse_memo" in lockcheck.named_globals()
    srcs = [f'Count(Bitmap(rowID={i}, frame="f"))' for i in range(20)]
    results: dict = {}
    errors: list = []
    mu = threading.Lock()

    def worker():
        for i, src in enumerate(srcs):
            q = parser.parse_cached(src)
            with mu:
                prev = results.setdefault(i, q)
            assert prev is q or prev == q

    threads = [
        threading.Thread(target=_catching(worker, errors)) for _ in range(4)
    ]
    _join_all(threads, errors)
    # Steady state: the memoized object is returned by identity.
    q1 = parser.parse_cached(srcs[0])
    assert parser.parse_cached(srcs[0]) is q1
    # The seam publishes its gauges through any stats client.
    stats = ExpvarStatsClient()
    lockcheck.publish_global_stats(stats)
    snap = stats.snapshot()
    assert snap.get("analysis.globals.registered", 0) >= 1


def test_named_global_detects_seam_subversion():
    """A writer that mutates the backing store WITHOUT the named lock
    must produce a lockset-race violation (and a locked writer on the
    same global must not)."""
    ng = lockcheck.named_global("ft.test.subvert")
    ng.clear()
    done = threading.Barrier(2)

    def locked_writer():
        for i in range(50):
            ng.put(f"k{i}", i)
        done.wait()

    def unlocked_writer():
        done.wait()  # order the phases: shared state, disjoint locksets
        for i in range(50):
            ng._store[f"raw{i}"] = i  # bypasses the _GlobalLock
            ng._note_mutation()

    t1 = threading.Thread(target=locked_writer)
    t2 = threading.Thread(target=unlocked_writer)
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    violations = lockcheck.take_violations()  # consumed: the conftest
    # gate must not fail this test for the violation we seeded.
    assert any(
        v.kind == "lockset-race" and "NamedGlobal._store" in v.detail
        for v in violations
    ), f"seam subversion went undetected: {[v.kind for v in violations]}"


# -- striped stats: shard totals byte-identical to the serialized client ----


def test_striped_stats_totals_match_serialized():
    """A deterministic workload written from 4 threads through the
    striped client must snapshot EXACTLY the totals the same workload
    produces single-threaded: counters, histogram count/min/max/sum,
    timing count/sum.  (Reservoir percentiles are sampling-order
    dependent by design; the exact fields are the contract.)"""
    striped = ExpvarStatsClient()
    serial = ExpvarStatsClient()
    n_threads, per_thread = 4, 700  # crosses SHARD_FLUSH_CAP mid-run

    def workload(client, tid: int):
        tagged = client.with_tags(f"t:{tid % 2}")
        for i in range(per_thread):
            client.count("ft.reads", 1)
            tagged.count("ft.tagged", 2)
            client.histogram("ft.lat", float((tid * per_thread + i) % 97))
            client.timing("ft.exec", 0.001 * ((i + tid) % 11))

    errors: list = []
    threads = [
        threading.Thread(target=_catching(lambda tid=t: workload(striped, tid), errors))
        for t in range(n_threads)
    ]
    _join_all(threads, errors)
    for t in range(n_threads):
        workload(serial, t)

    got = striped.snapshot_typed()
    want = serial.snapshot_typed()
    assert got["counters"] == want["counters"]
    for name in want["histograms"]:
        for field in ("count", "min", "max", "sum"):
            assert got["histograms"][name][field] == pytest.approx(
                want["histograms"][name][field]
            ), (name, field)
    assert set(got["timings"]) == set(want["timings"])
    for name in want["timings"]:
        assert got["timings"][name]["count"] == want["timings"][name]["count"]
        assert got["timings"][name]["sum"] == pytest.approx(want["timings"][name]["sum"])
    # The flat snapshot agrees with itself after a second drain (no
    # residue left in shards, nothing merged twice).
    assert striped.snapshot()["ft.reads"] == n_threads * per_thread


def test_shard_flush_mid_snapshot_no_double_count():
    """The ISSUE-16 small fix, pinned deterministically: a shard whose
    self-flush (SHARD_FLUSH_CAP reached) races a snapshot drain must
    merge its deltas exactly once.  The schedule is forced: the main
    thread holds the client lock, the writer hits the cap and blocks in
    its flush, the snapshot drain runs first, then the flush proceeds
    over the already-zeroed shard."""
    from pilosa_tpu import stats as stats_mod

    c = ExpvarStatsClient()
    cap = stats_mod.SHARD_FLUSH_CAP
    buffered = threading.Event()   # writer parked CAP-1 samples
    flushing = threading.Event()   # writer entered its self-flush
    release = threading.Event()    # main finished the mid-snapshot drain

    orig_flush = c._flush_shard

    def traced_flush(sh):
        flushing.set()
        orig_flush(sh)  # blocks on the client lock the main thread holds

    c._flush_shard = traced_flush
    errors: list = []

    def writer():
        for i in range(cap - 1):
            c.timing("ft.race", float(i))
        buffered.set()
        release.wait(timeout=60)
        c.timing("ft.race", float(cap - 1))  # reaches the cap -> flush

    t = threading.Thread(target=_catching(writer, errors))
    t.start()
    assert buffered.wait(timeout=60)
    with c._lock:
        release.set()
        assert flushing.wait(timeout=60), "writer never reached its flush"
        # Mid-snapshot drain wins the race: every pending sample (the
        # full CAP) merges here, under this single lock hold.
        c._drain_all_locked()
        mid_count = int(c._timing_meta["ft.race"][0])
    t.join(timeout=60)
    assert not errors, errors
    assert mid_count == cap
    snap = c.snapshot_typed()
    assert snap["timings"]["ft.race"]["count"] == cap  # NOT 2x
    assert snap["timings"]["ft.race"]["sum"] == pytest.approx(
        sum(range(cap))
    )


# -- multicore smoke: a 2-thread server pool serving concurrent reads ------


def test_multicore_two_thread_pool_smoke(tmp_path):
    """ISSUE-16 multicore smoke: a server with a 2-thread worker pool
    serves concurrent readers correctly (striped stats + per-thread
    armed tables underneath), publishes the pool gauges, and sheds
    nothing at this load."""
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.client import Client
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=str(tmp_path / "mc"), host="127.0.0.1:0",
        engine="numpy", stats="expvar", qcache_enabled=False,
        server_max_threads=2,
    )
    s = Server(cfg)
    s.open()
    errors: list = []
    try:
        c = Client(s.host)
        c.create_index("i")
        c.create_frame("i", "f")
        body = "".join(
            f'SetBit(rowID={r}, frame="f", columnID={r * 7 + j})'
            for r in range(4) for j in range(30)
        )
        c.execute_query("i", body)
        q = " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a in range(4) for b in range(4)
        )
        want = c.execute_query("i", q)["results"]

        def reader():
            rc = Client(s.host)
            for _ in range(25):
                assert rc.execute_query("i", q)["results"] == want

        _join_all([
            threading.Thread(target=_catching(reader, errors), name=f"mc-{i}")
            for i in range(2)
        ], errors)

        with urllib.request.urlopen(f"http://{s.host}/debug/vars", timeout=30) as r:
            snap = json.loads(r.read())
        assert snap.get("server.pool.workers") == 2.0
        assert snap.get("server.pool.shed", 0) == 0
        assert snap.get("stats.shards", 0) >= 1  # striped client live
    finally:
        s.close()


def test_reuseport_two_servers_share_port(tmp_path):
    """[server] workers mode's kernel seam: two in-process servers bind
    the SAME port via SO_REUSEPORT (server_workers > 1 turns it on) and
    both front doors answer — the per-process shape the CLI's worker
    fallback runs N of on GIL builds."""
    import socket

    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("no SO_REUSEPORT on this platform")
    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    cfg1 = Config(data_dir=str(tmp_path / "a"), host="127.0.0.1:0",
                  engine="numpy", stats="expvar", server_workers=2)
    s1 = Server(cfg1)
    s1.open()
    s2 = None
    try:
        # Second server on the RESOLVED port: only SO_REUSEPORT lets
        # this bind succeed.
        cfg2 = Config(data_dir=str(tmp_path / "b"), host=s1.host,
                      engine="numpy", stats="expvar", server_workers=2)
        s2 = Server(cfg2)
        s2.open()
        assert s2.host == s1.host
        # The kernel spreads connections between the two sockets; every
        # request must be answered whichever server accepts it.
        for _ in range(10):
            with urllib.request.urlopen(
                f"http://{s1.host}/debug/vars", timeout=30
            ) as r:
                json.loads(r.read())
    finally:
        if s2 is not None:
            s2.close()
        s1.close()
