"""Hypothesis stateful model fuzz for the roaring engine.

The reference's strongest roaring coverage is testing/quick round-trips
(roaring/roaring_test.go:182-249); this is that idiom upgraded to a
STATEFUL model: random interleavings of add/remove/add_many/serialize/
reload/zero-copy-attach/COW-mutate against a python-set oracle, with
the structural invariants (Bitmap.check) asserted after every
serialization boundary.  Shrinking gives minimal failing op sequences.
"""

import io

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # optional test dep; suite stays runnable
from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import RuleBasedStateMachine, invariant, rule

from pilosa_tpu import roaring

# Positions concentrated into few containers (values near container
# boundaries and the array<->bitmap conversion threshold get dense
# coverage) plus a long tail across container keys.
_POS = st.one_of(
    st.integers(0, 1 << 17),
    st.integers((1 << 16) - 64, (1 << 16) + 64),
    st.integers(0, (1 << 22) - 1),
)


class RoaringMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        self.bm = roaring.Bitmap()
        self.model: set[int] = set()

    @rule(v=_POS)
    def add(self, v):
        assert self.bm.add(v) == (v not in self.model)
        self.model.add(v)

    @rule(v=_POS)
    def remove(self, v):
        assert self.bm.remove(v) == (v in self.model)
        self.model.discard(v)

    @rule(vs=st.lists(_POS, min_size=1, max_size=300))
    def add_many(self, vs):
        arr = np.asarray(sorted(set(vs)), dtype=np.uint64)
        added = self.bm.add_many_unlogged(arr)
        assert set(added.tolist()) == (set(vs) - self.model)
        self.model |= set(vs)

    @rule(v=_POS)
    def contains(self, v):
        assert self.bm.contains(v) == (v in self.model)

    @rule()
    def serialize_reload(self):
        buf = io.BytesIO()
        self.bm.write_to(buf)
        self.bm = roaring.Bitmap.from_bytes(buf.getvalue())
        self.bm.check()
        assert self.bm.count() == len(self.model)

    @rule()
    def zero_copy_attach_then_mutate(self):
        """Reload zero-copy (read-only views) then mutate: COW promotion
        must never corrupt neighbouring containers."""
        buf = io.BytesIO()
        self.bm.write_to(buf)
        self.bm = roaring.Bitmap.from_bytes(buf.getvalue(), zero_copy=True)
        self.bm.check()
        probe = 12345
        had = probe in self.model
        assert self.bm.add(probe) == (not had)
        self.model.add(probe)

    @rule(lo=_POS, hi=_POS)
    def count_range(self, lo, hi):
        lo, hi = min(lo, hi), max(lo, hi)
        want = sum(1 for v in self.model if lo <= v < hi)
        assert self.bm.count_range(lo, hi) == want

    @invariant()
    def count_matches(self):
        assert self.bm.count() == len(self.model)


TestRoaringModel = RoaringMachine.TestCase
TestRoaringModel.settings = settings(
    max_examples=40, stateful_step_count=60, deadline=None
)
