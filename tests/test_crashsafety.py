"""Crash-safety and file-locking tests for fragment storage.

Reference analogs: the exclusive flock on fragment open
(fragment.go:179-234), temp-write+rename snapshotting
(fragment.go:1017-1057), and WAL replay on open (roaring.go:590-611).
The torn-tail recovery goes BEYOND the reference (which errors on a torn
record and leaves trimming to hand repair — roaring.go:599-601 FIXME):
a crash mid-append must not brick the fragment.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pilosa_tpu import roaring
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.pilosa import ErrFragmentLocked


def _new_fragment(path: str, **kw) -> Fragment:
    f = Fragment(path, "i", "f", "standard", 0, **kw)
    f.open()
    return f


# -- flock ---------------------------------------------------------------


def test_flock_excludes_second_opener(tmp_path):
    path = str(tmp_path / "frag")
    f1 = _new_fragment(path)
    f1.set_bit(1, 2)
    f2 = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(ErrFragmentLocked):
        f2.open()
    f1.close()
    # Lock released on close: a new opener succeeds and sees the data.
    f2.open()
    assert f2.contains(1, 2)
    f2.close()


def test_flock_failed_open_leaves_no_lock(tmp_path):
    # An open that fails AFTER acquiring the lock must release it.
    path = str(tmp_path / "frag")
    with open(path, "wb") as fh:
        fh.write(b"garbage, not a roaring file")
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(ValueError):
        f.open()
    os.unlink(path)
    f2 = _new_fragment(path)  # no ErrFragmentLocked
    f2.close()


# -- torn WAL tail -------------------------------------------------------


def _reopen(path: str) -> Fragment:
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    return f


def test_torn_wal_partial_record_recovers(tmp_path):
    path = str(tmp_path / "frag")
    f = _new_fragment(path)
    for c in range(10):
        f.set_bit(3, c)  # 10 WAL op records after the initial snapshot
    f.close()
    os.unlink(path + ".cache")  # recovery must not depend on sidecars
    size = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(b"\x00\x01\x02\x03\x04\x05\x06")  # 7 bytes: torn record
    f = _reopen(path)
    assert f.row_count(3) == 10  # every acked op survived
    assert os.path.getsize(path) == size  # torn tail truncated away
    # The recovered fragment accepts and persists new writes.
    assert f.set_bit(3, 10)
    f.close()
    f = _reopen(path)
    assert f.row_count(3) == 11
    f.close()


def test_torn_wal_corrupt_checksum_recovers_prefix(tmp_path):
    path = str(tmp_path / "frag")
    f = _new_fragment(path)
    for c in range(6):
        f.set_bit(1, c)
    f.close()
    os.unlink(path + ".cache")
    # Flip a byte inside the LAST 13-byte op record's value field.
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 13 + 4)
        b = fh.read(1)
        fh.seek(size - 13 + 4)
        fh.write(bytes([b[0] ^ 0xFF]))
    f = _reopen(path)
    assert f.row_count(1) == 5  # 5 valid ops; the corrupt last one dropped
    assert os.path.getsize(path) == size - 13
    f.close()


def test_mid_log_corruption_with_valid_records_after_raises(tmp_path):
    # A byte flip in the MIDDLE of the op log (valid records follow it) is
    # storage corruption, not a crash tear — truncating there would
    # silently destroy acked ops, so the open must fail loudly instead.
    path = str(tmp_path / "frag")
    f = _new_fragment(path)
    for c in range(6):
        f.set_bit(1, c)
    f.close()
    os.unlink(path + ".cache")
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 3 * 13 + 4)  # third-from-last record's value field
        b = fh.read(1)
        fh.seek(size - 3 * 13 + 4)
        fh.write(bytes([b[0] ^ 0xFF]))
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(ValueError, match="refusing to truncate"):
        f.open()


def test_snapshot_body_corruption_still_raises(tmp_path):
    # Recovery is for torn APPENDS only: damage inside the snapshot body is
    # real corruption and must fail the open loudly (strict body parse).
    path = str(tmp_path / "frag")
    f = _new_fragment(path)
    f.import_bits(np.arange(5000, dtype=np.uint64) % 7, np.arange(5000, dtype=np.uint64))
    f.close()
    with open(path, "r+b") as fh:
        fh.seek(0)
        fh.write(b"\xde\xad\xbe\xef")  # clobber the cookie
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(ValueError):
        f.open()


def test_from_bytes_recover_roundtrip():
    bm = roaring.Bitmap()
    for v in (1, 5, 100000, 1 << 33):
        bm.add(v)
    body = bm.to_bytes()
    import io

    buf = io.BytesIO()
    bm2 = roaring.Bitmap.from_bytes(body)
    bm2.op_writer = buf
    bm2.add(7)
    bm2.remove(5)
    data = body + buf.getvalue() + b"\xff\xff"  # two valid ops + torn tail
    got, valid_len = roaring.Bitmap.from_bytes_recover(data)
    assert valid_len == len(body) + 26
    assert sorted(got.to_array().tolist()) == [1, 7, 100000, 1 << 33]


# -- orphaned snapshot temp files ----------------------------------------


def test_stale_snapshotting_temp_swept_on_open(tmp_path):
    path = str(tmp_path / "frag")
    f = _new_fragment(path)
    f.set_bit(2, 9)
    f.close()
    # Simulate a crash between temp write and rename: an orphaned temp
    # holding a half-written snapshot next to the intact previous file.
    orphan = path + ".abc123.snapshotting"
    with open(orphan, "wb") as fh:
        fh.write(b"half-written snapsho")
    # A NEIGHBOR fragment's orphan must not be swept by this fragment.
    neighbor = str(tmp_path / "frag2") + ".zzz.snapshotting"
    with open(neighbor, "wb") as fh:
        fh.write(b"x")
    f = _reopen(path)
    assert f.contains(2, 9)  # previous good state intact
    assert not os.path.exists(orphan)
    assert os.path.exists(neighbor)
    f.close()


# -- crash injection (SIGKILL a live writer process) ---------------------

_WRITER = r"""
import sys
from pilosa_tpu.core.fragment import Fragment

path = sys.argv[1]
f = Fragment(path, "i", "f", "standard", 0, max_opn=50)
f.open()
print("ready", flush=True)
i = 0
while True:  # snapshot every 50 ops; killed mid-stream by the parent
    f.set_bit(i % 17, i)
    i += 1
"""


@pytest.mark.parametrize("kill_after", [0.15, 0.4])
def test_sigkill_mid_write_stream_recovers(tmp_path, kill_after):
    path = str(tmp_path / "frag")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _WRITER, path],
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    try:
        assert proc.stdout.readline().strip() == b"ready"
        time.sleep(kill_after)  # let it race through WAL appends + snapshots
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    # The dead process's flock is gone; open recovers whatever prefix of
    # the op stream reached the kernel and passes the storage invariants.
    f = _reopen(path)
    f.storage.check()
    total = f.count()
    assert total > 0
    # The recovered fragment keeps working.
    assert f.set_bit(999, 5)
    assert f.count() == total + 1
    f.close()


def test_long_wal_torn_tail_recovers(tmp_path):
    """Round-4 scaled snapshot triggers mean WALs can carry tens of
    thousands of ops before a snapshot folds them; a crash with a torn
    final record must still recover the full acked prefix at that
    length (replay is native-decoded, ~100k ops/s)."""
    from pilosa_tpu.core.fragment import Fragment

    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0)  # default max_opn -> scaled
    f.open()
    n = 12000
    rng = np.random.default_rng(4)
    rows = rng.integers(0, 500, size=n).tolist()
    cols = rng.integers(0, 1 << 20, size=n).tolist()
    for r, c in zip(rows, cols):
        f.set_bit(r, c)
    want = f.count()
    assert f.storage.op_n > 2000, "scaled trigger should have deferred snapshots"
    # Simulate a crash: drop the handles without close() (no final
    # bookkeeping), then tear the last WAL record.
    f._wal.close(); f._wal = None; f.storage.op_writer = None
    f._release_flock(); f._open = False
    with open(p, "r+b") as fh:
        fh.seek(0, 2)
        fh.truncate(fh.tell() - 3)  # torn mid-record
    g = Fragment(p, "i", "f", "standard", 0)
    g.open()
    g.storage.check()
    # the torn op was the only possibly-lost one
    assert g.count() in (want, want - 1)
    assert g.set_bit(999, 7)
    g.close()
