"""Sanitizer leg of the native-boundary conformance gate.

Builds the ASAN+UBSAN flavor of the native library (``make -C native
asan`` → ``libpilosa_native-asan.so``) and re-runs the differential
suites — writelane, the native bridge (serve-pairs matcher included),
streaming ingest, roaring, and the executor serve-lane tests — in a
SUBPROCESS against it: ``PILOSA_TPU_NATIVE_LIB`` points the ctypes
bridge at the sanitized build, and ``LD_PRELOAD`` puts the ASAN runtime
first (plus ``libstdc++`` so the ``__cxa_throw`` interceptor can
resolve before jaxlib's pybind modules load — gcc's libasan aborts
otherwise).  A heap overflow, use-after-free, or UB in any
pointer-arithmetic container path then fails this test with the
sanitizer report instead of corrupting memory silently.

Mirrors the conftest native-build contract: without a toolchain (or an
ASAN runtime) the leg SKIPS with the reason logged, it never fails for
environmental reasons.  ``PILOSA_TPU_NO_SAN_LEG=1`` opts out explicitly
(e.g. inside the sanitized subprocess itself, or on memory-tight rigs).
"""

import os
import shutil
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_REPO, "native")
_ASAN_SO = os.path.join(_NATIVE, "libpilosa_native-asan.so")

# The differential selection re-run under the sanitizer.  Kept to the
# suites that drive the native kernels hard but run in seconds: the
# whole leg must fit tier-1's budget even at ASAN's ~2-4x slowdown.
_SUITES = [
    "tests/test_writelane.py",
    "tests/test_native.py",
    "tests/test_roaring.py",
    "tests/test_ingest.py",
    "tests/test_executor.py", "-k", "serve or flat",
]


def _skip(reason: str) -> None:
    sys.stderr.write(f"\n[test_native_sanitized] skipping: {reason}\n")
    pytest.skip(reason)


def _resolve_runtime(lib: str) -> str:
    """Real path of a gcc runtime library (``libasan.so`` prints as a
    linker-script/symlink path; LD_PRELOAD needs the actual DSO)."""
    out = subprocess.run(
        ["g++", f"-print-file-name={lib}"], capture_output=True, text=True,
        timeout=30,
    )
    path = out.stdout.strip()
    if not path or path == lib or not os.path.exists(path):
        return ""
    return os.path.realpath(path)


def test_differential_suites_pass_against_sanitized_so():
    if os.environ.get("PILOSA_TPU_NO_SAN_LEG"):
        _skip("PILOSA_TPU_NO_SAN_LEG set")
    if os.environ.get("PILOSA_TPU_NO_NATIVE"):
        _skip("PILOSA_TPU_NO_NATIVE set; nothing native to sanitize")
    missing = [t for t in ("make", "g++", "nm") if shutil.which(t) is None]
    if missing:
        _skip(f"toolchain missing: {', '.join(missing)}")

    # Build (or refresh) the sanitized flavor.
    build = subprocess.run(
        ["make", "-C", _NATIVE, "asan"],
        capture_output=True, text=True, timeout=240,
    )
    if build.returncode != 0 or not os.path.exists(_ASAN_SO):
        _skip(
            "make asan failed (no ASAN-capable toolchain?): "
            + (build.stderr or build.stdout)[-400:]
        )

    asan_rt = _resolve_runtime("libasan.so")
    stdcxx_rt = _resolve_runtime("libstdc++.so.6")
    if not asan_rt or not stdcxx_rt:
        _skip("libasan/libstdc++ runtime not resolvable for LD_PRELOAD")

    env = dict(os.environ)
    env.update(
        {
            "PILOSA_TPU_NATIVE_LIB": _ASAN_SO,
            "PILOSA_TPU_NO_SAN_LEG": "1",  # no recursion if selection grows
            # libstdc++ first-loaded so ASAN's __cxa_throw interceptor
            # resolves at init (jaxlib pybind throws during import).
            "LD_PRELOAD": f"{asan_rt} {stdcxx_rt}",
            # Python "leaks" by design; leak checking would drown real
            # reports.  halt_on_error stays default-on for ASAN errors.
            "ASAN_OPTIONS": "detect_leaks=0",
            "UBSAN_OPTIONS": "print_stacktrace=1",
            "JAX_PLATFORMS": "cpu",
        }
    )

    # Preamble: prove the subprocess really serves from the sanitized
    # .so — a silent fallback to the Python lanes (bad env path, load
    # failure) would pass every suite while sanitizing nothing.
    probe = subprocess.run(
        [
            sys.executable, "-c",
            "from pilosa_tpu import native; p = native.loaded_path(); "
            f"assert p == {_ASAN_SO!r}, f'loaded {{p}}'; print('sanitized-lib-ok')",
        ],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO,
    )
    assert probe.returncode == 0 and "sanitized-lib-ok" in probe.stdout, (
        "sanitized .so did not load in the subprocess:\n"
        + probe.stdout[-800:] + probe.stderr[-1600:]
    )

    res = subprocess.run(
        [
            sys.executable, "-m", "pytest", *_SUITES,
            "-q", "-m", "not slow",
            "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
        ],
        capture_output=True, text=True, timeout=480, env=env, cwd=_REPO,
    )
    if res.returncode != 0:
        tail = (res.stdout or "")[-4000:] + "\n" + (res.stderr or "")[-4000:]
        pytest.fail(
            "differential suites FAILED against the ASAN+UBSAN build "
            f"(exit {res.returncode}) — sanitizer report / failures:\n{tail}",
            pytrace=False,
        )
