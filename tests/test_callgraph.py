"""Pinning tests for analysis/callgraph.py's documented over-approximation.

The name-based call graph is the soundness foundation of the
lockstep-determinism and guarded-fields rules; its behavior on the
awkward shapes — decorated functions, aliased imports, method calls
through ``self.``-attributes, stoplisted bare names, same-file-first
resolution — was documented but never pinned.  These tests freeze the
contract so a refactor that silently changes reachability (and with it
which findings fire) breaks HERE, with a readable diff, instead of as a
mystery lint regression.
"""

import textwrap

from pilosa_tpu.analysis import engine
from pilosa_tpu.analysis.callgraph import STOPLIST, CallGraph


def _graph(tmp_path, files: dict) -> CallGraph:
    root = tmp_path / "pkg"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return CallGraph(engine.load_tree(str(root)))


def _reachable_scopes(graph, seed_rel, seed_scope):
    keys = graph.reachable_from([(seed_rel, seed_scope)])
    return {scope for _rel, scope in keys}


def test_decorated_functions_are_nodes_and_reachable(tmp_path):
    """Decorators neither hide the decorated def nor break edges INTO
    it: the node is keyed by the def name, and a call to that bare name
    reaches it.  The decorator expression itself contributes a call
    edge from the enclosing scope only when it is written as a call."""
    g = _graph(tmp_path, {"mod.py": """
    import functools

    def wraps_nothing(fn):
        return fn

    @wraps_nothing
    def helper():
        return 1

    @functools.lru_cache(maxsize=8)
    def cached_helper():
        return 2

    def entry():
        helper()
        cached_helper()
    """})
    scopes = _reachable_scopes(g, "mod.py", "entry")
    assert "helper" in scopes
    assert "cached_helper" in scopes


def test_aliased_imports_resolve_by_bare_attribute_name(tmp_path):
    """``import x as y; y.foo(...)`` produces a bare-name edge on
    ``foo`` — module aliasing is invisible to the name-based graph, so
    the call reaches EVERY in-package def named ``foo`` (same-file
    first when one exists).  This is the documented over-approximation:
    more edges, never fewer findings."""
    g = _graph(tmp_path, {
        "a.py": """
        from pkg import other as o

        def entry():
            o.foo()
        """,
        "other.py": """
        def foo():
            return 1
        """,
        "third.py": """
        def foo():
            return 2
        """,
    })
    scopes = _reachable_scopes(g, "a.py", "entry")
    # no same-file foo exists, so BOTH candidates are reachable
    keys = g.reachable_from([("a.py", "entry")])
    foo_files = {rel for rel, scope in keys if scope == "foo"}
    assert foo_files == {"other.py", "third.py"}
    assert "foo" in scopes


def test_self_attribute_method_calls_resolve_same_file_first(tmp_path):
    """``self.helper()`` is an Attribute call: the bare name ``helper``
    resolves to the SAME-FILE definition when one exists, shadowing the
    package-wide candidates — a same-file def almost always IS the
    callee."""
    g = _graph(tmp_path, {
        "svc.py": """
        class Service:
            def entry(self):
                self.helper()

            def helper(self):
                return far_away()
        """,
        "lib.py": """
        def helper():
            return 1

        def far_away():
            return 2
        """,
    })
    keys = g.reachable_from([("svc.py", "Service.entry")])
    assert ("svc.py", "Service.helper") in keys
    # same-file resolution shadowed the other-file namesake entirely
    assert ("lib.py", "helper") not in keys
    # ...but the method's own calls keep resolving package-wide
    assert ("lib.py", "far_away") in keys


def test_stoplisted_bare_names_produce_no_edges(tmp_path):
    """``thread.start()`` must not drag every ``def start`` into the
    reachable set — the stoplist eats the edge (the documented
    fewer-findings hole)."""
    assert "start" in STOPLIST and "get" in STOPLIST
    g = _graph(tmp_path, {"mod.py": """
    class Server:
        def start(self):
            return secret_sauce()

    def secret_sauce():
        return 1

    def entry(thread):
        thread.start()
    """})
    keys = g.reachable_from([("mod.py", "entry")])
    assert ("mod.py", "Server.start") not in keys
    assert ("mod.py", "secret_sauce") not in keys


def test_nested_defs_are_independent_nodes(tmp_path):
    """A nested def is its own node (scanned separately by the rules);
    calling its bare name from elsewhere reaches it."""
    g = _graph(tmp_path, {"mod.py": """
    def outer():
        def inner():
            return leaf()
        return inner

    def leaf():
        return 1

    def entry():
        outer()
    """})
    keys = g.reachable_from([("mod.py", "entry")])
    assert ("mod.py", "outer") in keys
    # outer() CALLS nothing by inner's bare name (it only defines it):
    # no call edge, so inner and leaf stay unreachable from entry.
    assert ("mod.py", "outer.inner") in g.funcs
    assert ("mod.py", "outer.inner") not in keys
    assert ("mod.py", "leaf") not in keys


def test_lambda_bodies_belong_to_enclosing_function(tmp_path):
    """Calls inside a lambda attribute to the enclosing def (lambdas
    are not nodes), so reachability flows through them."""
    g = _graph(tmp_path, {"mod.py": """
    def entry():
        f = lambda: leaf()
        return f()

    def leaf():
        return 1
    """})
    keys = g.reachable_from([("mod.py", "entry")])
    assert ("mod.py", "leaf") in keys
