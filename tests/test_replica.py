"""Replicated serving groups: the read router over N full serving units.

Each group here is a complete in-process Server (numpy engine) with its
own holder — the fast-rig analog of a lockstep job per group (the
multi-process case lives in tests/test_multihost.py).  The invariants
pinned:

- WRITES ship total-ordered to ALL groups (one sequencer, WAL-backed
  since PR 7), so every group's fragment generation vectors advance
  identically — a read routed to EITHER group immediately after a
  write's ack sees it.
- READS fan across healthy groups (least-inflight, round-robin ties)
  and fail over ONCE to a sibling on connect/5xx failure.
- A dead group degrades WRITES to 503 while fewer than a MAJORITY of
  groups remain (with 2 groups, majority = 2, so one death refuses
  writes — the degraded-quorum cases with 3 groups live in
  tests/test_replica_recovery.py) while reads keep serving from the
  survivors; the health probe restores a recovered group.
- Router observability: routed/failover/write_fanout counters,
  per-group health+inflight gauges at /debug/vars, trace roots tagged
  with the serving group.
"""

import json
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.config import Config
from pilosa_tpu.replica import (
    GROUP_HEADER,
    ReplicaRouter,
    format_group,
    parse_group,
)
from pilosa_tpu.stats import ExpvarStatsClient
from pilosa_tpu.trace import Tracer


class _Rig:
    """Two in-process group servers + a router in front."""

    def __init__(self, tmp, n_groups=2, failover=True, tracer=None,
                 probe_interval_s=0.1, **router_kw):
        from pilosa_tpu.server.server import Server

        self.servers = []
        for i in range(n_groups):
            cfg = Config(
                data_dir=f"{tmp}/g{i}", host="127.0.0.1:0", engine="numpy",
                stats="expvar", qcache_enabled=False, replica_group=f"g{i}",
            )
            srv = Server(cfg)
            srv.open()
            self.servers.append(srv)
        self.stats = ExpvarStatsClient()
        self.router = ReplicaRouter(
            [f"g{i}={srv.host}" for i, srv in enumerate(self.servers)],
            failover=failover, probe_interval_s=probe_interval_s,
            stats=self.stats, tracer=tracer, **router_kw,
        ).serve()
        self.base = f"http://127.0.0.1:{self.router.port}"

    def req(self, method, path, body=None, headers=None, timeout=30):
        rq = urllib.request.Request(self.base + path, data=body, method=method)
        for k, v in (headers or {}).items():
            rq.add_header(k, v)
        try:
            with urllib.request.urlopen(rq, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def query(self, q, headers=None):
        return self.req("POST", "/index/i/query", q.encode(), headers)

    def direct_count(self, i, q='Count(Bitmap(rowID=1, frame="f"))'):
        rq = urllib.request.Request(
            f"http://{self.servers[i].host}/index/i/query",
            data=q.encode(), method="POST",
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            return json.loads(resp.read())["results"][0]

    def seed(self):
        assert self.req("POST", "/index/i", b"{}")[0] == 200
        assert self.req("POST", "/index/i/frame/f", b"{}")[0] == 200

    def close(self):
        self.router.close()
        for s in self.servers:
            s.close()


@pytest.fixture
def rig():
    with tempfile.TemporaryDirectory() as tmp:
        r = _Rig(tmp)
        try:
            yield r
        finally:
            r.close()


def test_write_fanout_and_read_balance(rig):
    """Writes (and schema mutations) apply on EVERY group; sequential
    reads spread across groups via the least-inflight/fewest-routed
    pick; counters account for both."""
    rig.seed()
    for c in range(5):
        st, body, hdrs = rig.query(f'SetBit(rowID=1, frame="f", columnID={c})')
        assert st == 200 and json.loads(body)["results"] == [True]
        assert hdrs.get(GROUP_HEADER) == "all"  # write = whole group set
    # Both groups hold the identical result of the identical write order.
    assert rig.direct_count(0) == rig.direct_count(1) == 5
    served = set()
    for _ in range(4):
        st, body, hdrs = rig.query('Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200 and json.loads(body)["results"] == [5]
        served.add(hdrs.get(GROUP_HEADER))
    assert served == {"g0", "g1"}  # idle router round-robins the ties
    snap = rig.stats.snapshot()
    assert snap["replica.routed.g0"] >= 1 and snap["replica.routed.g1"] >= 1
    # Every data write + schema mutation fanned through the sequencer.
    assert snap["replica.write_fanout"] == 7
    assert snap["replica.inflight.g0"] == 0 and snap["replica.inflight.g1"] == 0
    assert snap["replica.healthy.g0"] == 1 and snap["replica.healthy.g1"] == 1
    # Schema mutations really reached both groups.
    for i in range(2):
        rq = urllib.request.Request(f"http://{rig.servers[i].host}/schema")
        schema = json.loads(urllib.request.urlopen(rq, timeout=10).read())
        assert [x["name"] for x in schema["indexes"]] == ["i"]


def test_cross_group_read_your_writes(rig):
    """A write acked by the router is visible on the IMMEDIATE next
    read no matter which group serves it — the total-order fan-out
    advanced both groups' generation vectors before the ack."""
    rig.seed()
    for step in range(1, 6):
        assert rig.query(f'SetBit(rowID=1, frame="f", columnID={100 + step})')[0] == 200
        # Two back-to-back reads hit BOTH groups (round-robin ties).
        groups_seen = set()
        for _ in range(2):
            st, body, hdrs = rig.query('Count(Bitmap(rowID=1, frame="f"))')
            assert st == 200
            assert json.loads(body)["results"] == [step], hdrs.get(GROUP_HEADER)
            groups_seen.add(hdrs.get(GROUP_HEADER))
        assert groups_seen == {"g0", "g1"}
    assert rig.direct_count(0) == rig.direct_count(1) == 5


def test_failover_keeps_reads_serving_and_refuses_writes(rig):
    """Kill one group: reads keep serving from the survivor (one-shot
    failover on the first failed pick), writes answer 503 + Retry-After
    until the group set is quorate again."""
    rig.seed()
    assert rig.query('SetBit(rowID=1, frame="f", columnID=3)')[0] == 200
    rig.servers[1].close()  # the whole group goes away
    for _ in range(6):
        st, body, hdrs = rig.query('Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200 and json.loads(body)["results"] == [1]
        assert hdrs.get(GROUP_HEADER) == "g0"
    snap = rig.stats.snapshot()
    assert snap.get("replica.failover", 0) >= 1
    assert snap["replica.healthy.g1"] == 0
    # Writes refuse without touching ANY group while non-quorate.
    before = rig.direct_count(0)
    st, body, hdrs = rig.query('SetBit(rowID=1, frame="f", columnID=9)')
    assert st == 503 and "quorate" in json.loads(body)["error"]
    assert "Retry-After" in hdrs
    assert rig.direct_count(0) == before
    # The group table tells the same story over HTTP.
    status = json.loads(rig.req("GET", "/replica/status")[1])
    assert status["quorate"] is False
    assert {g["name"]: g["healthy"] for g in status["groups"]} == {
        "g0": True, "g1": False,
    }


def test_health_probe_restores_a_live_group(rig):
    """A group marked unhealthy (e.g. by one failed read) but actually
    serving is restored by the background /replica/health probe — and
    writes work again once the set is quorate."""
    rig.seed()
    g1 = rig.router.groups[1]
    rig.router._mark_unhealthy(g1, "injected")
    deadline = time.monotonic() + 5
    while not g1.healthy and time.monotonic() < deadline:
        time.sleep(0.05)
    assert g1.healthy, "probe never restored a live group"
    snap = rig.stats.snapshot()
    assert snap.get("replica.recovered", 0) >= 1
    assert rig.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
    assert rig.direct_count(0) == rig.direct_count(1) == 1


def test_partial_write_failure_answers_502_and_degrades(rig, monkeypatch):
    """A write that fails MID-fan-out (first group applied, second
    unreachable) answers 502 (may be partially applied), marks the
    failed group unhealthy, and subsequent writes 503 until recovery."""
    rig.seed()
    real = rig.router._forward
    g1 = rig.router.groups[1]

    def flaky(g, method, path_qs, body, headers, **kw):
        if g is g1 and b"SetBit" in body:
            raise OSError("injected mid-fanout failure")
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig.router, "_forward", flaky)
    st, body, _ = rig.query('SetBit(rowID=1, frame="f", columnID=2)')
    assert st == 502 and "partially applied" in json.loads(body)["error"]
    assert rig.direct_count(0) == 1  # the first group DID commit
    # Non-quorate now: the next write refuses outright (no group touched).
    st, body, _ = rig.query('SetBit(rowID=1, frame="f", columnID=3)')
    assert st == 503
    snap = rig.stats.snapshot()
    assert snap.get("replica.write_error", 0) == 1
    assert snap.get("replica.write_refused", 0) == 1
    # The probe restores g1 (it is actually alive), and the idempotent
    # retry re-aligns the groups.
    monkeypatch.setattr(rig.router, "_forward", real)
    deadline = time.monotonic() + 5
    while not g1.healthy and time.monotonic() < deadline:
        time.sleep(0.05)
    assert g1.healthy
    assert rig.query('SetBit(rowID=1, frame="f", columnID=2)')[0] == 200
    assert rig.direct_count(0) == rig.direct_count(1) == 1


def test_write_shed_never_acked_as_success(rig, monkeypatch):
    """A 429 shed is LOAD-dependent, not deterministic: shed at the
    FIRST group passes the backpressure through (nothing applied, no
    demotion); shed AFTER a sibling committed is a partial write (502 +
    demotion) — the client never gets a success ack while a group
    silently missed the write."""
    rig.seed()
    real = rig.router._forward
    g0, g1 = rig.router.groups
    shed = (
        429, "application/json",
        json.dumps({"error": "shed"}).encode(), {"Retry-After": "0.250"},
    )

    def shed_first(g, method, path_qs, body, headers, **kw):
        if g is g0 and b"SetBit" in body:
            return shed
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig.router, "_forward", shed_first)
    st, body, hdrs = rig.query('SetBit(rowID=1, frame="f", columnID=2)')
    assert st == 429 and hdrs.get("Retry-After") == "0.250"
    # Nothing applied anywhere, and a loaded group is NOT demoted.
    assert rig.direct_count(0) == 0 and rig.direct_count(1) == 0
    assert g0.healthy and g1.healthy
    assert rig.stats.snapshot().get("replica.write_shed", 0) == 1

    # Shed at the SECOND group after the first committed: partial write.
    def shed_second(g, method, path_qs, body, headers, **kw):
        if g is g1 and b"SetBit" in body:
            return shed
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig.router, "_forward", shed_second)
    st, body, _ = rig.query('SetBit(rowID=1, frame="f", columnID=2)')
    assert st == 502 and "partially applied" in json.loads(body)["error"]
    assert rig.direct_count(0) == 1 and rig.direct_count(1) == 0
    assert not g1.healthy  # demoted: further writes refuse until recovery
    assert rig.query('SetBit(rowID=1, frame="f", columnID=3)')[0] == 503
    # The probe restores g1 (it is alive) and the idempotent retry
    # re-aligns the groups.
    monkeypatch.setattr(rig.router, "_forward", real)
    deadline = time.monotonic() + 5
    while not g1.healthy and time.monotonic() < deadline:
        time.sleep(0.05)
    assert g1.healthy
    assert rig.query('SetBit(rowID=1, frame="f", columnID=2)')[0] == 200
    assert rig.direct_count(0) == rig.direct_count(1) == 1


def test_read_504_is_request_scoped_not_group_health(rig, monkeypatch):
    """A 504 spent the REQUEST's own deadline budget, not the group's
    health: it returns to the client without demoting the group, so a
    burst of tight-deadline reads can never mark every group unhealthy
    and refuse writes cluster-wide via the quorum rule."""
    rig.seed()
    real = rig.router._forward

    def deadline_504(g, method, path_qs, body, headers, **kw):
        if b"Count" in body:
            return (
                504, "application/json",
                json.dumps({"error": "deadline exceeded"}).encode(), {},
            )
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig.router, "_forward", deadline_504)
    for _ in range(6):  # enough to have drawn BOTH groups
        assert rig.query('Count(Bitmap(rowID=1, frame="f"))')[0] == 504
    assert all(g.healthy for g in rig.router.groups)
    assert rig.router.quorate()
    assert rig.stats.snapshot().get("replica.failover", 0) == 0
    # Writes still flow: the deadline burst demoted nobody.
    monkeypatch.setattr(rig.router, "_forward", real)
    assert rig.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200


def test_router_deadline_and_trace():
    """The router honors deadlines at ITS door (an expired request never
    reaches a group) and forwards the remaining budget on the hop; a
    forced trace tags the root with the serving group and grafts the
    group's own span tree under the forward span."""
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, tracer=Tracer())
        try:
            rig.seed()
            assert rig.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
            # Expired at the router door: 504 before any forward.
            st, _, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))',
                                 headers={"X-Pilosa-Deadline-Ms": "0"})
            assert st == 504
            # Forced trace rides the hop and lands in the router ring.
            st, body, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))',
                                    headers={"X-Pilosa-Trace": "1"})
            assert st == 200 and json.loads(body)["results"] == [1]
            traces = json.loads(rig.req("GET", "/debug/traces")[1])["traces"]
            root = traces[0]["spans"]
            assert root["tags"]["group"] in ("g0", "g1")
            fwd = [c for c in root.get("children", []) if c["name"] == "forward"]
            assert fwd and fwd[0]["tags"]["group"] == root["tags"]["group"]
            # The group's own span tree (its "POST /index/i/query" root)
            # was grafted under the forward span — one trace, both sides.
            assert any(
                "query" in c.get("name", "") for c in fwd[0].get("children", [])
            ), fwd[0]
        finally:
            rig.close()


def test_router_debug_vars_http(rig):
    rig.seed()
    assert rig.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
    assert rig.query('Count(Bitmap(rowID=1, frame="f"))')[0] == 200
    st, body, _ = rig.req("GET", "/debug/vars")
    assert st == 200
    snap = json.loads(body)
    assert snap["replica.write_fanout"] >= 1
    assert any(k.startswith("replica.routed.") for k in snap)
    assert snap["replica.healthy.g0"] == 1 and snap["replica.healthy.g1"] == 1


def test_epoch_bump_detection(rig):
    """A changed X-Pilosa-Group epoch on a group's responses (job
    restart) is recorded and counted — the router's signal that the
    group's in-memory generation vectors were rebuilt."""
    g0 = rig.router.groups[0]
    rig.router._note_epoch(g0, "g0@1")
    rig.router._note_epoch(g0, "g0@1")
    assert rig.stats.snapshot().get("replica.epoch_bump", 0) == 0
    rig.router._note_epoch(g0, "g0@2")
    assert rig.stats.snapshot()["replica.epoch_bump"] == 1
    assert g0.epoch == "g0@2"


def test_group_header_on_plain_server(rig):
    """Every group-configured server stamps X-Pilosa-Group on every
    response (the router's attribution source), and /replica/health
    answers 200."""
    for i in range(2):
        rq = urllib.request.Request(f"http://{rig.servers[i].host}/version")
        with urllib.request.urlopen(rq, timeout=10) as resp:
            assert resp.headers.get(GROUP_HEADER) == f"g{i}"
        rq = urllib.request.Request(f"http://{rig.servers[i].host}/replica/health")
        with urllib.request.urlopen(rq, timeout=10) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["group"] == f"g{i}"


def test_client_surfaces_serving_group(rig):
    """Client.execute_query exposes which replica answered (and "all"
    for a router write), plus the router status helper."""
    from pilosa_tpu.server.client import Client

    rig.seed()
    c = Client(f"127.0.0.1:{rig.router.port}")
    resp = c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=4)')
    assert resp.get("group") == "all"
    resp = c.execute_query("i", 'Count(Bitmap(rowID=1, frame="f"))')
    assert resp.get("group") in ("g0", "g1")
    status = c.replica_status()
    assert status["quorate"] is True and len(status["groups"]) == 2


def test_no_failover_when_disabled():
    """[replica] failover = false: the first failed pick surfaces to
    the client instead of retrying a sibling."""
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig(tmp, failover=False)
        try:
            rig.seed()
            rig.servers[1].close()
            statuses = set()
            for _ in range(4):
                statuses.add(rig.query('Count(Bitmap(rowID=1, frame="f"))')[0])
            # The read that drew the dead group answered 503; once g1 is
            # marked unhealthy the rest route to g0 and succeed.
            assert 503 in statuses and 200 in statuses
            assert rig.stats.snapshot().get("replica.failover", 0) == 0
        finally:
            rig.close()


# -- config / CLI promotion --------------------------------------------------


def test_config_replica_promotion(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        "[replica]\n"
        'group = "g1@3"\n'
        'groups = ["g0=h0:1", "g1=h1:2"]\n'
        "router-port = 12345\n"
        "failover = false\n"
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.replica_group == "g1@3"
    assert cfg.replica_groups == ["g0=h0:1", "g1=h1:2"]
    assert cfg.replica_router_port == 12345
    assert cfg.replica_failover is False
    cfg.apply_env({
        "PILOSA_TPU_REPLICA_GROUP": "g2@5",
        "PILOSA_TPU_REPLICA_GROUPS": "a:1, b:2",
        "PILOSA_TPU_REPLICA_ROUTER_PORT": "4321",
        "PILOSA_TPU_REPLICA_FAILOVER": "true",
    })
    assert cfg.replica_group == "g2@5"
    assert cfg.replica_groups == ["a:1", "b:2"]
    assert cfg.replica_router_port == 4321
    assert cfg.replica_failover is True
    assert parse_group(cfg.replica_group) == ("g2", 5)
    assert parse_group("g0") == ("g0", 0)
    assert format_group("g2", 5) == "g2@5"
    assert format_group("") == ""


def test_router_from_config():
    from pilosa_tpu.replica import router_from_config

    cfg = Config(host="127.0.0.1:10101")
    cfg.replica_groups = ["127.0.0.1:1", "gX=127.0.0.1:2"]
    cfg.replica_router_port = 0
    cfg.replica_failover = False
    r = router_from_config(cfg)
    assert [g.name for g in r.groups] == ["g0", "gX"]
    assert r.failover is False and r.host == "127.0.0.1"


def test_cli_replica_router(rig, capsys):
    """The replica-router subcommand wires [replica] config + flags."""
    from pilosa_tpu.cli.main import build_parser

    p = build_parser()
    args = p.parse_args([
        "replica-router",
        "--groups", ",".join(f"g{i}={s.host}" for i, s in enumerate(rig.servers)),
        "--port", "0",
        "--test-exit",
    ])
    assert args.fn(args) == 0
    out = capsys.readouterr().out
    assert "replica-router" in out and "g0=" in out and "g1=" in out


def test_cli_replica_router_no_groups(capsys):
    from pilosa_tpu.cli.main import build_parser

    p = build_parser()
    args = p.parse_args(["replica-router", "--port", "0", "--test-exit"])
    assert args.fn(args) == 1


# -- lockstep group identity -------------------------------------------------


def test_streamed_ingest_converges_on_all_groups(rig):
    """Streamed columnar ingest through the router is a sequenced,
    WAL-logged write per chunk: every group applies every chunk in the
    same total order, both groups' contents (and digests) converge,
    and a replayed chunk acks idempotently."""
    import zlib

    from pilosa_tpu.ingest import encode_packed

    rig.seed()
    rng = np.random.default_rng(5)
    rows = rng.integers(0, 20, size=5000).astype(np.uint64)
    cols = rng.integers(0, 1 << 20, size=5000).astype(np.uint64)
    frames = [
        encode_packed(rows[i : i + 1024], cols[i : i + 1024])
        for i in range(0, 5000, 1024)
    ]
    total = sum(len(f) for f in frames)
    crc = 0
    for f in frames:
        crc = zlib.crc32(f, crc)
    off = 0
    body = b"{}"
    for fb in frames:
        st, body, hdrs = rig.req(
            "POST",
            f"/index/i/frame/f/ingest?off={off}&total={total}&crc={crc}"
            f"&ccrc={zlib.crc32(fb)}",
            fb,
        )
        assert st == 200, body
        assert hdrs.get(GROUP_HEADER) == "all"  # sequenced to every group
        off += len(fb)
    assert json.loads(body)["done"] is True
    # Both groups converge: identical per-row counts and digests.
    for r in (0, 3, 11):
        expect = len(np.unique(cols[rows == r]))
        q = f'Count(Bitmap(rowID={r}, frame="f"))'
        assert rig.direct_count(0, q) == rig.direct_count(1, q) == expect
    digests = []
    for srv in rig.servers:
        rq = urllib.request.Request(f"http://{srv.host}/replica/digest")
        digests.append(json.loads(urllib.request.urlopen(rq, timeout=10).read()))
    assert digests[0]["digest"] == digests[1]["digest"]
    # Idempotent replay of an applied chunk: deterministic 200, no
    # divergence (this is the WAL-replay delivery shape; the completed
    # transfer was dropped, so the replay opens a fresh one and the
    # re-applied bits converge).
    st, body, _ = rig.req(
        "POST",
        f"/index/i/frame/f/ingest?off=0&total={total}&crc={crc}"
        f"&ccrc={zlib.crc32(frames[0])}",
        frames[0],
    )
    assert st == 200 and json.loads(body)["staged"] == len(frames[0])
    q = 'Count(Bitmap(rowID=3, frame="f"))'
    assert rig.direct_count(0, q) == rig.direct_count(1, q)


def test_lockstep_group_epoch_guard(tmp_path):
    """A group-tagged LockstepService serves normally, and the worker
    epoch guard accepts only entries from ITS incarnation (legacy
    entries without the fields always pass)."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("g")
    idx.create_frame("f", FrameOptions())
    idx.frame("f").set_bit("standard", 1, 3)
    svc = LockstepService(
        h, control_addr=("127.0.0.1", 0), group="g0", group_epoch=2
    )
    assert svc.group == "g0" and svc.group_epoch == 2
    assert svc._execute("g", 'Count(Bitmap(rowID=1, frame="f"))') == [1]
    assert svc._epoch_ok({"op": "batch"})  # legacy wire: no identity
    assert svc._epoch_ok({"op": "batch", "group": "g0", "gepoch": 2})
    assert not svc._epoch_ok({"op": "batch", "group": "g0", "gepoch": 1})
    assert not svc._epoch_ok({"op": "batch", "group": "g9", "gepoch": 2})
    h.close()


def test_lockstep_front_end_serves_admin_gets(tmp_path):
    """The lockstep front end answers the common read-only admin GETs
    the router forwards like reads (/schema, /status, /slices/max,
    /version, /debug/vars, /debug/traces) — not just /replica/health —
    so admin tooling works unchanged through the router over lockstep
    groups."""
    import threading

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("g")
    idx.create_frame("f", FrameOptions())
    idx.frame("f").set_bit("standard", 1, 3)
    svc = LockstepService(
        h, control_addr=("127.0.0.1", 0), http_addr=("127.0.0.1", 0),
        group="g0", group_epoch=1,
    )
    threading.Thread(target=svc.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 10
    while svc._httpd is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc._httpd is not None, "lockstep front end never bound"
    base = f"http://{svc.http_addr[0]}:{svc.http_addr[1]}"

    def get(path):
        rq = urllib.request.Request(base + path)
        try:
            with urllib.request.urlopen(rq, timeout=10) as resp:
                return resp.status, json.loads(resp.read()), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, {}, dict(e.headers)

    try:
        st, schema, hdrs = get("/schema")
        assert st == 200 and [x["name"] for x in schema["indexes"]] == ["g"]
        assert hdrs.get(GROUP_HEADER) == "g0@1"
        st, status, _ = get("/status")
        assert st == 200 and status["status"]["state"] == "UP"
        assert status["status"]["group"] == "g0"
        st, sm, _ = get("/slices/max")
        assert st == 200 and "maxSlices" in sm
        st, ver, _ = get("/version")
        assert st == 200 and "version" in ver
        assert get("/debug/vars")[0] == 200
        st, tr, _ = get("/debug/traces")
        assert st == 200 and tr["traces"] == []
        assert get("/replica/health")[0] == 200
        # Content digest (PR 9): rank 0 computes over replicated state,
        # shape matches the full server's handler.
        st, dig, _ = get("/replica/digest")
        assert st == 200 and "g/f/standard/0" in dig["fragments"]
        assert dig["appliedSeq"] == 0 and dig["digest"]
        assert [x["name"] for x in dig["schema"]] == ["g"]
        assert get("/nope")[0] == 404
        # Through the router: admin GETs route like reads and now
        # answer over a lockstep group instead of 404ing.
        router = ReplicaRouter(
            [f"g0={svc.http_addr[0]}:{svc.http_addr[1]}"],
            stats=ExpvarStatsClient(),
        ).serve()
        try:
            rq = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/schema"
            )
            with urllib.request.urlopen(rq, timeout=10) as resp:
                assert resp.status == 200
                got = json.loads(resp.read())
                assert [x["name"] for x in got["indexes"]] == ["g"]
        finally:
            router.close()
    finally:
        svc.shutdown()
        h.close()


def test_lockstep_group_from_env(tmp_path, monkeypatch):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    monkeypatch.setenv("PILOSA_TPU_REPLICA_GROUP", "g7@4")
    h = Holder(str(tmp_path / "d"))
    h.open()
    svc = LockstepService(h, control_addr=("127.0.0.1", 0))
    assert svc.group == "g7" and svc.group_epoch == 4
    h.close()


# -- 2-D mesh construction ---------------------------------------------------


def test_replica_mesh_hybrid_fallback(rng):
    """ReplicaMesh(hybrid=True) on a host with NO DCN topology (this CPU
    rig) must fall back to the flat create_device_mesh reshape and stay
    numerically identical to the flat mesh — tier-1 never needs real
    multi-pod hardware."""
    import jax

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.parallel import ReplicaMesh, replica_gather_count

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ReplicaMesh(n_replicas=2, devices=jax.devices()[:8], hybrid=True)
    assert mesh.hybrid is False  # the fallback engaged (no DCN granules)
    assert mesh.n_devices == 4 and mesh.n_replicas == 2
    S, R, W, B = 8, 16, 1024, 12  # the proven test_parallel kernel shape
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
    got = np.asarray(replica_gather_count(
        mesh, "and", mesh.shard_stack(rm), jax.numpy.asarray(pairs), interpret=True
    ))
    want = [
        int(bw.np_popcount(rm[:, int(a)] & rm[:, int(b)]).sum()) for a, b in pairs
    ]
    assert got.tolist() == want


def test_build_group_mesh_single_process():
    """build_group_mesh picks the flat layout in a single-process job
    (no DCN to exploit) and returns a plain ReplicaMesh."""
    import jax

    from pilosa_tpu.parallel.sharded import ReplicaMesh
    from pilosa_tpu.replica import build_group_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = build_group_mesh(n_replicas=2)
    assert isinstance(mesh, ReplicaMesh)
    assert mesh.hybrid is False
    assert mesh.n_replicas == 2
