"""Tests for the deterministic interleaving explorer (analysis/sched.py)
and the replica write-protocol model / conformance checkers
(analysis/spec.py): explorer mechanics, the live-tree scenario gate,
the seeded known-bug fixtures with schedule-string replay, small-scope
model checking, trace conformance, linearizability, and the seeded
random-schedule fuzzer."""

import pytest

from pilosa_tpu.analysis import lockcheck, scenarios, sched, spec


# -- schedule strings --------------------------------------------------------


def test_schedule_string_roundtrip():
    seq = [0, 0, 0, 1, 1, 0, 2]
    s = sched.format_schedule(seq)
    assert s == "0x3,1x2,0,2"
    assert sched.parse_schedule(s) == seq
    assert sched.parse_schedule("") == []
    assert sched.parse_schedule("1") == [1]


# -- explorer mechanics (toy scenarios) --------------------------------------


class _LostUpdateCtx:
    """Unlocked read-modify-write on a guarded field: the canonical
    racy max()."""

    def __init__(self):
        self.g = _Guarded()

        def bump(n):
            def fn():
                cur = self.g.v
                self.g.v = max(cur, n)
            return fn

        self.threads = [bump(5), bump(9)]

    def check(self):
        assert self.g.v == 9, f"lost update: v={self.g.v}"


@lockcheck.guarded_class
class _Guarded:
    _guarded_by_ = {"v": "test.sched._mu"}

    def __init__(self):
        self.v = 0


class _DeadlockCtx:
    def __init__(self):
        self.a = lockcheck.named_lock("test.sched.A")
        self.b = lockcheck.named_lock("test.sched.B")

        def ab():
            with self.a:
                with self.b:
                    pass

        def ba():
            with self.b:
                with self.a:
                    pass

        self.threads = [ab, ba]

    def check(self):
        pass


def test_explorer_finds_lost_update_and_replays():
    sc = sched.Scenario("toy_lost_update", _LostUpdateCtx, known_bug=True)
    res = sched.explore(sc, bound=2)
    assert not res.ok
    bad = [o for o in res.outcomes if o.kind == "check"]
    assert bad
    # The printed schedule string replays the exact failure.
    outs = sched.replay(sc, bad[0].schedule)
    assert any(o.kind == "check" for o in outs)
    # A prefix that never interleaves (t0 runs out non-preempted, the
    # default policy completes the rest) stays clean.
    assert sched.replay(sc, "0") == []
    # A schedule prescribing a finished thread is reported, not hung.
    outs = sched.replay(sc, "0x50")
    assert any("diverged" in o.detail for o in outs)


def test_explorer_finds_deadlock_and_replays():
    sc = sched.Scenario("toy_deadlock", _DeadlockCtx, known_bug=True)
    res = sched.explore(sc, bound=2)
    dl = [o for o in res.outcomes if o.kind == "deadlock"]
    assert dl, res.describe()
    assert "test.sched" in dl[0].detail  # names the blocked locks
    outs = sched.replay(sc, dl[0].schedule)
    assert any(o.kind == "deadlock" for o in outs)


def test_explorer_bound_zero_is_single_nonpreemptive_family():
    # Bound 0 still explores forced switches (thread completion), so
    # the toy with 2 threads yields at least the two serial orders.
    sc = sched.Scenario("toy_lost_update", _LostUpdateCtx, known_bug=True)
    res0 = sched.explore(sc, bound=0)
    res2 = sched.explore(sc, bound=2)
    assert 1 <= res0.schedules <= res2.schedules


def test_explorer_determinism_same_bound_same_counts():
    for name in ("applied_seq_notes", "qcache_store_vs_write"):
        s = scenarios.get(name)
        a = sched.explore(s)
        b = sched.explore(s)
        assert a.schedules == b.schedules
        assert a.truncated == b.truncated
        assert sorted(o.schedule for o in a.outcomes) == sorted(
            o.schedule for o in b.outcomes
        )


# -- the tier-1 live-tree gate ----------------------------------------------


@pytest.mark.parametrize(
    "name", [s.name for s in scenarios.live_scenarios()]
)
def test_live_scenario_explores_clean(name):
    """Every registered non-fixture scenario must explore clean: a
    violation here is a REAL interleaving bug (fix it — do not baseline
    it)."""
    res = sched.explore(scenarios.get(name))
    assert res.ok, res.describe()
    assert res.schedules >= 2  # the exploration actually branched


# -- seeded known-bug fixtures ----------------------------------------------


@pytest.mark.parametrize(
    "name", [s.name for s in scenarios.known_bug_scenarios()]
)
def test_known_bug_found_and_schedule_replays(name):
    s = scenarios.get(name)
    res = sched.explore(s)
    assert res.outcomes, f"{name}: the seeded bug was NOT found"
    first = res.outcomes[0]
    outs = sched.replay(s, first.schedule)
    assert outs, f"{name}: schedule {first.schedule} did not reproduce"
    # Deterministic: the same schedule reproduces on every replay.
    outs2 = sched.replay(s, first.schedule)
    assert [o.kind for o in outs] == [o.kind for o in outs2]


def test_bug_compaction_flagged_by_trace_checker_too():
    res = sched.explore(scenarios.get("bug_compact_drops_unreplayed"))
    kinds = {o.kind for o in res.outcomes}
    assert "check" in kinds  # end-state invariant
    assert "trace" in kinds  # compact_plan floor conformance
    assert any("compaction floor" in o.detail for o in res.outcomes)


# -- small-scope exhaustive model checking -----------------------------------


def test_model_clean_at_small_scopes():
    for n_groups in (2, 3):
        res = spec.model_check(n_groups=n_groups, max_writes=2)
        assert res.ok, res.violations[:3]
        assert res.states > 100


def test_model_determinism():
    a = spec.model_check(n_groups=2, max_writes=2)
    b = spec.model_check(n_groups=2, max_writes=2)
    assert (a.states, a.transitions) == (b.states, b.transitions)


@pytest.mark.parametrize(
    "knob,needle",
    [
        ("break_quorum", "read-your-writes"),
        ("break_compaction", "lost"),
        ("break_abort", "tombstoned"),
    ],
)
def test_model_broken_variants_each_trip_their_invariant(knob, needle):
    res = spec.model_check(n_groups=3, max_writes=2, **{knob: True})
    assert not res.ok, f"{knob} explored clean — the checker is blind to it"
    assert any(needle in v for v in res.violations), res.violations[:3]


# -- sharded (2-shard x 2-replica) model -------------------------------------


def test_model_sharded_clean_at_issue_scope():
    # The PR 17 acceptance scope: 2 shards x 2 replicas, each shard its
    # own sequence space, shared restart budget — explores clean.
    res = spec.model_check_sharded(n_shards=2, n_groups=2)
    assert res.ok, res.violations[:3]
    assert res.states > 1000


def test_model_sharded_determinism():
    a = spec.model_check_sharded()
    b = spec.model_check_sharded()
    assert (a.states, a.transitions) == (b.states, b.transitions)


@pytest.mark.parametrize(
    "knob,kwargs,needle",
    [
        ("break_quorum", {}, "merged read"),
        ("break_compaction", {"n_groups": 3, "max_writes_per_shard": 2},
         "lost"),
        ("break_abort", {}, "tombstoned"),
        ("break_routing", {}, "foreign"),
    ],
)
def test_model_sharded_broken_variants_each_trip(knob, kwargs, needle):
    res = spec.model_check_sharded(**{knob: True}, **kwargs)
    assert not res.ok, f"{knob} explored clean — the checker is blind to it"
    assert any(needle in v for v in res.violations), res.violations[:3]


def test_model_reshard_clean_and_fence_rules_trip():
    res = spec.model_check_reshard()
    assert res.ok, res.violations[:3]
    for knob in ("break_fence", "break_clear"):
        broken = spec.model_check_reshard(**{knob: True})
        assert not broken.ok, f"{knob} explored clean"
        assert any("missing acked" in v for v in broken.violations)


def test_trace_reshard_epoch_must_advance():
    bad = [
        ("reshard", {"src": 9, "shard": "s0", "epoch": 1}),
        ("reshard", {"src": 9, "shard": "s0", "epoch": 1}),
    ]
    out = spec.check_trace(bad)
    assert any("epoch did not advance" in v for v in out)
    ok = [
        ("reshard", {"src": 9, "shard": "s0", "epoch": 1}),
        ("reshard", {"src": 9, "shard": "s0b", "epoch": 2}),
    ]
    assert spec.check_trace(ok) == []


# -- trace conformance -------------------------------------------------------


def _ev(kind, **f):
    f.setdefault("src", 1)
    return (kind, f)


def test_trace_clean_protocol_round():
    events = [
        _ev("config", groups=["g0", "g1"], quorum=2),
        _ev("append", seq=1),
        _ev("apply", group="g0", seq=1, ok=True),
        _ev("apply", group="g1", seq=1, ok=True),
        _ev("mark", group="g0", epoch="g0@1", value=1),
        _ev("ack", seq=1, status=200, applied=2),
        _ev("read", group="g0", applied=1),
        _ev("compact_plan", floor=1, tracked={"g0": 1, "g1": 1}, floors=[]),
        _ev("wal_compact", floor=1),
    ]
    assert spec.check_trace(events) == []


def test_trace_violations_each_detected():
    cases = {
        "not strictly increasing": [
            _ev("append", seq=2), _ev("append", seq=2),
        ],
        "tombstoned": [
            _ev("append", seq=1),
            _ev("apply", group="g0", seq=1, ok=True),
            _ev("abort", seq=1),
        ],
        "AFTER its abort": [
            _ev("append", seq=1),
            _ev("abort", seq=1),
            _ev("apply", group="g0", seq=1, ok=True),
        ],
        "< quorum": [
            _ev("config", groups=["a", "b", "c"], quorum=2),
            _ev("append", seq=1),
            _ev("apply", group="a", seq=1, ok=True),
            _ev("ack", seq=1, status=200, applied=1),
        ],
        "regressed": [
            _ev("mark", group="g0", epoch="g0@1", value=5),
            _ev("mark", group="g0", epoch="g0@1", value=3),
        ],
        "exceeds the minimum tracked": [
            _ev("compact_plan", floor=5, tracked={"g0": 5, "g1": 2},
                floors=[]),
        ],
        "read-your-writes": [
            _ev("append", seq=1),
            _ev("apply", group="g0", seq=1, ok=True),
            _ev("ack", seq=1, status=200, applied=1),
            _ev("read", group="g1", applied=0),
        ],
    }
    for needle, events in cases.items():
        got = spec.check_trace(events)
        assert any(needle in v for v in got), (needle, got)


def test_trace_mark_regress_allowed_across_epochs():
    events = [
        _ev("mark", group="g0", epoch="g0@1", value=5),
        _ev("probe_mark", group="g0", epoch="g0@2", value=2),  # restarted
        _ev("mark", group="g0", epoch="g0@2", value=3),
    ]
    assert spec.check_trace(events) == []
    # But the same regress WITHIN an epoch is a violation.
    events = [
        _ev("probe_mark", group="g0", epoch="g0@1", value=5),
        _ev("probe_mark", group="g0", epoch="g0@1", value=2),
    ]
    assert any("regressed" in v for v in spec.check_trace(events))


def test_trace_tolerates_pre_collector_sequences():
    # A recovered WAL replays records this trace never saw appended.
    events = [
        _ev("apply", group="g0", seq=7, ok=True, replay=True),
        _ev("mark", group="g0", epoch="g0@1", value=7),
    ]
    assert spec.check_trace(events) == []


def test_emit_zero_cost_when_uninstalled():
    assert not spec.collector_installed()
    spec.emit("append", src=1, seq=1)  # must be a no-op, not an error
    events = spec.install_collector()
    try:
        spec.emit("append", src=1, seq=1)
        assert events == [("append", {"src": 1, "seq": 1})]
    finally:
        spec.uninstall_collector()


# -- linearizability ---------------------------------------------------------


def test_linearizable_bitmap_history():
    h = spec.LinHistory()
    a = h.invoke(0, "set", (0, 1))
    h.respond(a, True)
    b = h.invoke(1, "count")
    h.respond(b, 1)
    ok, _ = spec.check_linearizable(h, frozenset(), spec.bitmap_apply)
    assert ok


def test_non_linearizable_bitmap_history_rejected():
    h = spec.LinHistory()
    # count=1 completes BEFORE any set is invoked: impossible.
    b = h.invoke(1, "count")
    h.respond(b, 1)
    a = h.invoke(0, "set", (0, 1))
    h.respond(a, True)
    ok, detail = spec.check_linearizable(h, frozenset(), spec.bitmap_apply)
    assert not ok
    assert "no linearization" in detail


def test_qcache_spec_allows_conservative_decline_rejects_stale_hit():
    # Declining a store the generation would have allowed: linearizable.
    h = spec.LinHistory()
    a = h.invoke(0, "store", ("v0", 0))
    h.respond(a, False)
    ok, _ = spec.check_linearizable(h, (None, 0), spec.qcache_apply)
    assert ok
    # A get returning a value whose generation moved: NOT linearizable.
    h = spec.LinHistory()
    a = h.invoke(0, "store", ("v0", 0))
    h.respond(a, True)
    b = h.invoke(1, "bump")
    h.respond(b, None)
    c = h.invoke(2, "get")
    h.respond(c, "v0")  # stale hit after the bump completed
    ok, _ = spec.check_linearizable(h, (None, 0), spec.qcache_apply)
    assert not ok


# -- seeded random-schedule fuzzing ------------------------------------------


def test_fuzz_smoke_live_scenarios_clean():
    """Tier-1 smoke slice: a few seeded random schedules per light
    scenario; the full sweep is the slow-marked test below."""
    for name in ("applied_seq_notes", "ingest_resume_vs_apply",
                 "qcache_store_vs_write"):
        res = sched.fuzz(scenarios.get(name), seed=1234, runs=4)
        assert res.ok, res.describe()


def test_fuzz_finds_seeded_bug_and_is_deterministic():
    s = scenarios.get("bug_applied_seq_lost_update")
    a = sched.fuzz(s, seed=7, runs=16)
    b = sched.fuzz(s, seed=7, runs=16)
    assert sorted(o.schedule for o in a.outcomes) == sorted(
        o.schedule for o in b.outcomes
    )
    assert a.outcomes, "16 random schedules never lost the update"
    # The fuzz failure replays through the same schedule-string lane.
    outs = sched.replay(s, a.outcomes[0].schedule)
    assert any(o.kind == "check" for o in outs)


@pytest.mark.slow
def test_fuzz_sweep_fixed_seeds():
    """Dependency-free slow sweep: many deterministic seeds over every
    live scenario (the hypothesis variant below widens the draw where
    hypothesis is installed)."""
    for seed in range(8):
        for s in scenarios.live_scenarios():
            res = sched.fuzz(s, seed=seed, runs=4)
            assert res.ok, res.describe()


@pytest.mark.slow
def test_fuzz_sweep_hypothesis_seeds():
    """Beyond the preemption bound: hypothesis-drawn seeds over every
    live scenario (deterministic per seed — failures print replayable
    schedule strings)."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import HealthCheck, given, settings, strategies as st

    @given(seed=st.integers(min_value=0, max_value=2**32 - 1))
    @settings(max_examples=12, deadline=None,
              suppress_health_check=list(HealthCheck))
    def sweep(seed):
        for s in scenarios.live_scenarios():
            res = sched.fuzz(s, seed=seed, runs=3)
            assert res.ok, res.describe()

    sweep()


# -- the WAL bug this PR's explorer found ------------------------------------


def test_wal_append_after_close_refuses(tmp_path):
    """The append-vs-close scenario found a file-backed WAL silently
    buffering post-close appends to memory (a seq ACKed into nothing);
    it must refuse instead."""
    from pilosa_tpu.replica.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "w.wal"), fsync=False)
    wal.append("POST", "/a", b"x")
    wal.close()
    with pytest.raises(OSError):
        wal.append("POST", "/b", b"y")
    with pytest.raises(OSError):
        wal.abort(1)
    # The in-memory log's close stays a no-op (no durability to lose).
    mem = WriteAheadLog(None)
    mem.append("POST", "/a", b"x")
    mem.close()
    assert mem.append("POST", "/b", b"y") == 2


# -- CLI ---------------------------------------------------------------------


def test_cli_explore_lists_scenarios(capsys):
    from pilosa_tpu.analysis.__main__ import main

    assert main(["--explore"]) == 0
    out = capsys.readouterr().out
    assert "wal_append_vs_compact" in out
    assert "known-bug fixture" in out


def test_cli_explore_runs_one_scenario(capsys):
    from pilosa_tpu.analysis.__main__ import main

    assert main(["--explore", "applied_seq_notes"]) == 0
    out = capsys.readouterr().out
    assert "applied_seq_notes" in out and "schedule(s)" in out


def test_cli_explore_bug_scenario_exits_nonzero_with_schedule(capsys):
    from pilosa_tpu.analysis.__main__ import main

    assert main(["--explore", "bug_applied_seq_lost_update"]) == 1
    out = capsys.readouterr().out
    assert "schedule" in out
    # Pull a printed schedule and replay it through the CLI.
    line = next(l for l in out.splitlines() if "[check] schedule" in l)
    schedule = line.split("schedule", 1)[1].strip()
    assert main(["--explore", "bug_applied_seq_lost_update",
                 "--schedule", schedule]) == 1


def test_cli_replay_clean_schedule_exits_zero(capsys):
    from pilosa_tpu.analysis.__main__ import main

    assert main(["--explore", "applied_seq_notes", "--schedule", "0"]) == 0
    assert "replayed clean" in capsys.readouterr().out
