"""Anti-entropy tests: two replicated nodes converge after divergence
(reference analog: fragment syncer paths fragment.go:1300-1481 +
holder.go:364-562)."""

import socket

import pytest

from pilosa_tpu.config import ClusterConfig, Config
from pilosa_tpu.server.client import Client
from pilosa_tpu.server.server import Server


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def two_replicated_nodes(tmp_path):
    hosts = [f"127.0.0.1:{free_port()}" for _ in range(2)]
    servers = []
    for i, h in enumerate(hosts):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            host=h,
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts), replica_n=2),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_fragment_sync_converges(two_replicated_nodes):
    s0, s1 = two_replicated_nodes
    c0, c1 = Client(s0.host), Client(s1.host)
    for c in (c0, c1):
        c.create_index("i")
        c.create_frame("i", "f")
    # Diverge: write different bits directly to each node (remote=True stops
    # forwarding, simulating a missed replica write).
    c0.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=10)', remote=True)
    c0.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=11)', remote=True)
    c1.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=11)', remote=True)
    c1.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=12)', remote=True)

    # Run anti-entropy on node 0: majority(2)=1 → union convergence.
    s0.syncer.sync_holder()

    r0 = c0.execute_query("i", 'Bitmap(rowID=1, frame="f")', remote=True)
    r1 = c1.execute_query("i", 'Bitmap(rowID=1, frame="f")', remote=True)
    assert r0["results"][0]["bitmap"]["bits"] == [10, 11, 12]
    assert r1["results"][0]["bitmap"]["bits"] == [10, 11, 12]


def test_attr_sync(two_replicated_nodes):
    s0, s1 = two_replicated_nodes
    c0, c1 = Client(s0.host), Client(s1.host)
    for c in (c0, c1):
        c.create_index("i")
        c.create_frame("i", "f")
    # Write attrs only to node 1 (remote bypasses broadcast).
    s1.executor.execute("i", 'SetRowAttrs(rowID=3, frame="f", name="bob")')
    s1.executor.execute("i", 'SetColumnAttrs(columnID=8, tag="z")')
    s0.syncer.sync_holder()
    assert s0.holder.frame("i", "f").row_attr_store.attrs(3) == {"name": "bob"}
    assert s0.holder.index("i").column_attr_store.attrs(8) == {"tag": "z"}


@pytest.fixture
def three_replicated_nodes(tmp_path):
    hosts = [f"127.0.0.1:{free_port()}" for _ in range(3)]
    servers = []
    for i, h in enumerate(hosts):
        cfg = Config(
            data_dir=str(tmp_path / f"m{i}"),
            host=h,
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts), replica_n=3),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_three_node_majority_vote(three_replicated_nodes):
    """With 3 replicas the merge threshold is 2 (fragment.go:802-920
    setN >= (len+1)/2): bits on >=2 nodes survive, bits on exactly one
    node are CLEARED everywhere — not unioned."""
    servers = three_replicated_nodes
    clients = [Client(s.host) for s in servers]
    for c in clients:
        c.create_index("i")
        c.create_frame("i", "f")
    # col=1 on all three; col=2 on two nodes; col=3 on one node only.
    for c in clients:
        c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=1)', remote=True)
    for c in clients[:2]:
        c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=2)', remote=True)
    clients[2].execute_query("i", 'SetBit(rowID=1, frame="f", columnID=3)', remote=True)

    servers[0].syncer.sync_holder()

    for c in clients:
        r = c.execute_query("i", 'Bitmap(rowID=1, frame="f")', remote=True)
        assert r["results"][0]["bitmap"]["bits"] == [1, 2]


def test_sync_survives_down_peer(two_replicated_nodes):
    """A dead replica must not break anti-entropy for the live pair
    (executor.go:1147-1159-style degradation: skip, don't crash) — but
    the skips must be VISIBLE: syncer.peer_errors counts per node and
    the last error string lands at /debug/vars, so a silent anti-entropy
    stall shows on a dashboard instead of only as diverging replicas."""
    s0, s1 = two_replicated_nodes
    c0 = Client(s0.host)
    for c in (c0, Client(s1.host)):
        c.create_index("i")
        c.create_frame("i", "f")
    c0.execute_query("i", 'SetBit(rowID=5, frame="f", columnID=77)', remote=True)
    assert s0.syncer.stat_peer_errors == 0
    s1.close()  # peer goes dark
    s0.syncer.sync_holder()  # must not raise
    r = c0.execute_query("i", 'Bitmap(rowID=5, frame="f")', remote=True)
    assert r["results"][0]["bitmap"]["bits"] == [77]
    # Every swallowed peer failure was counted, node-tagged, with the
    # last error string kept.
    assert s0.syncer.stat_peer_errors > 0
    assert s1.host in s0.syncer.last_peer_error
    snap = s0.stats.snapshot()
    key = f"syncer.peer_errors[node:{s1.host}]"
    assert snap.get(key, 0) == s0.syncer.stat_peer_errors
    assert s1.host in snap.get("syncer.last_peer_error", "")


def test_syncer_counts_errors_without_stats_client(tmp_path):
    """Directly-constructed syncers (no stats sink) still count — the
    NOP stats coercion keeps emission sites guard-free."""
    from pilosa_tpu.cluster import Cluster, Node
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server.client import Client as _Client
    from pilosa_tpu.syncer import HolderSyncer

    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("i")
    cluster = Cluster(
        nodes=[Node(host="127.0.0.1:1"), Node(host="127.0.0.1:9")], replica_n=2
    )
    sy = HolderSyncer(
        h, cluster, "127.0.0.1:1", lambda host: _Client(host, timeout=0.2)
    )
    sy.sync_index_attrs("i")  # dead peer: swallowed, counted
    assert sy.stat_peer_errors == 1
    assert "127.0.0.1:9" in sy.last_peer_error
    h.close()
