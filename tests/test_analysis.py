"""Tests for pilosa_tpu/analysis/: the five lint rules (golden firing +
passing fixtures each), suppression-comment and baseline round-trips,
the counters-registry generation/drift check, the runtime lock checker
(seeded order inversion, seeded blocking-under-lock, allowlists), the
CLI, and the LIVE-TREE GATE — the tier-1 test that runs every pass over
the real package and fails on new findings (the in-suite half of the CI
wiring; run_big_benches.sh runs the same gate as a preflight).
"""

import os
import textwrap
import threading

import pytest

from pilosa_tpu.analysis import engine, lockcheck, registry
from pilosa_tpu.analysis.__main__ import main as analysis_main


# -- fixture harness --------------------------------------------------------


def _mkpkg(tmp_path, files: dict, registry_for=None):
    """Materialize a fake package tree and return its root path.
    ``registry_for`` writes a COUNTERS.md matching the given tree (or
    an explicit text when a str is passed)."""
    root = tmp_path / "pkg"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    (root / "analysis").mkdir(exist_ok=True)
    if registry_for is not None:
        text = (
            registry_for
            if isinstance(registry_for, str)
            else registry.generate_counters_registry(str(root))
        )
        (root / "analysis" / registry.REGISTRY_NAME).write_text(text)
    return str(root)


def _run(root, rules):
    return engine.run_analysis(root=root, rules=rules)


def _new(findings):
    return engine.new_findings(findings)


# -- rule 1: lockstep-determinism ------------------------------------------

_DET_FIRING = {
    "parallel/service.py": """
    import os
    import time

    class Service:
        def _exec_batch_entries(self, entries):
            return det_helper(entries)

    def det_helper(entries):
        t = time.time()
        for x in {1, 2, 3}:
            t += x
        mode = os.environ.get("SOME_VAR")
        return t, mode
    """,
}


def test_determinism_fires_on_reachable_nondeterminism(tmp_path):
    root = _mkpkg(tmp_path, _DET_FIRING)
    msgs = [f.message for f in _new(_run(root, ("lockstep-determinism",)))]
    assert any("wall clock" in m for m in msgs)
    assert any("iteration over a set" in m for m in msgs)
    assert any("environment read" in m for m in msgs)


def test_determinism_passes_unreachable_and_sorted(tmp_path):
    files = {
        "parallel/service.py": """
        import time

        class Service:
            def _exec_batch_entries(self, entries):
                for x in sorted({1, 2, 3}):
                    pass
                return len(entries)

        def never_called_from_batch():
            return time.time()
        """,
    }
    root = _mkpkg(tmp_path, files)
    assert _new(_run(root, ("lockstep-determinism",))) == []


# -- rule 2: lock-discipline ------------------------------------------------


def test_lock_discipline_fires_on_raw_primitive(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        import threading

        class T:
            def __init__(self):
                self.mu = threading.Lock()
                self.cv = threading.Condition()
        """},
    )
    fs = _new(_run(root, ("lock-discipline",)))
    assert len(fs) == 2
    assert "named_lock" in fs[0].message
    assert "named_condition" in fs[1].message


def test_lock_discipline_passes_factories(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        from pilosa_tpu.analysis import lockcheck

        class T:
            def __init__(self):
                self.mu = lockcheck.named_lock("t.mu")
                self.cv = lockcheck.named_condition("t.cv")
        """},
    )
    assert _new(_run(root, ("lock-discipline",))) == []


# -- rule 3: stats-registry -------------------------------------------------

_STATS_MOD = {
    "mod.py": """
    class T:
        def __init__(self, stats):
            self.stats = stats

        def work(self, cls):
            self.stats.count("t.known")
            self.stats.gauge(f"t.by_class.{cls}", 1)
    """,
}


def test_stats_registry_passes_when_registered(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD, registry_for=True)
    text = (tmp_path / "pkg" / "analysis" / registry.REGISTRY_NAME).read_text()
    # f-strings normalize to <x> patterns in the generated registry
    assert "`t.by_class.<cls>`" in text
    assert _new(_run(root, ("stats-registry",))) == []


def test_stats_registry_fires_on_unknown_name_and_drift(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD, registry_for=True)
    # a new emission lands without regenerating the registry
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text(mod.read_text().replace(
        'self.stats.count("t.known")',
        'self.stats.count("t.known")\n        self.stats.count("t.brand_new")',
    ))
    fs = _new(_run(root, ("stats-registry",)))
    assert any("`t.brand_new` not in the counters registry" in f.message for f in fs)
    assert any("registry is stale" in f.message and "--write-registry" in f.message
               for f in fs)


def test_stats_registry_fires_when_missing(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD)
    fs = _new(_run(root, ("stats-registry",)))
    assert len(fs) == 1 and "registry missing" in fs[0].message


# -- rule 4: exception-hygiene ----------------------------------------------


def test_exception_hygiene_fires_on_silent_swallow(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            except Exception:
                pass
        """},
    )
    fs = _new(_run(root, ("exception-hygiene",)))
    assert len(fs) == 1 and "broad except swallows" in fs[0].message


def test_exception_hygiene_passes_stat_reraise_use(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def counted(stats):
            try:
                g()
            except Exception:
                stats.count("mod.errors")

        def reraised():
            try:
                g()
            except Exception:
                raise RuntimeError("wrapped")

        def used(errs):
            try:
                g()
            except Exception as e:
                errs.append(e)

        def narrow():
            try:
                g()
            except ValueError:
                pass
        """},
    )
    assert _new(_run(root, ("exception-hygiene",))) == []


# -- rule 5: deadline-propagation ------------------------------------------


def test_deadline_propagation_fires_on_dropped_budget(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def fan_out(client, index, q, deadline):
            return client.execute_remote(index, q)
        """},
    )
    fs = _new(_run(root, ("deadline-propagation",)))
    assert len(fs) == 1 and "without deadline=" in fs[0].message


def test_deadline_propagation_passes_forwarded(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def fan_out(client, index, q, deadline):
            return client.execute_remote(index, q, deadline=deadline)

        def via_opts(client, index, q, opt):
            return client.execute_remote(index, q, deadline=opt.deadline)

        def via_kwargs(client, index, q, deadline, kw):
            return client.execute_remote(index, q, **kw)

        def no_deadline_in_scope(client, index, q):
            return client.execute_remote(index, q)
        """},
    )
    assert _new(_run(root, ("deadline-propagation",))) == []


# -- suppression + baseline round-trips ------------------------------------


def test_suppression_comment_round_trip(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            # analysis-ok: exception-hygiene: fixture reason
            except Exception:
                pass

        def g():
            try:
                h()
            # analysis-ok: exception-hygiene:
            except Exception:
                pass
        """},
    )
    fs = _run(root, ("exception-hygiene",))
    assert len(fs) == 2
    by_scope = {f.scope: f for f in fs}
    assert by_scope["f"].suppressed  # reason given
    assert not by_scope["g"].suppressed  # empty reason does not suppress
    assert [f.scope for f in _new(fs)] == ["g"]


def test_baseline_round_trip(tmp_path):
    files = {"mod.py": """
    def f():
        try:
            g()
        except Exception:
            pass
    """}
    root = _mkpkg(tmp_path, files)
    fs = _run(root, ("exception-hygiene",))
    assert len(_new(fs)) == 1
    engine.write_baseline(engine.baseline_path(root), fs)
    fs2 = _run(root, ("exception-hygiene",))
    assert len(fs2) == 1 and fs2[0].baselined
    assert _new(fs2) == []
    # a SECOND identical violation in the same scope is NEW (occurrence
    # index keeps fingerprints distinct)
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text(mod.read_text() + textwrap.dedent("""
    def f2():
        try:
            g()
        except Exception:
            pass
    """))
    fs3 = _run(root, ("exception-hygiene",))
    assert len(_new(fs3)) == 1


# -- CLI --------------------------------------------------------------------


def test_cli_exit_codes_and_write_flows(tmp_path, capsys):
    root = _mkpkg(tmp_path, _STATS_MOD)
    # registry missing -> nonzero
    assert analysis_main(["--root", root, "--rules", "stats-registry"]) == 1
    assert analysis_main(["--root", root, "--write-registry"]) == 0
    assert analysis_main(["--root", root, "--rules", "stats-registry"]) == 0
    assert analysis_main(["--root", root, "--rules", "nope"]) == 2
    out = capsys.readouterr().out
    assert "0 NEW" in out


# -- runtime lock checker ---------------------------------------------------


@pytest.fixture
def checker():
    """Explicitly-enabled checker, restored afterwards (this module is
    not in conftest's auto-enabled set)."""
    lockcheck.enable()
    lockcheck.reset()
    try:
        yield lockcheck.checker()
    finally:
        lockcheck.take_violations()
        lockcheck.disable()


def test_lockcheck_seeded_order_inversion(checker):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()
    vs = lockcheck.take_violations()
    assert len(vs) == 1 and vs[0].kind == "lock-order-cycle"
    assert "t.a" in vs[0].detail and "t.b" in vs[0].detail


def test_lockcheck_consistent_order_is_clean(checker):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockcheck.take_violations() == []


def test_lockcheck_rlock_reentry_no_self_edge(checker):
    r = lockcheck.named_rlock("t.r")
    with r:
        with r:
            pass
    assert lockcheck.take_violations() == []


def test_lockcheck_seeded_blocking_under_lock(checker, tmp_path):
    mu = lockcheck.named_lock("t.mu")
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            os.fsync(f.fileno())
        vs = lockcheck.take_violations()
        assert len(vs) == 1 and vs[0].kind == "blocking-under-lock"
        assert "fsync" in vs[0].detail and "t.mu" in vs[0].detail
    finally:
        f.close()


def test_lockcheck_blocking_without_lock_is_clean(checker, tmp_path):
    f = open(tmp_path / "x", "wb")
    try:
        os.fsync(f.fileno())
    finally:
        f.close()
    assert lockcheck.take_violations() == []


def test_lockcheck_scoped_allow(checker, tmp_path):
    mu = lockcheck.named_lock("t.mu")
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            with lockcheck.allowed("fsync"):
                os.fsync(f.fileno())
    finally:
        f.close()
    assert lockcheck.take_violations() == []


def test_lockcheck_allowlist_pair(checker, tmp_path):
    mu = lockcheck.named_lock("t.allowed_mu")
    checker.allow_pairs.add(("t.allowed_mu", "fsync"))
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            os.fsync(f.fileno())
    finally:
        f.close()
        checker.allow_pairs.discard(("t.allowed_mu", "fsync"))
    assert lockcheck.take_violations() == []


def test_lockcheck_condition_wait_releases_held_state(checker):
    cv = lockcheck.named_condition("t.cv")
    other = lockcheck.named_lock("t.other")
    woke = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
        # after the wait returned we re-held and released t.cv; taking
        # another lock now must not see t.cv as held
        with other:
            pass
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    # let the waiter block, then wake it
    import time

    time.sleep(0.1)
    with cv:
        cv.notify_all()
    t.join(timeout=5.0)
    assert woke.is_set()
    assert lockcheck.take_violations() == []


def test_lockcheck_disabled_factories_are_plain():
    assert not lockcheck.enabled()
    assert type(lockcheck.named_lock("x")) is type(threading.Lock())
    assert isinstance(lockcheck.named_rlock("x"), type(threading.RLock()))


# -- the live-tree gate (CI smoke tier) ------------------------------------


def test_live_tree_analysis_gate():
    """`python -m pilosa_tpu.analysis` over the REAL package: every rule
    runs and no new findings exist.  This is the tier-1 CI gate — a new
    un-suppressed, un-baselined finding fails the suite with the same
    report the CLI prints."""
    findings = engine.run_analysis()
    fresh = engine.new_findings(findings)
    assert fresh == [], "new analysis findings:\n" + "\n".join(
        f.render() for f in fresh
    )


def test_live_tree_registry_is_current():
    """Committed COUNTERS.md must match regeneration exactly (the
    stats-registry drift half of the gate, asserted directly so the
    failure message carries the regenerate hint)."""
    root = engine.package_root()
    with open(registry.registry_path(root), encoding="utf-8") as f:
        committed = f.read()
    assert committed == registry.generate_counters_registry(root), (
        "counters registry is stale — run "
        "`python -m pilosa_tpu.analysis --write-registry` and commit"
    )
