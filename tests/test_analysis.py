"""Tests for pilosa_tpu/analysis/: the lint rules (golden firing +
passing fixtures each), suppression-comment and baseline round-trips,
the counters-registry generation/drift check, the runtime lock checker
(seeded order inversion, seeded blocking-under-lock, allowlists, the
generation-2 lockset race detector), the native-abi conformance gate,
the stale-suppression sweep, the CLI, and the LIVE-TREE GATE — the
tier-1 test that runs every pass over the real package and fails on new
findings (the in-suite half of the CI wiring; run_big_benches.sh runs
the same gate as a preflight).
"""

import os
import textwrap
import threading

import pytest

from pilosa_tpu.analysis import engine, lockcheck, registry
from pilosa_tpu.analysis.__main__ import main as analysis_main


# -- fixture harness --------------------------------------------------------


def _mkpkg(tmp_path, files: dict, registry_for=None):
    """Materialize a fake package tree and return its root path.
    ``registry_for`` writes a COUNTERS.md matching the given tree (or
    an explicit text when a str is passed)."""
    root = tmp_path / "pkg"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    (root / "analysis").mkdir(exist_ok=True)
    if registry_for is not None:
        text = (
            registry_for
            if isinstance(registry_for, str)
            else registry.generate_counters_registry(str(root))
        )
        (root / "analysis" / registry.REGISTRY_NAME).write_text(text)
    return str(root)


def _run(root, rules):
    return engine.run_analysis(root=root, rules=rules)


def _new(findings):
    return engine.new_findings(findings)


# -- rule 1: lockstep-determinism ------------------------------------------

_DET_FIRING = {
    "parallel/service.py": """
    import os
    import time

    class Service:
        def _exec_batch_entries(self, entries):
            return det_helper(entries)

    def det_helper(entries):
        t = time.time()
        for x in {1, 2, 3}:
            t += x
        mode = os.environ.get("SOME_VAR")
        return t, mode
    """,
}


def test_determinism_fires_on_reachable_nondeterminism(tmp_path):
    root = _mkpkg(tmp_path, _DET_FIRING)
    msgs = [f.message for f in _new(_run(root, ("lockstep-determinism",)))]
    assert any("wall clock" in m for m in msgs)
    assert any("iteration over a set" in m for m in msgs)
    assert any("environment read" in m for m in msgs)


def test_determinism_passes_unreachable_and_sorted(tmp_path):
    files = {
        "parallel/service.py": """
        import time

        class Service:
            def _exec_batch_entries(self, entries):
                for x in sorted({1, 2, 3}):
                    pass
                return len(entries)

        def never_called_from_batch():
            return time.time()
        """,
    }
    root = _mkpkg(tmp_path, files)
    assert _new(_run(root, ("lockstep-determinism",))) == []


# -- rule 2: lock-discipline ------------------------------------------------


def test_lock_discipline_fires_on_raw_primitive(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        import threading

        class T:
            def __init__(self):
                self.mu = threading.Lock()
                self.cv = threading.Condition()
        """},
    )
    fs = _new(_run(root, ("lock-discipline",)))
    assert len(fs) == 2
    assert "named_lock" in fs[0].message
    assert "named_condition" in fs[1].message


def test_lock_discipline_passes_factories(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        from pilosa_tpu.analysis import lockcheck

        class T:
            def __init__(self):
                self.mu = lockcheck.named_lock("t.mu")
                self.cv = lockcheck.named_condition("t.cv")
        """},
    )
    assert _new(_run(root, ("lock-discipline",))) == []


# -- rule 3: stats-registry -------------------------------------------------

_STATS_MOD = {
    "mod.py": """
    class T:
        def __init__(self, stats):
            self.stats = stats

        def work(self, cls):
            self.stats.count("t.known")
            self.stats.gauge(f"t.by_class.{cls}", 1)
    """,
}


def test_stats_registry_passes_when_registered(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD, registry_for=True)
    text = (tmp_path / "pkg" / "analysis" / registry.REGISTRY_NAME).read_text()
    # f-strings normalize to <x> patterns in the generated registry
    assert "`t.by_class.<cls>`" in text
    assert _new(_run(root, ("stats-registry",))) == []


def test_stats_registry_fires_on_unknown_name_and_drift(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD, registry_for=True)
    # a new emission lands without regenerating the registry
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text(mod.read_text().replace(
        'self.stats.count("t.known")',
        'self.stats.count("t.known")\n        self.stats.count("t.brand_new")',
    ))
    fs = _new(_run(root, ("stats-registry",)))
    assert any("`t.brand_new` not in the counters registry" in f.message for f in fs)
    assert any("registry is stale" in f.message and "--write-registry" in f.message
               for f in fs)


def test_stats_registry_fires_when_missing(tmp_path):
    root = _mkpkg(tmp_path, _STATS_MOD)
    fs = _new(_run(root, ("stats-registry",)))
    assert len(fs) == 1 and "registry missing" in fs[0].message


# -- rule 4: exception-hygiene ----------------------------------------------


def test_exception_hygiene_fires_on_silent_swallow(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            except Exception:
                pass
        """},
    )
    fs = _new(_run(root, ("exception-hygiene",)))
    assert len(fs) == 1 and "broad except swallows" in fs[0].message


def test_exception_hygiene_passes_stat_reraise_use(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def counted(stats):
            try:
                g()
            except Exception:
                stats.count("mod.errors")

        def reraised():
            try:
                g()
            except Exception:
                raise RuntimeError("wrapped")

        def used(errs):
            try:
                g()
            except Exception as e:
                errs.append(e)

        def narrow():
            try:
                g()
            except ValueError:
                pass
        """},
    )
    assert _new(_run(root, ("exception-hygiene",))) == []


# -- rule 5: deadline-propagation ------------------------------------------


def test_deadline_propagation_fires_on_dropped_budget(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def fan_out(client, index, q, deadline):
            return client.execute_remote(index, q)
        """},
    )
    fs = _new(_run(root, ("deadline-propagation",)))
    assert len(fs) == 1 and "without deadline=" in fs[0].message


def test_deadline_propagation_passes_forwarded(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def fan_out(client, index, q, deadline):
            return client.execute_remote(index, q, deadline=deadline)

        def via_opts(client, index, q, opt):
            return client.execute_remote(index, q, deadline=opt.deadline)

        def via_kwargs(client, index, q, deadline, kw):
            return client.execute_remote(index, q, **kw)

        def no_deadline_in_scope(client, index, q):
            return client.execute_remote(index, q)
        """},
    )
    assert _new(_run(root, ("deadline-propagation",))) == []


# -- rule 6: guarded-fields -------------------------------------------------


_GUARDED_FIRING = {
    "mod.py": """
    from pilosa_tpu.analysis import lockcheck

    class Store:
        _guarded_by_ = {"table": "store._mu", "count": "store._mu"}

        def __init__(self):
            self._mu = lockcheck.named_lock("store._mu")
            self.table = {}
            self.count = 0

        def racy_rebind(self):
            self.count = self.count + 1

        def racy_item(self, k, v):
            self.table[k] = v

        def racy_call(self, k):
            self.table.pop(k, None)
    """,
}


def test_guarded_fields_fires_on_unlocked_mutations(tmp_path):
    root = _mkpkg(tmp_path, _GUARDED_FIRING)
    fs = _new(_run(root, ("guarded-fields",)))
    kinds = {(f.scope, f.message.split("this ")[1].split(" mutation")[0]) for f in fs}
    assert ("Store.racy_rebind", "rebind") in kinds
    assert ("Store.racy_item", "item") in kinds
    assert ("Store.racy_call", "call") in kinds
    assert len(fs) == 3  # __init__ writes are lifecycle-exempt


def test_guarded_fields_passes_locked_and_locked_call_paths(tmp_path):
    files = {
        "mod.py": """
        from pilosa_tpu.analysis import lockcheck

        class Store:
            _guarded_by_ = {"table": "store._mu"}

            def __init__(self):
                self._mu = lockcheck.named_lock("store._mu")
                self.table = {}

            def put(self, k, v):
                with self._mu:
                    self.table[k] = v

            def _drop_locked(self, k):
                # no acquisition here, but every caller path holds one
                self.table.pop(k, None)

            def drop(self, k):
                with self._mu:
                    self._drop_locked(k)

            def open(self):
                self.table = {}  # lifecycle-exempt

            def _reset_from_open(self):
                self.table = {}  # only reachable from open(): init phase

        class NotDeclared:
            def free(self):
                self.table = {}
        """,
    }
    root = _mkpkg(tmp_path, files)
    # open() calls _reset_from_open through a non-stoplisted name
    p = tmp_path / "pkg" / "mod.py"
    p.write_text(p.read_text().replace(
        "self.table = {}  # lifecycle-exempt",
        "self.table = {}  # lifecycle-exempt\n        self._reset_from_open()",
    ))
    assert _new(_run(root, ("guarded-fields",))) == []


# -- rule 7: native-abi ------------------------------------------------------


_ABI_CPP_OK = """
#include <cstdint>
extern "C" {

int64_t pn_write_batch(const char* src, int64_t len,
                       const uint64_t* keys, int64_t* ns, int32_t wal_fd,
                       int64_t* applied) {
    (void)src; (void)keys; (void)ns; (void)applied; (void)wal_fd;
    return len;
}

uint64_t pn_fnv1a64(const uint8_t* data, size_t len) { (void)data; return len; }

}  // extern "C"

// outside extern "C": never considered
int64_t pn_internal_helper(int64_t x) { return x; }
"""

_ABI_PY_OK = """
import ctypes

def load():
    lib = ctypes.CDLL("x.so")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.pn_write_batch.restype = ctypes.c_int64
    lib.pn_write_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.pn_fnv1a64.restype = ctypes.c_uint64
    lib.pn_fnv1a64.argtypes = [u8p, ctypes.c_size_t]
    return lib
"""


def _mk_abi_tree(tmp_path, py_src=_ABI_PY_OK, cpp_src=_ABI_CPP_OK):
    root = _mkpkg(tmp_path, {"native.py": py_src})
    native_dir = tmp_path / "native"
    native_dir.mkdir(exist_ok=True)
    (native_dir / "pilosa_native.cpp").write_text(textwrap.dedent(cpp_src))
    return root


def test_native_abi_passes_conformant_fixture(tmp_path):
    root = _mk_abi_tree(tmp_path)
    assert _new(_run(root, ("native-abi",))) == []


def test_native_abi_fails_mutated_write_batch_signature(tmp_path):
    # The C side grows an argument (parse flags) — the Python table was
    # not updated: the classic silent-drift-into-memory-corruption case.
    mutated = _ABI_CPP_OK.replace(
        "int64_t* ns, int32_t wal_fd,",
        "int64_t* ns, int32_t wal_fd, int32_t flags,",
    )
    root = _mk_abi_tree(tmp_path, cpp_src=mutated)
    fs = _new(_run(root, ("native-abi",)))
    assert len(fs) == 1 and "arity mismatch" in fs[0].message
    assert fs[0].scope == "pn_write_batch" and fs[0].path == "native.py"


def test_native_abi_fails_width_mismatch_and_missing_symbol(tmp_path):
    # wal_fd narrows to int32 on the C side while Python says 64-bit,
    # and a declared function vanishes from the source entirely.
    py = _ABI_PY_OK.replace("ctypes.c_int32,", "ctypes.c_int64,")
    py = py.replace(
        "    return lib",
        "    lib.pn_vanished.restype = None\n"
        "    lib.pn_vanished.argtypes = []\n"
        "    return lib",
    )
    root = _mk_abi_tree(tmp_path, py_src=py)
    msgs = [f.message for f in _new(_run(root, ("native-abi",)))]
    assert any("width mismatch" in m and "pn_write_batch" in m for m in msgs)
    assert any("missing symbol" in m and "pn_vanished" in m for m in msgs)


def test_native_abi_real_tree_is_conformant():
    """The real bridge (30 signatures incl. the 22-arg pn_write_batch)
    against the real C++ and the built .so: zero issues.  Part of the
    live gate too; asserted directly so a drift names the function."""
    from pilosa_tpu.analysis import abi, rules

    root = engine.package_root()
    native_dir = os.path.join(os.path.dirname(root), "native")
    cpp = os.path.join(native_dir, rules.NATIVE_CPP_NAME)
    if not os.path.exists(cpp):
        pytest.skip("no native source next to the package")
    issues = abi.check_abi(
        cpp, os.path.join(root, "native.py"),
        so_path=os.path.join(native_dir, rules.NATIVE_SO_NAME),
    )
    assert issues == [], "\n".join(i.message for i in issues)
    # The parser really covered the bridge (a regression that parses
    # nothing would vacuously pass): every declared pn_* was matched.
    decls = abi.parse_ctypes_decls(os.path.join(root, "native.py"))
    assert len(decls) >= 20
    assert "pn_write_batch" in decls and len(decls["pn_write_batch"][1]) == 23


# -- rule 8: stale-suppression ----------------------------------------------


def test_stale_suppression_fires_on_dead_and_unknown_tags(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            # analysis-ok: exception-hygiene: live tag, still fires below
            except Exception:
                pass

        # analysis-ok: exception-hygiene: nothing fires at this site
        X = 1
        # analysis-ok: no-such-rule: bogus rule name
        Y = 2
        """},
    )
    fs = _new(_run(root, ("exception-hygiene", "stale-suppression")))
    assert len(fs) == 2
    assert all(f.rule == "stale-suppression" for f in fs)
    assert any("no longer matches any finding" in f.message for f in fs)
    assert any("unknown rule `no-such-rule`" in f.message for f in fs)


def test_stale_suppression_subset_run_spares_other_rules_tags(tmp_path):
    # A lock-discipline-only run must not call a live exception-hygiene
    # tag stale just because that rule didn't run.
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            # analysis-ok: exception-hygiene: live tag
            except Exception:
                pass
        """},
    )
    assert _new(_run(root, ("lock-discipline", "stale-suppression"))) == []
    # ...but the full run keeps it counted as USED, not stale.
    assert _new(_run(root, ("exception-hygiene", "stale-suppression"))) == []


def test_stale_suppression_empty_reason_tag_is_not_double_reported(tmp_path):
    # An empty-reason tag does not suppress (the finding stays NEW) —
    # but it is attached to a live finding, so the sweep must not ALSO
    # call it stale.
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            # analysis-ok: exception-hygiene:
            except Exception:
                pass
        """},
    )
    fs = _new(_run(root, ("exception-hygiene", "stale-suppression")))
    assert [f.rule for f in fs] == ["exception-hygiene"]


# -- deadline-propagation: replica forward paths ----------------------------


def test_deadline_propagation_covers_replica_forwards(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"replica/router.py": """
        class Router:
            def route(self, g, body, deadline):
                return self._forward(g, "POST", "/q", body, {})
        """},
    )
    fs = _new(_run(root, ("deadline-propagation",)))
    assert len(fs) == 1 and "._forward(...)" in fs[0].message


def test_deadline_propagation_accepts_timeout_s_budget(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"replica/catchup.py": """
        class Catchup:
            def _replay_one(self, g, rec, timeout_s=None):
                return self.router._forward(
                    g, rec.method, rec.path, rec.body, {}, timeout_s=timeout_s
                )

            def drain(self, g, deadline):
                return self._replay_one(g, None, timeout_s=deadline.remaining_s())
        """},
    )
    assert _new(_run(root, ("deadline-propagation",))) == []


# -- rules 9+10: global-mutable-state + check-then-act ---------------------

_GIL_FIRING = {
    "server/handler.py": """
    def handle_query(req):
        return lookup(req)
    """,
    "mod.py": """
    _CACHE = {}
    _FROZEN = ("a", "b")

    def lookup(key):
        if key in _CACHE:
            return _CACHE[key]
        v = probe(key)
        _CACHE[key] = v
        return v

    def probe(key):
        return key
    """,
}


def test_global_mutable_state_fires_on_serving_reachable_mutation(tmp_path):
    root = _mkpkg(tmp_path, _GIL_FIRING)
    fs = _new(_run(root, ("global-mutable-state",)))
    assert len(fs) == 1
    f = fs[0]
    assert f.scope == "<module>" and f.path == "mod.py"
    assert "module-level mutable `_CACHE`" in f.message
    assert "lockcheck.named_global" in f.message


def test_global_mutable_state_passes_seam_frozen_and_unreachable(tmp_path):
    files = {
        "server/handler.py": """
        def handle_query(req):
            return lookup(req)
        """,
        "mod.py": """
        from pilosa_tpu.analysis import lockcheck

        _MEMO = lockcheck.named_global("mod.memo", max_entries=64)
        _TABLE = {"a": 1}      # read-only at runtime: frozen at import
        _OFFLINE = {}          # mutated only by an unreachable tool path

        def lookup(key):
            v = _MEMO.get(key)
            if v is None:
                v = _TABLE.get(key)
                _MEMO.put(key, v)
            return v

        def offline_rebuild():
            _OFFLINE["x"] = 1
        """,
    }
    root = _mkpkg(tmp_path, files)
    assert _new(_run(root, ("global-mutable-state",))) == []


def test_global_mutable_state_suppression_tags(tmp_path):
    files = dict(_GIL_FIRING)
    files["mod.py"] = """
    # analysis-ok: global-mutable-state: fixture reason — import-time only in production
    _CACHE = {}

    def lookup(key):
        if key in _CACHE:
            return _CACHE[key]
        _CACHE[key] = key
        return key
    """
    root = _mkpkg(tmp_path, files)
    fs = _run(root, ("global-mutable-state",))
    assert _new(fs) == [] and any(f.suppressed for f in fs)


def test_check_then_act_fires_all_four_shapes(tmp_path):
    files = {
        "server/handler.py": """
        def handle_query(req, h):
            return h.serve(req)
        """,
        "mod.py": """
        class Handler:
            def serve(self, req):
                self.total += 1
                self.stat_requests += 1
                if req in self.seen:
                    return self.seen[req]
                v = self.table.get(req)
                if v is None:
                    self.table[req] = object()
                self.pending.setdefault(req, [])
                return v
        """,
    }
    root = _mkpkg(tmp_path, files)
    msgs = [f.message for f in _new(_run(root, ("check-then-act",)))]
    assert any("read-modify-write of shared `self.total`" in m for m in msgs)
    assert any("membership test on `self.seen`" in m for m in msgs)
    assert any("`self.table.get(...)`" in m and "paired" in m for m in msgs)
    assert any("`self.pending.setdefault(...)`" in m for m in msgs)
    # The approximate-counter convention: stat_* increments are exempt.
    assert not any("stat_requests" in m for m in msgs)


def test_check_then_act_passes_locked_lifecycle_and_locals(tmp_path):
    files = {
        "server/handler.py": """
        def handle_query(req, h):
            return h.serve(req)
        """,
        "mod.py": """
        from pilosa_tpu.analysis import lockcheck

        class Handler:
            def __init__(self):
                self.table = {}          # lifecycle-exempt
                self._mu = lockcheck.named_lock("h._mu")

            def serve(self, req):
                local = {}
                if req in local:         # thread-private: no receiver
                    return local[req]
                return self._serve_locked(req)

            def _serve_locked(self, req):
                with self._mu:
                    if req in self.table:
                        return self.table[req]
                    self.table[req] = object()
                    return self.table[req]

        def never_served(h):
            h.counter += 1               # unreachable from the entries
        """,
    }
    root = _mkpkg(tmp_path, files)
    assert _new(_run(root, ("check-then-act",))) == []


def test_check_then_act_suppression_tag(tmp_path):
    files = {
        "server/handler.py": """
        def handle_query(req, h):
            return h.serve(req)
        """,
        "mod.py": """
        class Handler:
            def serve(self, req):
                # analysis-ok: check-then-act: fixture reason — externally synchronized
                self.total += 1
                return self.total
        """,
    }
    root = _mkpkg(tmp_path, files)
    fs = _run(root, ("check-then-act",))
    assert _new(fs) == [] and any(f.suppressed for f in fs)


# -- suppression + baseline round-trips ------------------------------------


def test_suppression_comment_round_trip(tmp_path):
    root = _mkpkg(
        tmp_path,
        {"mod.py": """
        def f():
            try:
                g()
            # analysis-ok: exception-hygiene: fixture reason
            except Exception:
                pass

        def g():
            try:
                h()
            # analysis-ok: exception-hygiene:
            except Exception:
                pass
        """},
    )
    fs = _run(root, ("exception-hygiene",))
    assert len(fs) == 2
    by_scope = {f.scope: f for f in fs}
    assert by_scope["f"].suppressed  # reason given
    assert not by_scope["g"].suppressed  # empty reason does not suppress
    assert [f.scope for f in _new(fs)] == ["g"]


def test_baseline_round_trip(tmp_path):
    files = {"mod.py": """
    def f():
        try:
            g()
        except Exception:
            pass
    """}
    root = _mkpkg(tmp_path, files)
    fs = _run(root, ("exception-hygiene",))
    assert len(_new(fs)) == 1
    engine.write_baseline(engine.baseline_path(root), fs)
    fs2 = _run(root, ("exception-hygiene",))
    assert len(fs2) == 1 and fs2[0].baselined
    assert _new(fs2) == []
    # a SECOND identical violation in the same scope is NEW (occurrence
    # index keeps fingerprints distinct)
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text(mod.read_text() + textwrap.dedent("""
    def f2():
        try:
            g()
        except Exception:
            pass
    """))
    fs3 = _run(root, ("exception-hygiene",))
    assert len(_new(fs3)) == 1


# -- CLI --------------------------------------------------------------------


def test_cli_exit_codes_and_write_flows(tmp_path, capsys):
    root = _mkpkg(tmp_path, _STATS_MOD)
    # registry missing -> nonzero
    assert analysis_main(["--root", root, "--rules", "stats-registry"]) == 1
    assert analysis_main(["--root", root, "--write-registry"]) == 0
    assert analysis_main(["--root", root, "--rules", "stats-registry"]) == 0
    assert analysis_main(["--root", root, "--rules", "nope"]) == 2
    out = capsys.readouterr().out
    assert "0 NEW" in out


# -- runtime lock checker ---------------------------------------------------


@pytest.fixture
def checker():
    """Explicitly-enabled checker, restored afterwards (this module is
    not in conftest's auto-enabled set)."""
    lockcheck.enable()
    lockcheck.reset()
    try:
        yield lockcheck.checker()
    finally:
        lockcheck.take_violations()
        lockcheck.disable()


def test_lockcheck_seeded_order_inversion(checker):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()
    vs = lockcheck.take_violations()
    assert len(vs) == 1 and vs[0].kind == "lock-order-cycle"
    assert "t.a" in vs[0].detail and "t.b" in vs[0].detail


def test_lockcheck_consistent_order_is_clean(checker):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockcheck.take_violations() == []


def test_lockcheck_rlock_reentry_no_self_edge(checker):
    r = lockcheck.named_rlock("t.r")
    with r:
        with r:
            pass
    assert lockcheck.take_violations() == []


def test_lockcheck_seeded_blocking_under_lock(checker, tmp_path):
    mu = lockcheck.named_lock("t.mu")
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            os.fsync(f.fileno())
        vs = lockcheck.take_violations()
        assert len(vs) == 1 and vs[0].kind == "blocking-under-lock"
        assert "fsync" in vs[0].detail and "t.mu" in vs[0].detail
    finally:
        f.close()


def test_lockcheck_blocking_without_lock_is_clean(checker, tmp_path):
    f = open(tmp_path / "x", "wb")
    try:
        os.fsync(f.fileno())
    finally:
        f.close()
    assert lockcheck.take_violations() == []


def test_lockcheck_scoped_allow(checker, tmp_path):
    mu = lockcheck.named_lock("t.mu")
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            with lockcheck.allowed("fsync"):
                os.fsync(f.fileno())
    finally:
        f.close()
    assert lockcheck.take_violations() == []


def test_lockcheck_allowlist_pair(checker, tmp_path):
    mu = lockcheck.named_lock("t.allowed_mu")
    checker.allow_pairs.add(("t.allowed_mu", "fsync"))
    f = open(tmp_path / "x", "wb")
    try:
        with mu:
            os.fsync(f.fileno())
    finally:
        f.close()
        checker.allow_pairs.discard(("t.allowed_mu", "fsync"))
    assert lockcheck.take_violations() == []


def test_lockcheck_condition_wait_releases_held_state(checker):
    cv = lockcheck.named_condition("t.cv")
    other = lockcheck.named_lock("t.other")
    woke = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
        # after the wait returned we re-held and released t.cv; taking
        # another lock now must not see t.cv as held
        with other:
            pass
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    # let the waiter block, then wake it
    import time

    time.sleep(0.1)
    with cv:
        cv.notify_all()
    t.join(timeout=5.0)
    assert woke.is_set()
    assert lockcheck.take_violations() == []


def test_lockcheck_disabled_factories_are_plain():
    assert not lockcheck.enabled()
    assert type(lockcheck.named_lock("x")) is type(threading.Lock())
    assert isinstance(lockcheck.named_rlock("x"), type(threading.RLock()))


# -- runtime lockset race detector (generation 2) ---------------------------


def _spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive()


def test_lockset_detects_two_thread_unguarded_mutation(checker):
    """The seeded race fixture: one thread writes under the declared
    lock, a second writes with no lock — empty intersection, violation
    with BOTH witness stacks."""

    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0

    mu = lockcheck.named_lock("t.mu")
    s = Shared()

    def locked_writer():
        with mu:
            s.val = 1

    _spawn(locked_writer)
    s.val = 2  # main thread, no lock held: the race
    vs = lockcheck.take_violations()
    assert len(vs) == 1 and vs[0].kind == "lockset-race"
    assert "Shared.val" in vs[0].detail and "t.mu" in vs[0].detail
    assert "first-witness" in vs[0].detail  # earliest recorded write stack
    assert vs[0].stack  # the emptying write's stack


def test_lockset_clean_when_every_write_holds_the_lock(checker):
    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0

    mu = lockcheck.named_lock("t.mu")
    s = Shared()

    def w():
        with mu:
            s.val += 1

    for _ in range(3):
        _spawn(w)
    with mu:
        s.val = 99
    assert lockcheck.take_violations() == []


def test_lockset_any_common_lock_suffices(checker):
    """Eraser semantics: the candidate set is the INTERSECTION of held
    locks — a consistent lock other than the declared one still means
    no race (the declaration names the intent, the model checks mutual
    exclusion)."""

    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0

    other = lockcheck.named_lock("t.other")
    s = Shared()

    def w():
        with other:
            s.val += 1

    _spawn(w)
    _spawn(w)
    assert lockcheck.take_violations() == []


def test_lockset_init_phase_single_thread_exempt(checker):
    """Unlocked writes BEFORE the object is shared are the normal
    construction pattern, never a violation; the lockset only starts
    refining at the first second-thread write."""

    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0

    mu = lockcheck.named_lock("t.mu")
    s = Shared()
    s.val = 1  # still exclusive: fine without the lock
    s.val = 2

    def w():
        with mu:
            s.val = 3

    _spawn(w)
    with mu:
        s.val = 4  # post-sharing writes hold the lock
    assert lockcheck.take_violations() == []


def test_lockset_post_sharing_unlocked_write_by_creator_is_caught(checker):
    """The inverse of the init exemption: once a second thread writes,
    the CREATOR loses its free pass too."""

    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0

    mu = lockcheck.named_lock("t.mu")
    s = Shared()

    def w():
        with mu:
            s.val = 1

    _spawn(w)
    _spawn(w)
    s.val = 2  # creator, no lock, object is shared now
    vs = lockcheck.take_violations()
    assert len(vs) == 1 and vs[0].kind == "lockset-race"
    # thread idents can be recycled between the two spawns, so only the
    # floor is stable: the creator plus at least one worker
    assert "threads observed" in vs[0].detail


def test_lockset_instance_level_guarded_registration(checker):
    class Plain:
        pass

    p = Plain()
    lockcheck.guarded(p, "x", lock="t.mu")
    p.x = 0

    def w():
        p.x = 1  # second thread, no lock

    _spawn(w)
    vs = lockcheck.take_violations()
    assert len(vs) == 1 and "Plain.x" in vs[0].detail
    # undeclared attributes on the same object stay untracked
    lockcheck.reset()
    p.y = 0
    _spawn(lambda: setattr(p, "y", 1))
    assert lockcheck.take_violations() == []


def test_lockset_undeclared_fields_untracked_and_disable_restores(checker):
    @lockcheck.guarded_class
    class Shared:
        _guarded_by_ = {"val": "t.mu"}

        def __init__(self):
            self.val = 0
            self.free = 0

    s = Shared()
    _spawn(lambda: setattr(s, "free", 1))
    s.free = 2
    assert lockcheck.take_violations() == []
    assert "__lockcheck_wrapped_setattr__" in Shared.__dict__
    lockcheck.disable()
    try:
        assert "__lockcheck_wrapped_setattr__" not in Shared.__dict__
        s.val = 5  # plain setattr again, nothing recorded
        assert lockcheck.take_violations() == []
    finally:
        lockcheck.enable()  # the fixture's finally expects enabled state


def test_lockset_real_tree_fragment_declares_guarded_state():
    """The declarations this PR ships: the hot shared structures carry
    _guarded_by_ maps naming their real locks (spot-check the contract
    the conftest-gated suites run under)."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.replica.router import GroupState, ReplicaRouter, ShardRuntime
    from pilosa_tpu.replica.wal import WriteAheadLog
    from pilosa_tpu.qcache import QueryCache
    from pilosa_tpu.ingest import StreamIngestor, WriteQueue
    from pilosa_tpu.executor import Executor

    assert Fragment._guarded_by_["storage"] == "core.fragment._mu"
    assert Fragment._guarded_by_["generation"] == "core.fragment._mu"
    assert GroupState._guarded_by_["applied_seq"] == "replica.router._mu"
    assert ShardRuntime._guarded_by_["write_seq"] == "replica.router._seq_mu"
    assert ReplicaRouter._guarded_by_["_fleet_cache"] == "replica.router._fleet_mu"
    assert WriteAheadLog._guarded_by_["_synced_off"] == "replica.wal._sync_cv"
    assert QueryCache._guarded_by_["_store"] == "qcache._mu"
    assert StreamIngestor._guarded_by_["_transfers"] == "ingest.stream._mu"
    assert WriteQueue._guarded_by_["_committing"] == "ingest._mu"
    assert Executor._guarded_by_["_serve_states"] == "executor._matrix_mu"


# -- the live-tree gate (CI smoke tier) ------------------------------------


def test_live_tree_analysis_gate():
    """`python -m pilosa_tpu.analysis` over the REAL package: every rule
    runs and no new findings exist.  This is the tier-1 CI gate — a new
    un-suppressed, un-baselined finding fails the suite with the same
    report the CLI prints."""
    findings = engine.run_analysis()
    fresh = engine.new_findings(findings)
    assert fresh == [], "new analysis findings:\n" + "\n".join(
        f.render() for f in fresh
    )


def test_live_tree_registry_is_current():
    """Committed COUNTERS.md must match regeneration exactly (the
    stats-registry drift half of the gate, asserted directly so the
    failure message carries the regenerate hint)."""
    root = engine.package_root()
    with open(registry.registry_path(root), encoding="utf-8") as f:
        committed = f.read()
    assert committed == registry.generate_counters_registry(root), (
        "counters registry is stale — run "
        "`python -m pilosa_tpu.analysis --write-registry` and commit"
    )
