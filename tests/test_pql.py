"""PQL parser tests (reference analog: pql/parser_test.go, ast_test.go)."""

import pytest

from pilosa_tpu.pql import Call, ParseError, Query, parse


def test_simple_call():
    q = parse("Bitmap(rowID=10, frame='stargazer')")
    assert len(q.calls) == 1
    c = q.calls[0]
    assert c.name == "Bitmap"
    assert c.args == {"rowID": 10, "frame": "stargazer"}
    assert c.children == []


def test_nested_calls():
    q = parse("Count(Intersect(Bitmap(rowID=10, frame=a), Bitmap(rowID=5, frame=b)))")
    count = q.calls[0]
    assert count.name == "Count"
    inter = count.children[0]
    assert inter.name == "Intersect"
    assert [c.name for c in inter.children] == ["Bitmap", "Bitmap"]
    assert inter.children[0].args == {"rowID": 10, "frame": "a"}


def test_children_then_args():
    q = parse("TopN(Bitmap(rowID=1, frame=other), frame=f, n=20)")
    c = q.calls[0]
    assert c.children[0].name == "Bitmap"
    assert c.args == {"frame": "f", "n": 20}


def test_multiple_calls_whitespace_separated():
    q = parse('SetBit(rowID=1, frame="f", columnID=2)\nCount(Bitmap(rowID=1, frame="f"))')
    assert [c.name for c in q.calls] == ["SetBit", "Count"]
    assert q.write_call_n() == 1


def test_value_types():
    q = parse('F(a=1, b=-2, c=3.5, d="str", e=bare, f=true, g=false, h=null, i=[1,2,"x",true])')
    args = q.calls[0].args
    assert args["a"] == 1 and args["b"] == -2
    assert args["c"] == 3.5
    assert args["d"] == "str"
    assert args["e"] == "bare"
    assert args["f"] is True and args["g"] is False
    assert args["h"] is None
    assert args["i"] == [1, 2, "x", True]


def test_ident_with_dots_dashes():
    q = parse("Range(rowID=1, frame=f, start=x, end=y)")
    assert q.calls[0].args["start"] == "x"
    q2 = parse('Bitmap(frame=my-frame.v2_x, rowID=1)')
    assert q2.calls[0].args["frame"] == "my-frame.v2_x"


def test_quoted_strings_with_escapes():
    q = parse('F(a="hello \\"world\\"", b=\'it\')')
    assert q.calls[0].args["a"] == 'hello "world"'
    assert q.calls[0].args["b"] == "it"


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("Bitmap(")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=)")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=1,rowID=2)")  # duplicate key
    with pytest.raises(ParseError):
        parse("123(rowID=1)")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=1) !")


def test_uint_arg_helpers():
    c = parse("F(n=5, ids=[1,2,3], s=x)").calls[0]
    assert c.uint_arg("n") == (5, True)
    assert c.uint_arg("missing") == (0, False)
    assert c.uint_slice_arg("ids") == ([1, 2, 3], True)
    with pytest.raises(TypeError):
        c.uint_arg("s")


def test_is_inverse():
    c = parse("Bitmap(columnID=5, frame=f)").calls[0]
    assert c.is_inverse("rowID", "columnID")
    c2 = parse("Bitmap(rowID=5, frame=f)").calls[0]
    assert not c2.is_inverse("rowID", "columnID")
    c3 = parse("Intersect(Bitmap(columnID=1, frame=f))").calls[0]
    assert not c3.is_inverse("rowID", "columnID")


def test_clone_and_str_roundtrip():
    q = parse('TopN(Bitmap(rowID=1, frame=o), frame="f", n=2, filters=["a",2])')
    c = q.calls[0]
    clone = c.clone()
    clone.args["n"] = 99
    assert c.args["n"] == 2
    # String form re-parses to the same structure.
    q2 = parse(str(c))
    assert q2.calls[0].name == "TopN"
    assert q2.calls[0].args["n"] == 2
    assert q2.calls[0].children[0].name == "Bitmap"
