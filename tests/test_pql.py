"""PQL parser tests (reference analog: pql/parser_test.go, ast_test.go)."""

import pytest

from pilosa_tpu.pql import Call, ParseError, Query, parse


def test_simple_call():
    q = parse("Bitmap(rowID=10, frame='stargazer')")
    assert len(q.calls) == 1
    c = q.calls[0]
    assert c.name == "Bitmap"
    assert c.args == {"rowID": 10, "frame": "stargazer"}
    assert c.children == []


def test_nested_calls():
    q = parse("Count(Intersect(Bitmap(rowID=10, frame=a), Bitmap(rowID=5, frame=b)))")
    count = q.calls[0]
    assert count.name == "Count"
    inter = count.children[0]
    assert inter.name == "Intersect"
    assert [c.name for c in inter.children] == ["Bitmap", "Bitmap"]
    assert inter.children[0].args == {"rowID": 10, "frame": "a"}


def test_children_then_args():
    q = parse("TopN(Bitmap(rowID=1, frame=other), frame=f, n=20)")
    c = q.calls[0]
    assert c.children[0].name == "Bitmap"
    assert c.args == {"frame": "f", "n": 20}


def test_multiple_calls_whitespace_separated():
    q = parse('SetBit(rowID=1, frame="f", columnID=2)\nCount(Bitmap(rowID=1, frame="f"))')
    assert [c.name for c in q.calls] == ["SetBit", "Count"]
    assert q.write_call_n() == 1


def test_value_types():
    q = parse('F(a=1, b=-2, c=3.5, d="str", e=bare, f=true, g=false, h=null, i=[1,2,"x",true])')
    args = q.calls[0].args
    assert args["a"] == 1 and args["b"] == -2
    assert args["c"] == 3.5
    assert args["d"] == "str"
    assert args["e"] == "bare"
    assert args["f"] is True and args["g"] is False
    assert args["h"] is None
    assert args["i"] == [1, 2, "x", True]


def test_ident_with_dots_dashes():
    q = parse("Range(rowID=1, frame=f, start=x, end=y)")
    assert q.calls[0].args["start"] == "x"
    q2 = parse('Bitmap(frame=my-frame.v2_x, rowID=1)')
    assert q2.calls[0].args["frame"] == "my-frame.v2_x"


def test_quoted_strings_with_escapes():
    q = parse('F(a="hello \\"world\\"", b=\'it\')')
    assert q.calls[0].args["a"] == 'hello "world"'
    assert q.calls[0].args["b"] == "it"


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("Bitmap(")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=)")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=1,rowID=2)")  # duplicate key
    with pytest.raises(ParseError):
        parse("123(rowID=1)")
    with pytest.raises(ParseError):
        parse("Bitmap(rowID=1) !")


def test_uint_arg_helpers():
    c = parse("F(n=5, ids=[1,2,3], s=x)").calls[0]
    assert c.uint_arg("n") == (5, True)
    assert c.uint_arg("missing") == (0, False)
    assert c.uint_slice_arg("ids") == ([1, 2, 3], True)
    with pytest.raises(TypeError):
        c.uint_arg("s")


def test_is_inverse():
    c = parse("Bitmap(columnID=5, frame=f)").calls[0]
    assert c.is_inverse("rowID", "columnID")
    c2 = parse("Bitmap(rowID=5, frame=f)").calls[0]
    assert not c2.is_inverse("rowID", "columnID")
    c3 = parse("Intersect(Bitmap(columnID=1, frame=f))").calls[0]
    assert not c3.is_inverse("rowID", "columnID")


def test_clone_and_str_roundtrip():
    q = parse('TopN(Bitmap(rowID=1, frame=o), frame="f", n=2, filters=["a",2])')
    c = q.calls[0]
    clone = c.clone()
    clone.args["n"] = 99
    assert c.args["n"] == 2
    # String form re-parses to the same structure.
    q2 = parse(str(c))
    assert q2.calls[0].name == "TopN"
    assert q2.calls[0].args["n"] == 2
    assert q2.calls[0].children[0].name == "Bitmap"


def _ast_eq(a, b):
    if isinstance(a, Query):
        return isinstance(b, Query) and len(a.calls) == len(b.calls) and all(
            _ast_eq(x, y) for x, y in zip(a.calls, b.calls)
        )
    return (
        a.name == b.name
        and a.args == b.args
        and all(type(a.args[k]) is type(b.args[k]) for k in a.args)
        and len(a.children) == len(b.children)
        and all(_ast_eq(x, y) for x, y in zip(a.children, b.children))
    )


@pytest.mark.parametrize(
    "src",
    [
        "Bitmap(rowID=10, frame='stargazer')",
        'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
        "SetBit(rowID=1, frame=f, columnID=5, timestamp='2017-01-02T03:04')",
        "TopN(Bitmap(rowID=1, frame=o), frame=\"f\", n=2)",
        "Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f), Bitmap(rowID=3, frame=f))",
        "F(a=true, b=false, c=null, d=some-ident.x, e=-42)",
        "A() B(x=1) C(D(), E(y='z'))",
        "Range(rowID=1, frame=f, start='2010-01-01T00:00', end='2011-01-01T00:00')",
        "  \n\t Bitmap( rowID = 7 , frame = f )  \n",
        "Xor(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f))",
    ],
)
def test_native_parser_matches_python(src):
    """The C++ fast path (pn_pql_parse) must produce the exact AST of the
    pure-Python parser — values, types, nesting, and call order."""
    from pilosa_tpu.pql import parser as pmod

    py = pmod._Parser(pmod.tokenize(src), src).parse_query()
    fast = pmod.parse(src)
    assert _ast_eq(py, fast)


@pytest.mark.parametrize(
    "src",
    [
        "TopN(frame=f, ids=[1,2,3])",          # list -> fallback
        "F(x=1.5)",                             # float -> fallback
        "F(s='a\\'b')",                         # escape -> fallback
        "F(n=123456789012345678901234567890)",  # >int64 -> fallback
    ],
)
def test_native_parser_falls_back(src):
    """Unsupported constructs still parse correctly via the Python path."""
    from pilosa_tpu.pql import parser as pmod

    py = pmod._Parser(pmod.tokenize(src), src).parse_query()
    assert _ast_eq(py, pmod.parse(src))


@pytest.mark.parametrize(
    "src",
    ["F(", "F)x", "F(x=1,,)", "F(x=1 y=2)", "F(x=)", "F(x=1)G", "9(x=1)", "F(x=1, x=2)"],
)
def test_native_parser_error_parity(src):
    """Malformed sources raise ParseError with the .so loaded (the native
    path must reject them and defer to the Python parser for the error)."""
    with pytest.raises(ParseError):
        parse(src)


def test_deeply_nested_query_does_not_crash():
    """A crafted deeply-nested body must never kill the process: the
    native parser caps its recursion depth and defers to the Python
    parser, which raises a survivable error."""
    src = "A(" * 100000 + ")" * 100000
    with pytest.raises((RecursionError, ParseError)):
        parse(src)
    # Deep-but-reasonable nesting still parses (through either path).
    src2 = "A(" * 90 + "B(x=1)" + ")" * 90
    c = parse(src2).calls[0]
    depth = 0
    while c.children:
        c = c.children[0]
        depth += 1
    assert depth == 90 and c.name == "B" and c.args == {"x": 1}


def test_parser_fuzz_native_python_parity():
    """Bounded structured fuzz: random sources must either produce the
    SAME AST from the native fast path and the pure-Python parser, or
    raise through the same error path (never crash, never diverge)."""
    import random

    from pilosa_tpu.pql import parser as pmod

    rng = random.Random(1234)
    names = ["Count", "Intersect", "Bitmap", "Union", "TopN", "F", "my-f.x"]
    keys = ["rowID", "frame", "n", "columnID", "x_y"]
    vals = ["1", "-5", "0", '"str"', "'s'", "true", "false", "null", "ident-v",
            "1.5", "[1,2]", "99999999999999999999"]

    def gen_call(depth):
        name = rng.choice(names)
        parts = []
        for _ in range(rng.randint(0, 2)):
            if depth < 2 and rng.random() < 0.4:
                parts.append(gen_call(depth + 1))
        args = ", ".join(
            f"{rng.choice(keys)}={rng.choice(vals)}" for _ in range(rng.randint(0, 3))
        )
        inner = ", ".join(p for p in parts if p)
        if inner and args:
            return f"{name}({inner}, {args})"
        return f"{name}({inner or args})"

    for _ in range(300):
        src = " ".join(gen_call(0) for _ in range(rng.randint(1, 4)))
        try:
            slow = pmod._Parser(pmod.tokenize(src), src).parse_query()
            slow_err = None
        except Exception as e:
            slow, slow_err = None, type(e)
        try:
            fast = pmod.parse(src)
            fast_err = None
        except Exception as e:
            fast, fast_err = None, type(e)
        if slow_err is not None:
            assert fast_err is slow_err, (src, slow_err, fast_err)
        else:
            assert fast_err is None and _ast_eq(slow, fast), src
