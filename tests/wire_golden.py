"""Golden wire-format fixtures generated with the OFFICIAL protobuf
toolchain (protoc 3.21.12 + google.protobuf 6.33.5, deterministic
serialization) from the reference schema (internal/public.proto +
internal/private.proto field numbers/types).  pilosa_tpu/wire.py must
decode these bytes exactly and, for messages it encodes, reproduce
them byte-for-byte — the cross-implementation check the hand-rolled
codec needs (reference encoder: gogo/protobuf, same proto3 rules).

Regeneration recipe (never shipped): write the schema to a scratch
dir, `protoc --python_out=.`, build each message with the corner
values in tests/test_wire_golden.py, SerializeToString(deterministic
=True).hex().
"""

GOLDEN = {
    "attr_bool_false_zero_omitted": bytes.fromhex("0a04666c61671003"),
    "attr_float": bytes.fromhex("0a0166100431000000000000f83f"),
    "attr_int_neg": bytes.fromhex("0a0178100220fdffffffffffffffff01"),
    "attr_string": bytes.fromhex("0a046e616d6510011a05616c696365"),
    "attrmap": bytes.fromhex("0a070a0161100220070a080a016210011a017a"),
    "bit": bytes.fromhex("08031080808080802018ffffffffffffffffff01"),
    "bitmap_empty": b"",
    "bitmap_packed": bytes.fromhex("0a0e0001ac0280808080808080808001"),
    "block_data_request": bytes.fromhex("0a0169120166180720032a087374616e64617264"),
    "block_data_response": bytes.fromhex("0a030001011203050009"),
    "cache": bytes.fromhex("0a0303000b"),
    "cache_empty": b"",
    "cluster_status": bytes.fromhex("0a070a0161120255500a090a01621204444f574e"),
    "column_attr_set": bytes.fromhex("084d12070a016e10022001"),
    "create_frame": bytes.fromhex("0a01691201661a0a0a01721a036c72752064"),
    "create_index": bytes.fromhex("0a016912060a0163120159"),
    "create_slice": bytes.fromhex("0a016910091801"),
    "create_slice_zero": bytes.fromhex("0a0169"),
    "delete_frame": bytes.fromhex("0a0169120166"),
    "delete_index": bytes.fromhex("0a0169"),
    "frame_meta": bytes.fromhex("0a05726f77494410011a0672616e6b656420d086032a03594d44"),
    "frame_meta_defaults": b"",
    "import_request": bytes.fromhex("0a0169120166180222030100022a03030400321000fbffffffffffffffff0180dea0cb05"),
    "import_response": bytes.fromhex("0a046e6f7065"),
    "import_response_empty": b"",
    "index_meta": bytes.fromhex("0a08636f6c756d6e49441204594d4448"),
    "index_msg": bytes.fromhex("0a02693112050a03636f6c180322140a026631120e0a01721a0672616e6b656420e8072a03000103"),
    "max_slices": bytes.fromhex("0a050a016110000a070a036964781004"),
    "node_status": bytes.fromhex("0a0868313a3130313031120255501a280a02693112050a03636f6c180322140a026631120e0a01721a0672616e6b656420e8072a030001031a040a026932"),
    "pair": bytes.fromhex("080a102a"),
    "pair_zero_count": bytes.fromhex("0809"),
    "pair_zero_key": bytes.fromhex("1005"),
    "query_request": bytes.fromhex("0a16436f756e74284269746d617028726f7749443d312929120300010518012203594d442801"),
    "query_request_minimal": bytes.fromhex("0a1e5365744269742869643d312c206672616d653d2266222c20636f6c3d3229"),
    "query_response": bytes.fromhex("12060a040a0202091202107b120a1a04080110021a021001120220011a0c080512080a016b10011a0176"),
    "query_response_err": bytes.fromhex("0a0f696e646578206e6f7420666f756e64"),
    "query_result_bitmap": bytes.fromhex("0a040a020209"),
    "query_result_changed": bytes.fromhex("2001"),
    "query_result_n": bytes.fromhex("107b"),
    "query_result_pairs": bytes.fromhex("1a04080110021a021001"),
}
