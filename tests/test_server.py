"""HTTP API + server tests (reference analogs: handler_test.go,
server/server_test.go — real in-process servers on ephemeral ports)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.config import ClusterConfig, Config
from pilosa_tpu.server.client import Client, ClientError
from pilosa_tpu.server.server import Server
from pilosa_tpu.pilosa import SLICE_WIDTH


def make_server(tmp_path, name="s0", **cfg_kwargs):
    cfg = Config(data_dir=str(tmp_path / name), host="127.0.0.1:0", engine="numpy", **cfg_kwargs)
    s = Server(cfg)
    s.open()
    return s


@pytest.fixture
def srv(tmp_path):
    s = make_server(tmp_path)
    yield s
    s.close()


@pytest.fixture
def client(srv):
    return Client(srv.host)


def test_version_hosts_status(client):
    assert client.version().startswith("0.")
    assert client.status()["state"] == "UP"
    assert len(client.hosts()) == 1


def test_index_frame_lifecycle(client):
    client.create_index("i", {"columnLabel": "col"})
    client.create_frame("i", "f", {"rowLabel": "row", "inverseEnabled": True})
    schema = client.schema()
    assert schema[0]["name"] == "i"
    assert schema[0]["frames"][0]["name"] == "f"
    with pytest.raises(ClientError) as e:
        client.create_index("i")
    assert e.value.status == 409
    client.delete_frame("i", "f")
    client.delete_index("i")
    assert client.schema() == []


def test_query_json_and_protobuf(srv, client):
    client.create_index("i")
    client.create_frame("i", "f")
    # protobuf query path
    resp = client.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=100)')
    assert resp["results"][0]["changed"] is True
    resp = client.execute_query("i", 'Bitmap(rowID=1, frame="f")')
    assert resp["results"][0]["bitmap"]["bits"] == [100]
    # JSON query path
    req = urllib.request.Request(
        f"http://{srv.host}/index/i/query",
        data=b'Count(Bitmap(rowID=1, frame="f"))',
        method="POST",
    )
    body = json.loads(urllib.request.urlopen(req).read())
    assert body == {"results": [1]}


def test_query_column_attrs(client):
    client.create_index("i")
    client.create_frame("i", "f")
    client.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=7)')
    client.execute_query("i", 'SetColumnAttrs(columnID=7, tag="x")')
    resp = client.execute_query("i", 'Bitmap(rowID=1, frame="f")', column_attrs=True)
    assert resp["columnAttrSets"] == [{"id": 7, "attrs": {"tag": "x"}}]


def test_query_errors(srv, client):
    client.create_index("i")
    with pytest.raises(ClientError):
        client.execute_query("i", "Bogus(")
    # GET on query endpoint → 405
    req = urllib.request.Request(f"http://{srv.host}/index/i/query", method="GET")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 405


def test_import_and_export(client):
    client.create_index("i")
    client.create_frame("i", "f")
    bits = [(1, 10), (1, SLICE_WIDTH + 3), (2, 20)]
    client.import_bits("i", "f", bits)
    resp = client.execute_query("i", 'Bitmap(rowID=1, frame="f")')
    assert resp["results"][0]["bitmap"]["bits"] == [10, SLICE_WIDTH + 3]
    csv0 = client.export_csv("i", "f", "standard", 0)
    assert "1,10" in csv0 and "2,20" in csv0
    csv1 = client.export_csv("i", "f", "standard", 1)
    assert f"1,{SLICE_WIDTH + 3}" in csv1


def test_slices_max_and_views(client):
    client.create_index("i")
    client.create_frame("i", "f", {"timeQuantum": "YM"})
    client.execute_query(
        "i", f'SetBit(rowID=1, frame="f", columnID={2 * SLICE_WIDTH}, timestamp="2017-05-01T00:00")'
    )
    assert client.max_slices() == {"i": 2}
    views = client.frame_views("i", "f")
    assert "standard" in views and "standard_2017" in views


def test_fragment_data_roundtrip_and_blocks(client):
    client.create_index("i")
    client.create_frame("i", "f")
    client.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=3)')
    client.execute_query("i", 'SetBit(rowID=150, frame="f", columnID=9)')
    blocks = client.fragment_blocks("i", "f", "standard", 0)
    assert [b for b, _ in blocks] == [0, 1]
    rows, cols = client.block_data("i", "f", "standard", 0, 1)
    assert rows.tolist() == [150] and cols.tolist() == [9]
    data = client.fragment_data("i", "f", "standard", 0)
    assert data[:4] == (12346).to_bytes(4, "little")
    # restore into a fresh frame
    client.create_frame("i", "g")
    client.restore_fragment("i", "g", "standard", 0, data)
    resp = client.execute_query("i", 'Bitmap(rowID=150, frame="g")')
    assert resp["results"][0]["bitmap"]["bits"] == [9]


def test_attr_diff_endpoints(client):
    client.create_index("i")
    client.create_frame("i", "f")
    client.execute_query("i", 'SetRowAttrs(rowID=5, frame="f", name="x")')
    client.execute_query("i", 'SetColumnAttrs(columnID=2, tag="y")')
    # empty local blocks → server returns everything it has
    assert client.row_attr_diff("i", "f", []) == {5: {"name": "x"}}
    assert client.column_attr_diff("i", []) == {2: {"tag": "y"}}


def test_persistence_across_restart(tmp_path):
    s = make_server(tmp_path, "p")
    c = Client(s.host)
    c.create_index("i")
    c.create_frame("i", "f")
    c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=42)')
    s.close()
    s2 = make_server(tmp_path, "p")
    c2 = Client(s2.host)
    resp = c2.execute_query("i", 'Bitmap(rowID=1, frame="f")')
    assert resp["results"][0]["bitmap"]["bits"] == [42]
    s2.close()


def test_two_node_cluster_distributed_query(tmp_path):
    """Two real servers; fan-out + reduce across both (executor_test.go
    TestExecutor_Execute_Remote_* analog with real processes)."""
    # Start both on fixed free ports so the shared host list is consistent.
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    p0, p1 = free_port(), free_port()
    hosts = [f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"]
    servers = []
    for i, p in enumerate((p0, p1)):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            host=hosts[i],
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts)),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        c0, c1 = Client(hosts[0]), Client(hosts[1])
        # schema must exist on both nodes (static cluster: no broadcast)
        for c in (c0, c1):
            c.create_index("i")
            c.create_frame("i", "f")
        # import routes each slice to its owner; set bits across 4 slices
        bits = [(1, s * SLICE_WIDTH + 7) for s in range(4)]
        cluster = servers[0].cluster
        c0.import_bits("i", "f", bits, fragment_nodes=cluster.fragment_nodes)
        # force both nodes to know the global max slice
        servers[0]._monitor_max_slices()
        servers[1]._monitor_max_slices()
        resp = c0.execute_query("i", 'Count(Bitmap(rowID=1, frame="f"))')
        assert resp["results"][0]["n"] == 4
        resp = c1.execute_query("i", 'Bitmap(rowID=1, frame="f")')
        assert resp["results"][0]["bitmap"]["bits"] == [s * SLICE_WIDTH + 7 for s in range(4)]
        # distributed write: send SetBit to the non-owner; it must forward
        owner = cluster.fragment_nodes("i", 0)[0].host
        non_owner = hosts[1] if owner == hosts[0] else hosts[0]
        resp = Client(non_owner).execute_query("i", 'SetBit(rowID=9, frame="f", columnID=1)')
        assert resp["results"][0]["changed"] is True
        resp = Client(owner).execute_query("i", 'Count(Bitmap(rowID=9, frame="f"))')
        assert resp["results"][0]["n"] == 1
    finally:
        for s in servers:
            s.close()


def test_two_node_cluster_qcache_invalidation(tmp_path):
    """qcache in a multi-node HTTP cluster: a write to a REMOTELY-owned
    slice must be visible through the coordinator's very next read.
    Cluster writes apply only on slice-owner nodes, so the coordinator's
    local generation vector can never see them — coordinator-scope
    results are therefore never cached (counted ineligible); only each
    node's remote sub-requests are, and those invalidate locally."""
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    p0, p1 = free_port(), free_port()
    hosts = [f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"]
    servers = []
    for i, p in enumerate((p0, p1)):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            host=hosts[i],
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts)),
            # Admit every eligible result: any unsafely-keyed entry
            # WOULD be stored and served, so staleness can't hide
            # behind cost-based admission.
            qcache_min_cost_ms=0.0,
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        c0, c1 = Client(hosts[0]), Client(hosts[1])
        for c in (c0, c1):
            c.create_index("i")
            c.create_frame("i", "f")
        bits = [(1, s * SLICE_WIDTH + 7) for s in range(4)]
        cluster = servers[0].cluster
        c0.import_bits("i", "f", bits, fragment_nodes=cluster.fragment_nodes)
        servers[0]._monitor_max_slices()
        servers[1]._monitor_max_slices()

        q = 'Count(Bitmap(rowID=1, frame="f"))'
        assert c0.execute_query("i", q)["results"][0]["n"] == 4
        assert c0.execute_query("i", q)["results"][0]["n"] == 4
        # The coordinator never cached its global answers.
        assert servers[0].qcache.stores == 0
        assert servers[0].qcache.ineligible >= 2

        # Write a NEW bit into a slice node 0 does NOT own: the
        # coordinator only forwards it, so no local generation moves —
        # exactly the write a coordinator-scope cache entry would miss.
        remote_slice = next(
            s for s in range(4)
            if all(n.host != hosts[0] for n in cluster.fragment_nodes("i", s))
        )
        col = remote_slice * SLICE_WIDTH + 99
        r = c0.execute_query("i", f'SetBit(rowID=1, frame="f", columnID={col})')
        assert r["results"][0]["changed"] is True
        # Read-your-writes THROUGH the coordinator, immediately.
        assert c0.execute_query("i", q)["results"][0]["n"] == 5
        # And through the other node too (it owns the written slice).
        assert c1.execute_query("i", q)["results"][0]["n"] == 5

        # Per-node remote sub-requests DID use the cache: the repeated
        # coordinator reads hit on the peer's remote-scope entries.
        assert (servers[0].qcache.hits + servers[1].qcache.hits) > 0
    finally:
        for s in servers:
            s.close()


def test_debug_traces_and_slow_query_log(tmp_path):
    """Tracing end to end through a real server: sampled requests land
    in /debug/traces (newest-first, min-ms filterable) with executor
    stage spans, requests past [trace] slow-ms emit one structured
    slow-query log line, and the X-Pilosa-Trace force override samples
    even at rate 0."""
    import logging

    s = make_server(
        tmp_path, name="tr0",
        trace_sample_rate=1.0, trace_slow_ms=0.0001, qcache_min_cost_ms=0.0,
    )
    records = []
    h = logging.Handler()
    h.emit = lambda rec: records.append(rec.getMessage())
    logging.getLogger("pilosa_tpu.slowquery").addHandler(h)
    try:
        c = Client(s.host)
        c.create_index("i")
        c.create_frame("i", "f")
        c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=3)')
        q = 'Count(Bitmap(rowID=1, frame="f"))'
        c.execute_query("i", q)  # miss
        c.execute_query("i", q)  # hit

        with urllib.request.urlopen(f"http://{s.host}/debug/traces", timeout=30) as r:
            traces = json.loads(r.read())["traces"]
        assert traces, "sampled requests never reached the ring"
        # Newest-first: the LAST query (the cache hit) leads.
        query_traces = [t for t in traces if t["name"].endswith("/index/i/query")]
        assert len(query_traces) >= 3
        hit = query_traces[0]
        assert hit["ms"] > 0 and hit["spans"]["tags"]["status"] == 200
        assert hit["spans"]["tags"]["qcache"] == "hit"
        names = [c_["name"] for c_ in hit["spans"]["children"]]
        assert "qos.admit" in names and "qcache.lookup" in names
        # The miss before it carried the execution stages.
        miss = query_traces[1]
        assert miss["spans"]["tags"]["qcache"] == "miss"
        # min-ms filter: an impossible floor returns nothing.
        with urllib.request.urlopen(
            f"http://{s.host}/debug/traces?min-ms=1e9", timeout=30
        ) as r:
            assert json.loads(r.read())["traces"] == []

        # Slow-query log: slow-ms is microscopic, so every request
        # logged — structured JSON with fingerprint + stage breakdown.
        assert records, "no slow-query log lines emitted"
        recs = [json.loads(r.split("slow-query ", 1)[1]) for r in records]
        qrecs = [r for r in recs if r["name"].endswith("/index/i/query")]
        assert qrecs, recs
        rec = qrecs[-1]
        assert rec["ms"] > 0 and rec["fp"] and rec["trace_id"]
        assert "Count(" in rec["snippet"]
        # The miss's breakdown attributed the execution: the compiled
        # serve lane (lane=flat) times its single native crossing as a
        # "device" stage; the general lane emits fused/per-call spans.
        miss_rec = next(r for r in qrecs if r["tags"].get("qcache") == "miss")
        if miss_rec["tags"].get("lane") == "flat":
            assert "device" in miss_rec["stages"]
        else:
            assert "call.Count" in miss_rec["stages"] or "fused" in miss_rec["stages"]

        # Force override: a zero-rate tracer still samples on demand.
        s.tracer.sample_rate = 0.0
        before = len(s.tracer.traces_json(limit=1000))
        req = urllib.request.Request(
            f"http://{s.host}/index/i/query", data=q.encode(), method="POST"
        )
        urllib.request.urlopen(req, timeout=30).read()  # unsampled
        req.add_header("X-Pilosa-Trace", "1")
        urllib.request.urlopen(req, timeout=30).read()  # forced
        after = s.tracer.traces_json(limit=1000)
        # The unsampled request appears only if slow (root-only); the
        # forced one definitely appears with forced=True.
        assert any(t["forced"] for t in after[: len(after) - before])
        # /debug/vars carries the tracer counters.
        snap = json.loads(
            urllib.request.urlopen(f"http://{s.host}/debug/vars", timeout=30).read()
        )
        assert snap.get("trace.sampled", 0) >= 3
        assert snap.get("trace.slow", 0) >= 1
    finally:
        logging.getLogger("pilosa_tpu.slowquery").removeHandler(h)
        s.close()


def test_two_node_cluster_trace_remote_subspans(tmp_path):
    """Cross-node propagation: a force-traced coordinator query fans out
    to the peer with the trace id in X-Pilosa-Trace; the peer's span
    tree comes back in X-Pilosa-Trace-Spans and lands grafted under the
    coordinator's remote span — ONE trace shows both sides of the hop."""
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    hosts = [f"127.0.0.1:{free_port()}" for _ in range(2)]
    servers = []
    for i, h in enumerate(hosts):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            host=h,
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts)),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        c0 = Client(hosts[0])
        for c in (c0, Client(hosts[1])):
            c.create_index("i")
            c.create_frame("i", "f")
        bits = [(1, s * SLICE_WIDTH + 7) for s in range(4)]
        cluster = servers[0].cluster
        c0.import_bits("i", "f", bits, fragment_nodes=cluster.fragment_nodes)
        servers[0]._monitor_max_slices()
        servers[1]._monitor_max_slices()

        req = urllib.request.Request(
            f"http://{hosts[0]}/index/i/query",
            data=b'Count(Bitmap(rowID=1, frame="f"))',
            method="POST",
        )
        req.add_header("X-Pilosa-Trace", "1")
        resp = urllib.request.urlopen(req, timeout=60)
        assert json.loads(resp.read())["results"] == [4]
        # The coordinator returned its own span tree too (propagation).
        assert resp.headers.get("X-Pilosa-Trace-Spans")

        traces = servers[0].tracer.traces_json(limit=10)
        tr = next(t for t in traces if t["name"].endswith("/index/i/query"))

        def walk(span, out):
            out.append(span)
            for ch in span.get("children", []):
                walk(ch, out)
            return out

        spans = walk(tr["spans"], [])
        remotes = [sp for sp in spans if sp["name"] == "remote"]
        assert remotes, f"no remote hop span in {tr}"
        assert remotes[0]["tags"]["host"] == hosts[1]
        # The peer's own root span (its handler door) was grafted under
        # the hop — with the same trace id having forced it.
        peer_roots = [
            sp for sp in spans if sp["name"].startswith("POST /index/i/query")
            and sp is not tr["spans"]
        ]
        assert peer_roots, f"peer sub-spans missing from {tr}"
        # And the peer recorded the hop under the SAME trace id.
        peer_traces = servers[1].tracer.traces_json(limit=10)
        assert any(t["id"] == tr["id"] for t in peer_traces)
    finally:
        for s in servers:
            s.close()


def test_webui_served_to_browsers(srv):
    """`/` serves the console to Accept: text/html clients and the plain
    banner to API clients; /assets/* serves the bundle (handler.go:132-145)."""
    def get(path, accept=None):
        req = urllib.request.Request(f"http://{srv.host}{path}")
        if accept:
            req.add_header("Accept", accept)
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, r.headers.get("Content-Type", ""), r.read()

    st, ct, body = get("/", accept="text/html,application/xhtml+xml")
    assert st == 200 and ct.startswith("text/html")
    assert b"pilosa-tpu console" in body

    st, ct, body = get("/")
    assert st == 200 and ct.startswith("text/plain")

    st, ct, body = get("/assets/main.js")
    assert st == 200 and ct == "application/javascript" and b"runQuery" in body
    st, ct, body = get("/assets/style.css")
    assert st == 200 and ct == "text/css"

    with pytest.raises(urllib.error.HTTPError) as e:
        get("/assets/nope.js")
    assert e.value.code == 404
    # path traversal is rejected, not served
    with pytest.raises(urllib.error.HTTPError) as e:
        get("/assets/..%2Findex.html")
    assert e.value.code == 404


def test_profile_endpoints(client):
    """JAX trace start/stop round trip (aux tracing subsystem)."""
    status, body = client._request("POST", "/debug/profile/start")
    if status == 500:
        pytest.skip("jax profiler unavailable in this environment")
    assert status == 200 and b"tracing" in body
    # double start conflicts
    status2, _ = client._request("POST", "/debug/profile/start")
    assert status2 == 409
    status3, body3 = client._request("POST", "/debug/profile/stop")
    assert status3 == 200 and b"written" in body3
    status4, _ = client._request("POST", "/debug/profile/stop")
    assert status4 == 409


def test_set_quick_property(tmp_path):
    """Full-stack property test (server_test.go:42-121 TestMain_Set_Quick):
    random SetBits over HTTP, Bitmap() must match a model dict, and state
    must survive a restart."""
    rng = np.random.default_rng(1234)
    s = make_server(tmp_path)
    try:
        c = Client(s.host)
        c.create_index("q")
        c.create_frame("q", "f")
        model: dict[int, set[int]] = {}
        for _ in range(120):
            row = int(rng.integers(0, 5))
            col = int(rng.integers(0, 3 * SLICE_WIDTH))
            resp = c.execute_query("q", f'SetBit(rowID={row}, frame="f", columnID={col})')
            changed = resp["results"][0]["changed"]
            assert changed == (col not in model.setdefault(row, set()))
            model[row].add(col)
        for row, cols in model.items():
            resp = c.execute_query("q", f'Bitmap(rowID={row}, frame="f")')
            assert resp["results"][0]["bitmap"]["bits"] == sorted(cols)
    finally:
        s.close()
    # restart on the same data dir; all bits must come back
    s2 = make_server(tmp_path)
    try:
        c2 = Client(s2.host)
        for row, cols in model.items():
            resp = c2.execute_query("q", f'Bitmap(rowID={row}, frame="f")')
            assert resp["results"][0]["bitmap"]["bits"] == sorted(cols)
    finally:
        s2.close()


def test_stats_wired_through_data_path(tmp_path):
    """Counters flow holder->index->frame->view->fragment with tags and
    surface at /debug/vars (stats.go + holder.go:113/252, fragment.go:410)."""
    s = make_server(tmp_path, name="stats0")
    try:
        c = Client(s.host)
        c.create_index("st")
        c.create_frame("st", "f")
        c.execute_query("st", 'SetBit(rowID=1, frame="f", columnID=5) '
                              'SetBit(rowID=1, frame="f", columnID=6)')
        c.execute_query("st", 'ClearBit(rowID=1, frame="f", columnID=6)')
        with urllib.request.urlopen(f"http://{s.host}/debug/vars") as resp:
            vars_ = json.loads(resp.read())
        flat = json.dumps(vars_)
        assert "indexN" in flat
        assert "setN" in flat and "clearN" in flat
        assert "index:st" in flat and "frame:f" in flat  # tag propagation
    finally:
        s.close()


def test_two_node_fused_batch_query(tmp_path):
    """A batch of Count(pair-op) calls against a 2-node cluster runs
    through the distributed fused path (one forwarded batch per node) and
    matches per-call execution."""
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    hosts = [f"127.0.0.1:{free_port()}" for _ in range(2)]
    servers = []
    for i, h in enumerate(hosts):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            host=h,
            engine="numpy",
            cluster=ClusterConfig(type="static", hosts=list(hosts)),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        c0 = Client(hosts[0])
        for c in (c0, Client(hosts[1])):
            c.create_index("i")
            c.create_frame("i", "f")
        cluster = servers[0].cluster
        rng = np.random.default_rng(9)
        bits = []
        for r in range(4):
            for s_i in range(4):
                for c_i in rng.choice(1000, size=40, replace=False):
                    bits.append((r, s_i * SLICE_WIDTH + int(c_i)))
        c0.import_bits("i", "f", bits, fragment_nodes=cluster.fragment_nodes)
        servers[0]._monitor_max_slices()
        servers[1]._monitor_max_slices()

        combos = [("Intersect", 0, 1), ("Union", 1, 2), ("Difference", 2, 3), ("Xor", 0, 3)]
        batch = " ".join(
            f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for op, a, b in combos
        )
        fused = c0.execute_query("i", batch)["results"]
        singles = [
            c0.execute_query(
                "i", f'Count({op}(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            )["results"][0]
            for op, a, b in combos
        ]
        assert fused == singles
        # Both nodes agree (the batch coordinated from node 1 too).
        assert Client(hosts[1]).execute_query("i", batch)["results"] == fused
    finally:
        for s in servers:
            s.close()


def test_status_merge_skips_bad_items(tmp_path):
    """A peer-advertised frame with invalid options (e.g. persisted by an
    older node) must not abort the rest of the status merge."""
    s = make_server(tmp_path, name="m0")
    try:
        indexes = [
            {"name": "a", "meta": {}, "maxSlice": 3,
             "frames": [{"name": "bad", "meta": {"cacheType": "bogus"}},
                        {"name": "good", "meta": {}}]},
            {"name": "b", "meta": {}, "maxSlice": 1, "frames": []},
        ]
        from pilosa_tpu import wire

        s.handle_remote_status(wire.encode_node_status(s.host, "UP", indexes))
        # bad frame skipped; everything after it still merged.
        assert s.holder.index("a") is not None
        assert s.holder.index("a").frame("bad") is None
        assert s.holder.index("a").frame("good") is not None
        assert s.holder.index("a").max_slice() == 3
        assert s.holder.index("b") is not None
    finally:
        s.close()


def test_status_merge_survives_malformed_items(tmp_path):
    """Structurally-malformed peer items (missing keys, wrong types — a
    different-version peer) are skipped per item, not merge-aborting."""
    s = make_server(tmp_path, name="mm0")
    try:
        indexes = [
            {"name": "a", "meta": {}, "maxSlice": 0,
             "frames": [{"meta": {}},                      # no "name"
                        {"name": "ok", "meta": {}}]},
            {"name": "b", "meta": {}, "maxSlice": 2, "frames": []},
        ]
        from pilosa_tpu import wire

        s.handle_remote_status(wire.encode_node_status(s.host, "UP", indexes))
        assert s.holder.index("a") is not None
        assert s.holder.index("a").frame("ok") is not None
        assert s.holder.index("b") is not None
        assert s.holder.index("b").max_slice() == 2
    finally:
        s.close()


def test_http_surface_survives_garbage(srv, client):
    """Random paths/methods/bodies must yield clean HTTP errors, never
    kill the server or leak tracebacks as responses."""
    import random
    import urllib.error

    rng = random.Random(5)
    client.create_index("z")
    client.create_frame("z", "f")
    paths = [
        "/", "/index", "/index/", "/index/%ff", "/index/z/query", "/index/z/frame/f",
        "/schema", "/status", "/fragment/data?index=z&frame=f&view=standard&slice=0",
        "/fragment/data?index=z&frame=f&view=standard&slice=notanumber",
        "/fragment/nodes?index=z", "/fragment/nodes", "/export", "/nope/deep/path",
        "/index/z/query?slices=a,b", "/debug/vars", "/index/z/time-quantum",
    ]
    bodies = [b"", b"\x00\x01\x02" * 40, b"{", b'{"options": 5}', b"Count(", b"A" * 5000,
              bytes(rng.randrange(256) for _ in range(64))]
    for _ in range(120):
        path = rng.choice(paths)
        method = rng.choice(["GET", "POST", "DELETE", "PATCH", "PUT"])
        body = rng.choice(bodies) if method in ("POST", "PATCH", "PUT") else None
        req = urllib.request.Request(f"http://{srv.host}{path}", data=body, method=method)
        if rng.random() < 0.3:
            req.add_header("Content-Type", "application/x-protobuf")
            req.add_header("Accept", "application/x-protobuf")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            assert 400 <= e.code < 600
            e.read()
        except urllib.error.URLError as e:  # pragma: no cover
            raise AssertionError(f"server died on {method} {path}: {e}")
    # Server is still fully functional afterwards.
    assert client.status()["state"] == "UP"
    resp = client.execute_query("z", 'SetBit(rowID=1, frame="f", columnID=1)')
    assert resp["results"][0]["changed"] is True


def test_json_and_protobuf_codecs_agree(srv, client):
    """The same query answered over JSON and protobuf negotiation must
    carry identical data (handler.go content-negotiation parity)."""
    client.create_index("cp")
    client.create_frame("cp", "f", {"cacheType": "ranked"})
    bits = [(r, c) for r in range(3) for c in range(r, 40 + r)]
    client.import_bits("cp", "f", bits)
    client.execute_query("cp", 'SetRowAttrs(rowID=1, frame="f", name="x", n=3)')
    queries = [
        'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))',
        'Bitmap(rowID=1, frame="f")',
        'TopN(frame="f", n=2)',
        'Union(Bitmap(rowID=0, frame="f"), Bitmap(rowID=2, frame="f"))',
    ]
    for q in queries:
        pb = client.execute_query("cp", q)  # protobuf path
        req = urllib.request.Request(
            f"http://{srv.host}/index/cp/query", data=q.encode(), method="POST"
        )
        js = json.loads(urllib.request.urlopen(req).read())  # JSON path

        def norm(results):
            out = []
            for r in results:
                if isinstance(r, dict) and "bitmap" in r:
                    out.append(("bm", tuple(r["bitmap"]["bits"]),
                                tuple(sorted(r["bitmap"].get("attrs", {}).items()))))
                elif isinstance(r, dict) and "pairs" in r:
                    out.append(("pairs", tuple((p["id"], p["count"]) for p in r["pairs"])))
                elif isinstance(r, dict) and "n" in r:
                    out.append(("n", r["n"]))
                elif isinstance(r, dict) and "attrs" in r and "bits" in r:
                    out.append(("bm", tuple(r["bits"]), tuple(sorted(r["attrs"].items()))))
                elif isinstance(r, list):
                    out.append(("pairs", tuple((p["id"], p["count"]) for p in r)))
                elif isinstance(r, int):
                    out.append(("n", r))  # JSON carries counts as numbers
                else:
                    out.append(("v", r))
            return out

        assert norm(pb["results"]) == norm(js["results"]), q


def test_crash_durability_sigkill(tmp_path):
    """Acknowledged single-bit writes survive a SIGKILL: each SetBit's
    WAL record reaches the kernel (unbuffered append) before the HTTP
    response, so a crashed server replays them on reopen
    (roaring.go:590-611 + fragment.go WAL semantics)."""
    import os
    import signal
    import socket
    import subprocess
    import sys

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data_dir = str(tmp_path / "crash")
    port = free_port()
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PILOSA_TPU_ENGINE"] = "numpy"
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "--data-dir", data_dir, "--host", f"127.0.0.1:{port}"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        cwd=repo,
        env=env,
    )
    try:
        deadline = time.monotonic() + 60
        c = Client(f"127.0.0.1:{port}")
        while True:
            try:
                c.create_index("i")
                break
            except OSError:
                assert time.monotonic() < deadline, "server never came up"
                time.sleep(0.2)
        c.create_frame("i", "f")
        # Individual SetBits: each is one durable WAL append (no snapshot
        # for most of them), including time-view and inverse fan-out.
        rng = np.random.default_rng(3)
        cols = sorted(set(rng.integers(0, 2 * SLICE_WIDTH, size=120).tolist()))
        for col in cols:
            resp = c.execute_query("i", f'SetBit(rowID=5, frame="f", columnID={col})')
            assert resp["results"] in ([True], [{"changed": True}])
        # Hard kill: no close(), no flush hooks, no snapshot.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # Reopen the same data dir in-process: WAL replay must restore every
    # acknowledged bit.
    s2 = Server(Config(data_dir=data_dir, host="127.0.0.1:0", engine="numpy"))
    s2.open()
    try:
        c2 = Client(s2.host)
        got = c2.execute_query("i", 'Bitmap(rowID=5, frame="f")')
        assert got["results"][0]["bitmap"]["bits"] == cols
    finally:
        s2.close()


def test_pprof_proto_endpoints(srv):
    """/debug/pprof serves REAL pprof payloads (gzipped profile.proto,
    handler.go:99 net/http/pprof semantics): goroutine-analog thread
    profile, sampling CPU profile, text form at ?debug=1.  Structure
    validated by decoding the protobuf with the wire codec (the encoder
    was additionally cross-checked against a protoc-compiled official
    parser when authored)."""
    import gzip
    import threading
    import time as time_mod

    from pilosa_tpu import wire

    stop = threading.Event()

    def busy():  # a sampleable workload thread
        while not stop.wait(0.001):
            sum(range(200))

    t = threading.Thread(target=busy, name="busy-worker", daemon=True)
    t.start()
    try:
        def get(path):
            with urllib.request.urlopen(f"http://{srv.host}{path}", timeout=30) as r:
                return r.status, r.read()

        def parse_profile(body):
            raw = gzip.decompress(body)  # gzip magic implied
            strings, sample_types, samples, locs, fns = [], [], [], {}, {}
            for f, w, v in wire.iter_fields(raw):
                if f == 6:
                    strings.append(v.decode())
                elif f == 1:
                    d = dict((f2, v2) for f2, _, v2 in wire.iter_fields(v))
                    sample_types.append((d.get(1, 0), d.get(2, 0)))
                elif f == 2:
                    d = {}
                    for f2, _, v2 in wire.iter_fields(v):
                        d[f2] = wire.decode_packed_uint64(v2)
                    samples.append(d)
                elif f == 4:
                    d = dict((f2, v2) for f2, _, v2 in wire.iter_fields(v))
                    locs[d[1]] = d
                elif f == 5:
                    d = dict((f2, v2) for f2, _, v2 in wire.iter_fields(v))
                    fns[d[1]] = d
            return strings, sample_types, samples, locs, fns

        st, body = get("/debug/pprof/goroutine")
        assert st == 200 and body[:2] == b"\x1f\x8b"
        strings, stypes, samples, locs, fns = parse_profile(body)
        assert strings[0] == ""
        assert [(strings[a], strings[b]) for a, b in stypes] == [("threads", "count")]
        assert samples and all(s[2] == [1] for s in samples)
        # every referenced location resolves to a named function
        for s in samples:
            for lid in s[1]:
                line = dict(
                    (f2, v2) for f2, _, v2 in wire.iter_fields(locs[lid][4])
                )
                assert strings[fns[line[1]][2]]
        # one sample's root frame is the busy worker thread
        roots = set()
        for s in samples:
            lid = s[1][-1]
            line = dict((f2, v2) for f2, _, v2 in wire.iter_fields(locs[lid][4]))
            roots.add(strings[fns[line[1]][2]])
        assert any("busy-worker" in r for r in roots), roots

        st, body = get("/debug/pprof/profile?seconds=0.4")
        assert st == 200 and body[:2] == b"\x1f\x8b"
        strings, stypes, samples, _, _ = parse_profile(body)
        assert [(strings[a], strings[b]) for a, b in stypes] == [
            ("samples", "count"), ("cpu", "nanoseconds")
        ]
        assert samples, "CPU sampler collected nothing with a busy thread live"

        st, body = get("/debug/pprof/goroutine?debug=1")
        assert st == 200 and b"--- thread" in body
    finally:
        stop.set()
        t.join(timeout=5)


def test_serve_lane_through_http_server(tmp_path):
    """The single-call native serve lane must engage through the REAL
    threaded HTTP server: after the Gram warms, concurrent clients'
    batched Count requests are answered by pn_serve_pairs (executor
    serve state armed) with results identical to a cold numpy oracle."""
    import os
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu import native
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.server import Server

    # qcache OFF: this test proves the layer BELOW it (the native serve
    # lane) engages; with the query result cache on, byte-identical
    # repeats are answered above the executor and never arm the lane.
    cfg = Config(
        data_dir=str(tmp_path / "d"), host="127.0.0.1:0", engine="jax",
        qcache_enabled=False,
    )
    s = Server(cfg)
    s.open()
    try:
        base = f"http://{s.host}"

        def post(path, data):
            req = urllib.request.Request(
                base + path, data=data.encode(), method="POST"
            )
            return json.loads(urllib.request.urlopen(req, timeout=60).read())

        post("/index/i", "{}")
        post("/index/i/frame/f", "{}")
        rng = np.random.default_rng(4)
        s.holder.frame("i", "f").import_bits(
            rng.integers(0, 24, 800), rng.integers(0, 2 * (1 << 20), 800)
        )
        batch = " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in rng.integers(0, 24, size=(32, 2))
        )
        first = post("/index/i/query", batch)["results"]
        post("/index/i/query", batch)  # second request arms the Gram/state
        assert s.executor._serve_states, "serve lane did not arm over HTTP"
        # Count actual native serve calls: the concurrent requests must
        # ride pn_serve_pairs, not silently fall to the general lane.
        calls = {"n": 0}
        orig = native.serve_pairs

        def counting(*a, **kw):
            r = orig(*a, **kw)
            if r is not None:
                calls["n"] += 1
            return r

        native.serve_pairs = counting
        try:
            with ThreadPoolExecutor(6) as pool:
                outs = list(
                    pool.map(
                        lambda _: post("/index/i/query", batch)["results"], range(12)
                    )
                )
        finally:
            native.serve_pairs = orig
        assert calls["n"] == 12, f"only {calls['n']}/12 requests served natively"
        oracle = Executor(s.holder, engine="numpy")
        os.environ["PILOSA_TPU_NO_FASTLANE"] = "1"
        try:
            want = oracle.execute("i", batch)
        finally:
            del os.environ["PILOSA_TPU_NO_FASTLANE"]
        assert first == want
        assert all(o == want for o in outs)
    finally:
        s.close()
