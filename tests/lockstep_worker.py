"""Worker for the lockstep-service test: joins a 2-process gloo job and
runs pilosa_tpu.parallel.service.LockstepService.

Run: python tests/lockstep_worker.py <coordinator> <nprocs> <pid> <control_port> <http_port>

Rank 0 prints ``{"ready": ..., "http": ...}`` once serving, shuts down
when a line arrives on stdin, then both ranks print a final JSON line
with a host-side probe of their (replicated) holder state so the test
can assert write convergence.
"""

import json
import sys
import threading


def main() -> int:
    coordinator, nprocs, pid, control_port, http_port = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        int(sys.argv[4]),
        int(sys.argv[5]),
    )

    from pilosa_tpu.parallel.multihost import init_multihost

    init_multihost(coordinator, nprocs, pid, local_device_count=2)

    import tempfile

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.parallel.service import LockstepService
    from pilosa_tpu.pilosa import SLICE_WIDTH

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("g")
        idx.create_frame("f", FrameOptions(time_quantum="YM"))
        fr = idx.frame("f")
        # Identical seed data on every rank (replicated-holder model).
        # Slice count scales with the job so the global mesh (2 local
        # devices x nprocs ranks) keeps a divisible slice axis.
        n_slices = max(4, 2 * nprocs)
        for r in range(4):
            for s in range(n_slices):
                fr.set_bit("standard", r, s * SLICE_WIDTH + 10 + r)
                fr.set_bit("standard", r, s * SLICE_WIDTH + 500)

        svc = LockstepService(
            h,
            control_addr=("127.0.0.1", control_port),
            http_addr=("127.0.0.1", http_port) if pid == 0 else None,
        )
        if pid == 0:
            t = threading.Thread(target=svc.serve_forever, daemon=True)
            t.start()
            # Wait until the HTTP server is bound before announcing.
            import time

            deadline = time.monotonic() + 60
            while svc._httpd is None and time.monotonic() < deadline:
                time.sleep(0.05)
            print(json.dumps({"ready": True}), flush=True)
            sys.stdin.readline()  # parent signals shutdown
            svc.shutdown()
            t.join(timeout=30)
        else:
            svc.serve_forever()

        # Post-run probe through the plain numpy path: writes served over
        # HTTP must have replicated to every rank's holder.
        e = Executor(h, engine="numpy")
        (probe,) = e.execute("g", 'Count(Bitmap(rowID=0, frame="f"))')
        (rprobe,) = e.execute(
            "g",
            'Count(Range(rowID=0, frame="f", start="2017-01-01T00:00", end="2018-01-01T00:00"))',
        )

        # Collective ReplicaMesh probe over the GLOBAL job mesh: with 4
        # ranks x 2 local devices this is the (4, 2) slice x replica
        # layout (cluster.go:220-240's ReplicaN, TPU-first) — the batch
        # splits over the replica axis, each group psums over its slice
        # shards, and the counts must equal every rank's LOCAL numpy
        # ground truth (i.e. the replicated holders really converged).
        replica_probe = -1
        import jax

        n_dev = jax.device_count()
        if n_dev >= 4 and n_dev % 2 == 0 and n_slices % (n_dev // 2) == 0:
            import numpy as np

            from pilosa_tpu.parallel import ReplicaMesh, replica_gather_count

            frags = [
                h.fragment("g", "f", "standard", s) for s in range(n_slices)
            ]
            mat = np.stack(
                [
                    np.stack([f.row_dense(r) for r in range(4)])
                    for f in frags
                ]
            )
            rmesh = ReplicaMesh(n_replicas=2)
            pairs = np.array(
                [[a, b] for a in range(4) for b in range(2)], dtype=np.int32
            )
            out = replica_gather_count(
                rmesh, "and", rmesh.shard_stack(mat), jax.numpy.asarray(pairs),
                interpret=jax.default_backend() != "tpu",
            )
            if not getattr(out, "is_fully_addressable", True):
                from jax.experimental import multihost_utils

                got = np.asarray(multihost_utils.process_allgather(out, tiled=True))
            else:
                got = np.asarray(out)
            from pilosa_tpu.ops.bitwise import np_popcount

            want = [
                int(np_popcount(mat[:, a] & mat[:, b]).sum()) for a, b in pairs
            ]
            assert got.tolist() == want, f"replica probe mismatch: {got} != {want}"
            replica_probe = int(got.sum())
        h.close()

    print(
        json.dumps(
            {
                "pid": pid,
                "probe": int(probe),
                "range_probe": int(rprobe),
                "replica_probe": replica_probe,
                # Coalescing telemetry (rank 0 only counts ships):
                # control-plane batch entries vs requests carried.
                "batches": svc.stat_batches,
                "requests": svc.stat_requests,
                # QoS telemetry: arrival-queue sheds (rank 0) and
                # expired requests dropped at replay (every rank).
                "shed": svc.stat_shed,
                "expired": svc.stat_expired,
                # Query-result-cache telemetry (PILOSA_TPU_QCACHE=1):
                # hit/miss decisions must be IDENTICAL on every rank —
                # they are pure functions of replicated state (the
                # lockstep service forces min-cost-ms to 0).
                "qcache_hits": getattr(svc.executor.qcache, "hits", -1),
                "qcache_misses": getattr(svc.executor.qcache, "misses", -1),
                "qcache_stores": getattr(svc.executor.qcache, "stores", -1),
                # Tracing telemetry (PILOSA_TPU_TRACE_SAMPLE_RATE): the
                # sampling decision is made on rank 0 at ship time and
                # rides the batch entry — every rank counts the SAME
                # wire flags, so stat_traced must agree across ranks.
                "traced": svc.stat_traced,
                # Per-tenant wire accounting: the tenant is resolved
                # once on rank 0 at ship time and rides the batch entry
                # — every rank must tally IDENTICAL per-tenant counts.
                "tenants": svc.stat_tenants,
                # Rank 0 records ship/execute phases into its ring.
                "trace_ring": (
                    len(svc.tracer.traces_json(limit=10000))
                    if svc.tracer is not None
                    else 0
                ),
                "trace_phases": sorted(
                    {
                        c["name"]
                        for t in (
                            svc.tracer.traces_json(limit=10000)
                            if svc.tracer is not None
                            else []
                        )
                        for c in t["spans"].get("children", [])
                    }
                ),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
