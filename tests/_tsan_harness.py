"""True-concurrency driver for the native TSAN leg (not pytest-collected).

Run in a SUBPROCESS by tests/test_native_threaded.py with
``PILOSA_TPU_NATIVE_LIB`` pointing at the ``-fsanitize=thread`` build
and libtsan LD_PRELOADed.  Drives the GIL-released native kernels from
genuinely concurrent threads:

- the armed-table write lane (``pn_write_batch``) against a hand-built
  container table (sorted keys + slack buffers + in-place ns[]),
- the one-call serving lane (``pn_serve_pairs``) against a per-thread
  Gram table,
- streaming-ingest decode (varint / oplog / CSV) round trips,
- roaring kernels (popcount, fnv1a64, in-place array insert) and the
  flat PQL parser.

Two modes prove both sides of the threading contract:

``--mode clean``   — per-fragment threads: every thread owns ALL of its
                     buffers/tables (the documented contract: a fragment
                     and its armed table belong to one writer at a time,
                     enforced by fragment._mu in the real stack).  TSAN
                     must stay silent.
``--mode shared``  — the same write-lane driver with sharing
                     deliberately enabled: two threads hammer ONE armed
                     table through a barrier so the GIL-released
                     ``pn_write_batch`` calls overlap inside the .so.
                     The concurrent ns[] read-modify-writes and slack
                     buffer memmoves are a REAL data race; TSAN must
                     report it (the leg's seeded known-race fixture).

Deliberately imports only numpy + the ctypes bridge — no jax, no
server stack — so the TSAN shadow state covers a small, fully
understood process.
"""

import argparse
import os
import sys
import threading

import numpy as np

# Runs as a bare script (python tests/_tsan_harness.py): the package
# root is the repo checkout, not the scripts directory.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pilosa_tpu import native
from pilosa_tpu.pilosa import SLICE_WIDTH

W = SLICE_WIDTH
NCONT = 4  # containers per table: rows 0..3, cols < 65536 (slice 0)


def make_table(bufcap: int = 1 << 13) -> dict:
    """A minimal armed container table (the fragment._writelane_state
    shape): sorted u64 keys, slack-buffer addresses, in-place element
    counts, capacities.  Each container is seeded with one value."""
    keys = np.array([r * (W >> 16) for r in range(NCONT)], dtype=np.uint64)
    bufs = [np.zeros(bufcap, dtype=np.uint32) for _ in range(NCONT)]
    for b in bufs:
        b[0] = 1
    addrs = np.array([b.ctypes.data for b in bufs], dtype=np.uint64)
    ns = np.ones(NCONT, dtype=np.int64)
    caps = np.array([len(b) for b in bufs], dtype=np.int64)
    return {
        "keys": keys, "bufs": bufs, "addrs": addrs, "ns": ns, "caps": caps,
        "ptrs": (keys.ctypes.data, addrs.ctypes.data,
                 ns.ctypes.data, caps.ctypes.data),
    }


def drive_write_lane(table: dict, rounds: int, stride: int, base: int,
                     barrier=None) -> None:
    """Repeated canonical SetBit bodies through native.write_batch.
    ``base``/``stride`` pick per-caller column sets (disjoint per thread
    in clean mode; interleaved in shared mode so inserts memmove past
    each other)."""
    kp, ap, np_, cp = table["ptrs"]
    for rnd in range(rounds):
        lo = base + rnd * stride * 24
        src = "".join(
            f'SetBit(rowID={r}, frame="f", columnID={c})'
            for r in range(NCONT)
            for c in range(lo, lo + stride * 24, stride)
        ).encode()
        if barrier is not None:
            barrier.wait()
        res = native.write_batch(
            src, b"f", b"rowID", b"columnID", 0, W,
            kp, ap, np_, cp, NCONT, -1, 1 << 30,
        )
        assert res is not None, "write lane fell back"
        types, rows, cols, _changed = res
        assert len(types) == NCONT * 24


def drive_serve(seed: int, rounds: int) -> None:
    """pn_serve_pairs against a per-thread Gram table, result checked
    against the count identity every round."""
    rng = np.random.default_rng(seed)
    R = 8
    bits = rng.integers(0, 2, size=(R, 64))
    gram = np.ascontiguousarray((bits @ bits.T).astype(np.int64))
    rows_sorted = np.arange(2, 2 + R, dtype=np.int64)
    pos = np.arange(R, dtype=np.int32)
    raw = (
        b'Count(Intersect(Bitmap(rowID=2, frame="f"), '
        b'Bitmap(rowID=5, frame="f")))'
        b'Count(Union(Bitmap(rowID=3, frame="f"), '
        b'Bitmap(rowID=4, frame="f")))'
    )
    g = gram
    want = [int(g[0, 3]), int(g[1, 1] + g[2, 2] - g[1, 2])]
    for _ in range(rounds):
        counts = native.serve_pairs(
            raw, b"f", True, b"rowID", rows_sorted, pos, gram
        )
        assert counts is not None and counts.tolist() == want


def drive_ingest(seed: int, rounds: int) -> None:
    """Varint / oplog / CSV decode round trips on per-thread data."""
    rng = np.random.default_rng(seed)
    values = rng.integers(0, 1 << 40, size=512, dtype=np.uint64)
    types = rng.integers(0, 2, size=256, dtype=np.uint8).astype(np.uint8)
    ops = rng.integers(0, 1 << 30, size=256, dtype=np.uint64)
    csv = b"".join(
        b"%d,%d\n" % (int(rng.integers(0, 50)), int(rng.integers(0, 1 << 20)))
        for _ in range(200)
    )
    for _ in range(rounds):
        got = native.varint_decode(native.varint_encode(values))
        assert np.array_equal(got, values)
        t2, v2 = native.oplog_decode(native.oplog_encode(types, ops))
        assert np.array_equal(v2, ops)
        parsed = native.parse_csv(csv)
        assert parsed is None or len(parsed[0]) == 200


def drive_kernels(seed: int, rounds: int) -> None:
    """Roaring kernels + flat PQL parse on per-thread buffers."""
    lib = native.load()
    rng = np.random.default_rng(seed)
    words = rng.integers(0, 1 << 32, size=2048, dtype=np.uint64).astype(np.uint32)
    blob = bytes(rng.integers(0, 256, size=4096, dtype=np.uint8))
    pql = b'TopN(frame="f", n=12)Count(Bitmap(rowID=7, frame="f"))'
    buf = np.zeros(1 << 12, dtype=np.uint32)
    addr = buf.ctypes.data
    for rnd in range(rounds):
        native.popcount_words(words)
        native.fnv1a64(blob)
        assert native.pql_parse_flat(pql) is not None
        n = 0
        for v in range(rnd * 64, rnd * 64 + 48):
            newn = lib.pn_array_insert_u32(addr, n, v)
            if newn > 0:
                n = newn


def run_clean(threads: int, rounds: int) -> None:
    """Per-fragment threads: zero sharing — the documented contract."""
    errors: list = []

    def worker(k: int) -> None:
        try:
            table = make_table()
            drive_write_lane(table, rounds, stride=1, base=2)
            drive_serve(seed=100 + k, rounds=rounds * 4)
            drive_ingest(seed=200 + k, rounds=rounds)
            drive_kernels(seed=300 + k, rounds=rounds)
        except Exception as e:  # surfaced after join; threads can't fail pytest
            errors.append((k, repr(e)))

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errors:
        raise SystemExit(f"worker errors: {errors}")
    print("tsan-harness-ok")


def run_shared(rounds: int) -> None:
    """The seeded known-race fixture: TWO threads, ONE armed table, a
    barrier per round so the GIL-released pn_write_batch calls overlap
    inside the .so.  Interleaved column sets (base k, stride 2) force
    each insert to memmove past the other thread's values."""
    table = make_table(bufcap=1 << 15)
    barrier = threading.Barrier(2)
    errors: list = []

    def worker(k: int) -> None:
        try:
            drive_write_lane(table, rounds, stride=2, base=2 + k,
                             barrier=barrier)
        except Exception as e:
            errors.append((k, repr(e)))

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # A torn table can legitimately make a worker trip an assert; the
    # fixture's contract is only that TSAN REPORTS the race.
    print(f"tsan-harness-shared-done errors={len(errors)}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("clean", "shared"), default="clean")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=12)
    args = ap.parse_args()
    if not native.available():
        print("native-unavailable", file=sys.stderr)
        raise SystemExit(3)
    if args.mode == "clean":
        run_clean(args.threads, args.rounds)
    else:
        run_shared(args.rounds)


if __name__ == "__main__":
    main()
