"""Concurrency stress tests — the -race analog (SURVEY §5: the reference
relies on go test -race + mutex-per-object; here threaded stress over the
same object graph must never corrupt state or raise).
"""

import os
import threading

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.pilosa import SLICE_WIDTH


def test_concurrent_writers_readers_snapshots(tmp_path):
    """4 writer threads + 2 reader threads + a snapshotter against one
    frame: no exceptions, and the final bitmap equals the model."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    e = Executor(h, engine="numpy")

    n_per_thread = 300
    rngs = [np.random.default_rng(seed) for seed in range(4)]
    written: list[set[tuple[int, int]]] = [set() for _ in range(4)]
    errors: list[BaseException] = []
    stop = threading.Event()

    def writer(k):
        try:
            rng = rngs[k]
            for _ in range(n_per_thread):
                r = int(rng.integers(0, 8))
                c = int(rng.integers(0, 2 * SLICE_WIDTH))
                fr.set_bit("standard", r, c)
                written[k].add((r, c))
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def reader():
        try:
            while not stop.is_set():
                e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
                e.execute(
                    "i",
                    'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
                    ' Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))',
                )
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def snapshotter():
        try:
            while not stop.is_set():
                for frag in list(fr.view("standard").fragments.values()):
                    frag.snapshot()
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    aux = [threading.Thread(target=reader) for _ in range(2)] + [
        threading.Thread(target=snapshotter)
    ]
    for t in threads + aux:
        t.start()
    for t in threads:
        t.join(timeout=120)
    stop.set()
    for t in aux:
        t.join(timeout=30)

    assert not errors, errors
    model: dict[int, set[int]] = {}
    for s in written:
        for r, c in s:
            model.setdefault(r, set()).add(c)
    for r, cols in model.items():
        (bm,) = e.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))')
        assert bm == len(cols), f"row {r}: {bm} != {len(cols)}"
    # Durability: state survives close + reopen (WAL/snapshot interplay
    # under concurrent snapshots must not lose acked writes).
    h.close()
    h2 = Holder(str(tmp_path / "data"))
    h2.open()
    e2 = Executor(h2, engine="numpy")
    for r, cols in model.items():
        (n,) = e2.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))')
        assert n == len(cols), f"after reopen, row {r}: {n} != {len(cols)}"
    h2.close()


def test_concurrent_schema_and_writes(tmp_path):
    """Schema mutations racing writes on other frames must not interfere."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("stable", FrameOptions())
    fr = idx.frame("stable")
    errors: list[BaseException] = []

    def churn():
        try:
            for k in range(30):
                name = f"tmp{k % 3}"
                try:
                    idx.create_frame(name, FrameOptions())
                except Exception:
                    pass
                try:
                    idx.delete_frame(name)
                except Exception:
                    pass
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def write():
        try:
            for c in range(500):
                fr.set_bit("standard", 0, c)
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    ts = [threading.Thread(target=churn) for _ in range(2)] + [
        threading.Thread(target=write) for _ in range(2)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors
    assert fr.view("standard").fragment(0).row_count(0) == 500
    h.close()


@pytest.mark.skipif(
    not os.environ.get("PILOSA_TPU_SOAK"),
    reason="heavy soak; run with PILOSA_TPU_SOAK=1",
)
def test_soak_two_engines_with_snapshots(tmp_path):
    """8 writers (16k mixed direct/PQL/time-quantum writes), numpy AND
    jax readers, a snapshot+flush loop — then exact per-row counts and
    durability across reopen."""
    from pilosa_tpu.executor import Executor

    h = Holder(str(tmp_path / "soak"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame(
        "f", FrameOptions(inverse_enabled=True, time_quantum="YM", cache_type="ranked")
    )
    fr = idx.frame("f")
    e = Executor(h, engine="numpy")
    e2 = Executor(h, engine="jax")
    errors: list = []
    stop = threading.Event()
    written: list[set] = [set() for _ in range(8)]

    def writer(k):
        try:
            rng = np.random.default_rng(k)
            for j in range(2000):
                r = int(rng.integers(0, 16))
                c = int(rng.integers(0, 3 * SLICE_WIDTH))
                if j % 37 == 0:
                    e.execute(
                        "i",
                        f'SetBit(rowID={r}, frame="f", columnID={c}, '
                        f'timestamp="2017-0{1 + (j % 9)}-01T00:00")',
                    )
                else:
                    fr.set_bit("standard", r, c)
                written[k].add((r, c))
        except BaseException as x:  # pragma: no cover
            errors.append(("w", k, x))

    def reader(eng):
        try:
            while not stop.is_set():
                eng.execute(
                    "i",
                    'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
                    ' Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))'
                    # 3-operand tree: the multi-fold lane shares the matrix.
                    ' Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
                )
                # Fused Range batch: multi-view matrix + cover memo under
                # concurrent timestamped writes (generation invalidation).
                eng.execute(
                    "i",
                    'Count(Range(rowID=0, frame="f", start="2017-01-01T00:00", end="2018-01-01T00:00"))'
                    ' Count(Range(rowID=1, frame="f", start="2017-03-01T00:00", end="2017-06-01T00:00"))',
                )
                eng.execute("i", 'TopN(frame="f", n=3)')
                # TopN(src): the engine-backed candidate scorer against the
                # shared row matrix while writers mutate it.
                eng.execute("i", 'TopN(Bitmap(rowID=4, frame="f"), frame="f", n=3)')
                eng.execute("i", 'Bitmap(columnID=5, frame="f")')
        except BaseException as x:  # pragma: no cover
            errors.append(("r", x))

    def flusher():
        try:
            while not stop.is_set():
                h.flush_caches()
                for frag in list(fr.view("standard").fragments.values()):
                    frag.snapshot()
        except BaseException as x:  # pragma: no cover
            errors.append(("s", x))

    ws = [threading.Thread(target=writer, args=(k,)) for k in range(8)]
    aux = [threading.Thread(target=reader, args=(eng,)) for eng in (e, e2)] + [
        threading.Thread(target=flusher)
    ]
    for t in ws + aux:
        t.start()
    for t in ws:
        t.join(timeout=300)
    stop.set()
    for t in aux:
        t.join(timeout=60)
    assert not errors, errors[:3]
    model: dict[int, set] = {}
    for s in written:
        for r, c in s:
            model.setdefault(r, set()).add(c)
    for r, cols in model.items():
        assert e.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))') == [len(cols)]
    h.close()
    h2 = Holder(str(tmp_path / "soak"))
    h2.open()
    e3 = Executor(h2, engine="numpy")
    for r, cols in model.items():
        assert e3.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))') == [len(cols)]
    h2.close()
