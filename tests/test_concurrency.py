"""Concurrency stress tests — the -race analog (SURVEY §5: the reference
relies on go test -race + mutex-per-object; here threaded stress over the
same object graph must never corrupt state or raise).
"""

import os
import threading

import numpy as np
import pytest

from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.pilosa import SLICE_WIDTH


def test_concurrent_writers_readers_snapshots(tmp_path):
    """4 writer threads + 2 reader threads + a snapshotter against one
    frame: no exceptions, and the final bitmap equals the model."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    e = Executor(h, engine="numpy")

    n_per_thread = 300
    rngs = [np.random.default_rng(seed) for seed in range(4)]
    written: list[set[tuple[int, int]]] = [set() for _ in range(4)]
    errors: list[BaseException] = []
    stop = threading.Event()

    def writer(k):
        try:
            rng = rngs[k]
            for _ in range(n_per_thread):
                r = int(rng.integers(0, 8))
                c = int(rng.integers(0, 2 * SLICE_WIDTH))
                fr.set_bit("standard", r, c)
                written[k].add((r, c))
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def reader():
        try:
            while not stop.is_set():
                e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
                e.execute(
                    "i",
                    'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
                    ' Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))',
                )
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def snapshotter():
        try:
            while not stop.is_set():
                for frag in list(fr.view("standard").fragments.values()):
                    frag.snapshot()
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    aux = [threading.Thread(target=reader) for _ in range(2)] + [
        threading.Thread(target=snapshotter)
    ]
    for t in threads + aux:
        t.start()
    for t in threads:
        t.join(timeout=120)
    stop.set()
    for t in aux:
        t.join(timeout=30)

    assert not errors, errors
    model: dict[int, set[int]] = {}
    for s in written:
        for r, c in s:
            model.setdefault(r, set()).add(c)
    for r, cols in model.items():
        (bm,) = e.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))')
        assert bm == len(cols), f"row {r}: {bm} != {len(cols)}"
    # Durability: state survives close + reopen (WAL/snapshot interplay
    # under concurrent snapshots must not lose acked writes).
    h.close()
    h2 = Holder(str(tmp_path / "data"))
    h2.open()
    e2 = Executor(h2, engine="numpy")
    for r, cols in model.items():
        (n,) = e2.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))')
        assert n == len(cols), f"after reopen, row {r}: {n} != {len(cols)}"
    h2.close()


def test_concurrent_schema_and_writes(tmp_path):
    """Schema mutations racing writes on other frames must not interfere."""
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("stable", FrameOptions())
    fr = idx.frame("stable")
    errors: list[BaseException] = []

    def churn():
        try:
            for k in range(30):
                name = f"tmp{k % 3}"
                try:
                    idx.create_frame(name, FrameOptions())
                except Exception:
                    pass
                try:
                    idx.delete_frame(name)
                except Exception:
                    pass
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    def write():
        try:
            for c in range(500):
                fr.set_bit("standard", 0, c)
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    ts = [threading.Thread(target=churn) for _ in range(2)] + [
        threading.Thread(target=write) for _ in range(2)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors
    assert fr.view("standard").fragment(0).row_count(0) == 500
    h.close()


@pytest.mark.skipif(
    not os.environ.get("PILOSA_TPU_SOAK"),
    reason="heavy soak; run with PILOSA_TPU_SOAK=1",
)
def test_soak_two_engines_with_snapshots(tmp_path):
    """8 writers (16k mixed direct/PQL/time-quantum writes), numpy AND
    jax readers, a snapshot+flush loop — then exact per-row counts and
    durability across reopen."""
    from pilosa_tpu.executor import Executor

    h = Holder(str(tmp_path / "soak"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame(
        "f", FrameOptions(inverse_enabled=True, time_quantum="YM", cache_type="ranked")
    )
    fr = idx.frame("f")
    e = Executor(h, engine="numpy")
    e2 = Executor(h, engine="jax")
    errors: list = []
    stop = threading.Event()
    written: list[set] = [set() for _ in range(8)]

    def writer(k):
        try:
            rng = np.random.default_rng(k)
            for j in range(2000):
                r = int(rng.integers(0, 16))
                c = int(rng.integers(0, 3 * SLICE_WIDTH))
                if j % 37 == 0:
                    e.execute(
                        "i",
                        f'SetBit(rowID={r}, frame="f", columnID={c}, '
                        f'timestamp="2017-0{1 + (j % 9)}-01T00:00")',
                    )
                else:
                    fr.set_bit("standard", r, c)
                written[k].add((r, c))
        except BaseException as x:  # pragma: no cover
            errors.append(("w", k, x))

    def reader(eng):
        try:
            while not stop.is_set():
                eng.execute(
                    "i",
                    'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
                    ' Count(Union(Bitmap(rowID=2, frame="f"), Bitmap(rowID=3, frame="f")))'
                    # 3-operand tree: the multi-fold lane shares the matrix.
                    ' Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))',
                )
                # Fused Range batch: multi-view matrix + cover memo under
                # concurrent timestamped writes (generation invalidation).
                eng.execute(
                    "i",
                    'Count(Range(rowID=0, frame="f", start="2017-01-01T00:00", end="2018-01-01T00:00"))'
                    ' Count(Range(rowID=1, frame="f", start="2017-03-01T00:00", end="2017-06-01T00:00"))',
                )
                eng.execute("i", 'TopN(frame="f", n=3)')
                # TopN(src): the engine-backed candidate scorer against the
                # shared row matrix while writers mutate it.
                eng.execute("i", 'TopN(Bitmap(rowID=4, frame="f"), frame="f", n=3)')
                eng.execute("i", 'Bitmap(columnID=5, frame="f")')
        except BaseException as x:  # pragma: no cover
            errors.append(("r", x))

    def flusher():
        try:
            while not stop.is_set():
                h.flush_caches()
                view = fr.view("standard")  # None until the first write
                if view is None:
                    continue
                for frag in list(view.fragments.values()):
                    frag.snapshot()
        except BaseException as x:  # pragma: no cover
            errors.append(("s", x))

    ws = [threading.Thread(target=writer, args=(k,)) for k in range(8)]
    aux = [threading.Thread(target=reader, args=(eng,)) for eng in (e, e2)] + [
        threading.Thread(target=flusher)
    ]
    for t in ws + aux:
        t.start()
    for t in ws:
        t.join(timeout=300)
    stop.set()
    for t in aux:
        t.join(timeout=60)
    assert not errors, errors[:3]
    model: dict[int, set] = {}
    for s in written:
        for r, c in s:
            model.setdefault(r, set()).add(c)
    for r, cols in model.items():
        assert e.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))') == [len(cols)]
    h.close()
    h2 = Holder(str(tmp_path / "soak"))
    h2.open()
    e3 = Executor(h2, engine="numpy")
    for r, cols in model.items():
        assert e3.execute("i", f'Count(Bitmap(rowID={r}, frame="f"))') == [len(cols)]
    h2.close()


@pytest.mark.parametrize("write_queue", [False, True])
def test_gram_at_scale_reads_stable_under_write_churn(tmp_path, write_queue):
    """Round-4 Gram-at-scale lane under concurrent invalidation: reader
    threads issue fused pair-count batches over rows a writer thread
    NEVER touches, while the writer churns other rows of the same frame
    (every write kills the pool's cache box, forcing Gram rebuilds and
    lane re-decisions mid-stream).  The readers' counts must stay
    exactly constant throughout — a stale Gram, a torn box, or a lane
    race would surface as a changed count.  Runs both executor
    configurations: bare, and the server's serve-queue coalescing
    (merged cross-client batches racing the same invalidation)."""
    rng = np.random.default_rng(3)
    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("c").create_frame("f", FrameOptions())
    fr = h.index("c").frame("f")
    n_read_rows, n_churn_rows = 48, 8
    rows = np.repeat(np.arange(n_read_rows, dtype=np.uint64), 12)
    for s in range(2):
        cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(np.uint64) + np.uint64(
            s * SLICE_WIDTH
        )
        fr.import_bits(rows, cols)

    ex = Executor(h, engine="jax", write_queue=write_queue)
    if not getattr(ex.engine, "wants_static_shapes", False):
        pytest.skip("jax engine unavailable")

    def build_q(seed):
        perm = np.random.default_rng(seed).permutation(n_read_rows)
        return " ".join(
            f'Count(Intersect(Bitmap(rowID={int(perm[2 * i])}, frame="f"), '
            f'Bitmap(rowID={int(perm[2 * i + 1])}, frame="f")))'
            for i in range(8)
        )

    qs = [build_q(i) for i in range(6)]
    # Ground truth once, pre-churn, via numpy (the churned rows are
    # disjoint, so these stay correct throughout).
    want = {q: Executor(h, engine="numpy").execute("c", q) for q in qs}
    for q in qs:  # warm: rows resident, Gram builds
        assert ex.execute("c", q) == want[q]

    stop = threading.Event()
    failures: list = []
    writes_done = [0]

    def reader(tid):
        try:
            k = tid
            while not stop.is_set():
                q = qs[k % len(qs)]
                got = ex.execute("c", q)
                if got != want[q]:
                    failures.append((q, got, want[q]))
                    return
                k += 1
        except BaseException as exc:  # raising IS a failure here
            failures.append(("reader raised", exc))

    def writer():
        try:
            wrng = np.random.default_rng(99)
            while not stop.is_set():
                row = n_read_rows + int(wrng.integers(n_churn_rows))
                col = int(wrng.integers(2 * SLICE_WIDTH))
                ex.execute("c", f'SetBit(rowID={row}, frame="f", columnID={col})')
                writes_done[0] += 1
        except BaseException as exc:
            failures.append(("writer raised", exc))

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(4)]
    threads.append(threading.Thread(target=writer))
    for t in threads:
        t.start()
    import time

    time.sleep(6.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "thread hung (deadlock?)"
    assert not failures, failures[:2]
    assert writes_done[0] > 0, "writer made no progress: churn never happened"
    h.close()
