"""Request-lifecycle QoS tests: deadlines, admission control, shedding.

Covers the qos/ subsystem units (Deadline, classification, the bounded
admission gate, the bounded stats reservoirs) and the serving-path
integrations: queue-full -> 429 + Retry-After, expired deadline -> 504
BEFORE execution, executor checkpoint cancellation mid-query, the
client's Retry-After backoff and per-request timeout override, and the
lockstep arrival-queue bound.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import qos
from pilosa_tpu.config import Config
from pilosa_tpu.qos import (
    AdmissionController,
    CLASS_ADMIN,
    CLASS_READ,
    CLASS_WRITE,
    Deadline,
    DeadlineExceeded,
    ShedError,
    classify_request,
    deadline_from_headers,
)


# -- Deadline ---------------------------------------------------------------


def test_deadline_budget_and_expiry():
    clock = [100.0]
    d = Deadline(50, clock=lambda: clock[0])
    assert 49 < d.remaining_ms() <= 50
    assert not d.expired()
    d.check()  # no raise
    clock[0] += 0.049
    assert not d.expired()
    clock[0] += 0.002
    assert d.expired()
    with pytest.raises(DeadlineExceeded, match="mid-query"):
        d.check("mid-query")
    assert d.header_value() == "0"  # floor: never a negative hop budget


def test_deadline_from_headers_precedence():
    # Header wins over the configured default.
    d = deadline_from_headers({"x-pilosa-deadline-ms": "250"}, default_ms=5000)
    assert 200 < d.remaining_ms() <= 250
    # No header: the default applies; 0 default = unbounded.
    assert deadline_from_headers({}, default_ms=0) is None
    d = deadline_from_headers({}, default_ms=100)
    assert d is not None and d.remaining_ms() <= 100
    # Malformed header falls back to the default, never fails the door.
    d = deadline_from_headers({"x-pilosa-deadline-ms": "bogus"}, default_ms=100)
    assert d is not None and d.remaining_ms() <= 100


# -- classification ---------------------------------------------------------


@pytest.mark.parametrize(
    "method,path,body,want",
    [
        ("POST", "/index/i/query", b"Count(Bitmap(rowID=1))", CLASS_READ),
        ("POST", "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=2)', CLASS_WRITE),
        ("POST", "/index/i/query", b'ClearBit(rowID=1, frame="f", columnID=2)', CLASS_WRITE),
        ("POST", "/import", b"", CLASS_WRITE),
        ("POST", "/fragment/data", b"", CLASS_WRITE),
        ("POST", "/index/i/frame/f/restore", b"", CLASS_WRITE),
        ("GET", "/fragment/data", b"", CLASS_READ),
        ("GET", "/export", b"", CLASS_READ),
        ("POST", "/index/i/attr/diff", b"", CLASS_READ),
        ("GET", "/status", b"", CLASS_ADMIN),
        ("GET", "/debug/vars", b"", CLASS_ADMIN),
        ("POST", "/index/i", b"", CLASS_ADMIN),
        ("DELETE", "/index/i/frame/f", b"", CLASS_ADMIN),
    ],
)
def test_classify_request(method, path, body, want):
    assert classify_request(method, path, body) == want


# -- admission --------------------------------------------------------------


def test_admission_bounds_and_shed():
    adm = AdmissionController(
        depths={CLASS_READ: 1}, queue_wait_ms=30.0, retry_after_ms=100.0
    )
    adm.acquire(CLASS_READ)  # slot 1 of 1
    # Second concurrent request waits at the door, then sheds: nothing
    # releases within queue_wait_ms.
    t0 = time.monotonic()
    with pytest.raises(ShedError) as e:
        adm.acquire(CLASS_READ)
    assert time.monotonic() - t0 >= 0.025
    assert e.value.status == 429 and e.value.retry_after == pytest.approx(0.1)
    # After release the door admits again.
    adm.release(CLASS_READ)
    with adm.admit(CLASS_READ):
        pass
    assert adm.stat_shed == 1 and adm.stat_admitted >= 2


def test_admission_wait_lane_bound_sheds_immediately():
    """Waiters are bounded too (depth of them): the request past the
    wait lane is rejected at once, not queued into collapse."""
    adm = AdmissionController(depths={CLASS_READ: 1}, queue_wait_ms=500.0)
    adm.acquire(CLASS_READ)
    waiter_err = []

    def waiter():
        try:
            adm.acquire(CLASS_READ, deadline=Deadline(400))
        except ShedError as e:
            waiter_err.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)  # waiter is parked in the wait lane
    t1 = time.monotonic()
    with pytest.raises(ShedError):
        adm.acquire(CLASS_READ)  # wait lane full -> immediate shed
    assert time.monotonic() - t1 < 0.2
    adm.release(CLASS_READ)  # the parked waiter takes the slot
    t.join(timeout=2)
    assert not waiter_err
    adm.release(CLASS_READ)


def test_admission_unbounded_class():
    adm = AdmissionController(depths={CLASS_READ: 0})
    for _ in range(64):
        adm.acquire(CLASS_READ)  # depth 0 = no bound (pre-QoS behavior)
    for _ in range(64):
        adm.release(CLASS_READ)


def test_admission_respects_deadline_over_queue_wait():
    """A waiter never waits past its own deadline."""
    adm = AdmissionController(depths={CLASS_READ: 1}, queue_wait_ms=5000.0)
    adm.acquire(CLASS_READ)
    t0 = time.monotonic()
    with pytest.raises(ShedError):
        adm.acquire(CLASS_READ, deadline=Deadline(50))
    assert time.monotonic() - t0 < 1.0
    adm.release(CLASS_READ)


# -- stats reservoir (satellite) --------------------------------------------


def test_expvar_histogram_reservoir_bounded():
    from pilosa_tpu.stats import RESERVOIR_CAP, ExpvarStatsClient

    c = ExpvarStatsClient()
    n = RESERVOIR_CAP + 5000
    for i in range(n):
        c.histogram("lat", float(i))
        c.timing("t", float(i))
    # Memory is bounded at the cap; totals/min/max stay exact.
    assert len(c._histograms["lat"]) == RESERVOIR_CAP
    assert len(c._timings["t"]) == RESERVOIR_CAP
    snap = c.snapshot()
    h = snap["lat"]
    assert set(h) == {"count", "min", "max", "p50", "p95", "p99"}
    assert h["count"] == n and h["min"] == 0.0 and h["max"] == float(n - 1)
    # Percentiles come from a uniform sample of the full stream —
    # pre-computed (p50/p95/p99) so dashboards never re-derive them.
    assert 0.3 * n < h["p50"] < 0.7 * n
    assert 0.85 * n < h["p95"] <= h["p99"]
    assert h["p99"] > 0.9 * n
    # Timing average is exact (running sum), not reservoir-estimated.
    assert snap["t.avg_ms"] == pytest.approx((n - 1) / 2 * 1000)


def test_expvar_tagged_child_shares_reservoirs():
    from pilosa_tpu.stats import ExpvarStatsClient

    c = ExpvarStatsClient()
    child = c.with_tags("index:i")
    child.histogram("lat", 1.0)
    child.timing("t", 2.0)
    snap = c.snapshot()
    assert snap["lat[index:i]"]["count"] == 1
    assert snap["t[index:i].avg_ms"] == pytest.approx(2000.0)


# -- server integration -----------------------------------------------------


def _make_server(tmp_path, **cfg_kwargs):
    from pilosa_tpu.server.server import Server

    cfg = Config(data_dir=str(tmp_path / "s"), host="127.0.0.1:0", engine="numpy",
                 **cfg_kwargs)
    s = Server(cfg)
    s.open()
    return s


def _post(host, path, body=b"", headers=None, timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body, method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_server_queue_full_sheds_429_with_retry_after(tmp_path):
    srv = _make_server(
        tmp_path, qos_read_depth=1, qos_queue_wait_ms=20.0, qos_retry_after_ms=150.0
    )
    try:
        _post(srv.host, "/index/i")  # admin class: its own door
        _post(srv.host, "/index/i/frame/f")
        _post(srv.host, "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=3)')

        # Occupy the single read slot: a query blocked inside the
        # executor holds its admission token until released.
        gate = threading.Event()
        entered = threading.Event()
        real_execute = srv.executor.execute

        def slow_execute(*a, **kw):
            entered.set()
            gate.wait(10)
            return real_execute(*a, **kw)

        srv.executor.execute = slow_execute

        def bg_read():
            # The waiter may legitimately shed too (20 ms queue wait
            # elapses while the gate is held) — either outcome is fine
            # for a background thread; the assertions run on the third
            # request below.
            try:
                _post(srv.host, "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
            except urllib.error.HTTPError:
                pass

        t = threading.Thread(target=bg_read)
        t.start()
        assert entered.wait(10)
        # Wait lane holds one more; this third read fills it and sheds
        # after queue_wait_ms with 429 + Retry-After.
        t2 = threading.Thread(target=bg_read)
        t2.start()
        time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.host, "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
        assert e.value.code == 429
        assert float(e.value.headers["Retry-After"]) == pytest.approx(0.15)
        body = json.loads(e.value.read())
        assert "full" in body["error"]
        gate.set()
        t.join(timeout=10)
        t2.join(timeout=10)
        # Shed surfaced in /debug/vars counters.
        snap = json.loads(
            urllib.request.urlopen(f"http://{srv.host}/debug/vars", timeout=30).read()
        )
        assert snap.get("qos.shed.read", 0) >= 1
        assert any(k.startswith("qos.latency_ms.read") for k in snap)
    finally:
        srv.close()


def test_server_expired_deadline_504_before_execution(tmp_path):
    srv = _make_server(tmp_path)
    try:
        _post(srv.host, "/index/i")
        _post(srv.host, "/index/i/frame/f")
        calls = []
        real_execute = srv.executor.execute
        srv.executor.execute = lambda *a, **kw: (calls.append(a), real_execute(*a, **kw))[1]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(
                srv.host, "/index/i/query",
                b'Count(Bitmap(rowID=1, frame="f"))',
                headers={"X-Pilosa-Deadline-Ms": "0"},
            )
        assert e.value.code == 504
        assert "deadline exceeded" in json.loads(e.value.read())["error"]
        assert calls == []  # shed at the door, never reached the executor
        # /debug/vars records the expiry.
        snap = json.loads(
            urllib.request.urlopen(f"http://{srv.host}/debug/vars", timeout=30).read()
        )
        assert snap.get("qos.expired", 0) >= 1
    finally:
        srv.close()


def test_server_read_your_writes_with_deadline(tmp_path):
    """A generous deadline must not change results: write then read
    with deadlines enabled end to end (default-deadline config path)."""
    srv = _make_server(tmp_path, default_deadline_ms=30000.0)
    try:
        _post(srv.host, "/index/i")
        _post(srv.host, "/index/i/frame/f")
        _, _, _ = _post(srv.host, "/index/i/query", b'SetBit(rowID=2, frame="f", columnID=9)')
        _, _, payload = _post(srv.host, "/index/i/query", b'Count(Bitmap(rowID=2, frame="f"))')
        assert json.loads(payload)["results"] == [1]
    finally:
        srv.close()


def test_executor_checkpoint_cancels_mid_query(tmp_path):
    """The between-calls checkpoint: a deadline expiring after call 1
    stops the request before call 2 executes."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import ExecOptions, Executor

    from pilosa_tpu.core.frame import FrameOptions

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    idx.frame("f").set_bit("standard", 1, 3)
    ex = Executor(h, engine="numpy")

    clock = [0.0]
    d = Deadline(100, clock=lambda: clock[0])

    calls = []
    real = ex._execute_call

    def tracked(index, c, slices, opt):
        calls.append(c.name)
        clock[0] += 0.2  # the first call burns the whole budget
        return real(index, c, slices, opt)

    ex._execute_call = tracked
    q = 'TopN(frame="f", n=1) TopN(frame="f", n=2)'  # two unfused calls
    with pytest.raises(DeadlineExceeded, match="between calls"):
        ex.execute("i", q, opt=ExecOptions(deadline=d))
    assert calls == ["TopN"]  # the second call never ran
    # Pre-execution check: an expired deadline never enters the lane.
    calls.clear()
    with pytest.raises(DeadlineExceeded):
        ex.execute("i", q, opt=ExecOptions(deadline=d))
    assert calls == []
    h.close()


def test_map_reduce_chunk_checkpoint(tmp_path, monkeypatch):
    """The between-slice-chunks checkpoint in the fan-out."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import ExecOptions, Executor

    monkeypatch.setenv("PILOSA_TPU_SLICE_CHUNK", "1")
    from pilosa_tpu.core.frame import FrameOptions

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    from pilosa_tpu.pilosa import SLICE_WIDTH

    for s in range(4):
        idx.frame("f").set_bit("standard", 1, s * SLICE_WIDTH + 5)
    ex = Executor(h, engine="numpy")
    clock = [0.0]

    class TickingDeadline(Deadline):
        def expired(self):
            clock[0] += 1.0
            return clock[0] > 2.0  # chunk 1 passes, chunk 2's check trips

    d = TickingDeadline(1000, clock=lambda: clock[0])
    with pytest.raises(DeadlineExceeded, match="slice chunks"):
        ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))', opt=ExecOptions(deadline=d))
    h.close()


# -- client satellites ------------------------------------------------------


class _StubHTTP:
    """Minimal HTTP stub: scripted (status, headers, body) responses."""

    def __init__(self, script):
        import http.server
        import threading as _threading

        self.requests = []
        stub = self

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _serve(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                stub.requests.append(
                    {"path": self.path, "headers": dict(self.headers), "body": body}
                )
                status, headers, payload = (
                    script[min(len(stub.requests), len(script)) - 1]
                )
                if callable(payload):
                    payload = payload()
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = _serve

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.host = f"127.0.0.1:{self.httpd.server_address[1]}"
        t = _threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_client_honors_retry_after_on_429():
    from pilosa_tpu import wire
    from pilosa_tpu.server.client import Client

    ok = wire.encode_query_response(results=[1])
    stub = _StubHTTP([
        (429, {"Retry-After": "0.12", "Content-Type": "application/json"},
         b'{"error": "shed"}'),
        (200, {"Content-Type": "application/x-protobuf"}, ok),
    ])
    try:
        c = Client(stub.host)
        t0 = time.monotonic()
        resp = c.execute_query("i", "Count(Bitmap(rowID=1))")
        dt = time.monotonic() - t0
        assert len(stub.requests) == 2  # one retry after the hint
        assert dt >= 0.1  # honored the Retry-After
        assert resp["results"]
    finally:
        stub.close()


def test_client_retry_after_capped_and_bounded():
    """A huge Retry-After is capped, and the retry BUDGET bounds the
    loop: budget 1 = exactly one retry on the fan-out path, never an
    unbounded loop."""
    from pilosa_tpu.server.client import Client, ClientError

    stub = _StubHTTP([
        (429, {"Retry-After": "9999"}, b'{"error": "shed"}'),
        (429, {"Retry-After": "9999"}, b'{"error": "shed"}'),
    ])
    try:
        c = Client(stub.host, retry_budget=1)
        t0 = time.monotonic()
        with pytest.raises(ClientError) as e:
            c.execute_query("i", "Count(Bitmap(rowID=1))")
        dt = time.monotonic() - t0
        assert e.value.status == 429
        assert len(stub.requests) == 2
        assert dt < 5.0  # the 9999s hint was capped (RETRY_AFTER_CAP_S)
    finally:
        stub.close()


def test_client_forwards_deadline_header():
    from pilosa_tpu import wire
    from pilosa_tpu.server.client import Client

    ok = wire.encode_query_response(results=[1])
    stub = _StubHTTP([(200, {"Content-Type": "application/x-protobuf"}, ok)])
    try:
        c = Client(stub.host)
        c.execute_query("i", "Count(Bitmap(rowID=1))", deadline=Deadline(5000))
        hdrs = stub.requests[0]["headers"]
        sent = float(hdrs["X-Pilosa-Deadline-Ms"])
        assert 0 < sent <= 5000  # the REMAINING budget, not the original
    finally:
        stub.close()


def test_client_per_request_timeout_override():
    import time as _time

    stub = _StubHTTP([(200, {}, lambda: (_time.sleep(0.8), b"ok")[1])])
    try:
        from pilosa_tpu.server.client import Client

        c = Client(stub.host, timeout=30.0)  # constructor-wide default
        with pytest.raises(OSError):
            c._request("GET", "/version", timeout=0.15)  # per-request override
    finally:
        stub.close()


# -- lockstep arrival-queue bound -------------------------------------------


def test_lockstep_queue_bound_and_expired_drop(tmp_path):
    """Single-rank LockstepService: the arrival-queue bound sheds with
    429 semantics (ShedError), and an expired deadline resolves to 504
    semantics (DeadlineExceeded) through the ship-time flag."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("g")
    idx.create_frame("f", FrameOptions())
    idx.frame("f").set_bit("standard", 1, 3)
    svc = LockstepService(h, control_addr=("127.0.0.1", 0), queue_depth=1)
    q = 'Count(Bitmap(rowID=1, frame="f"))'
    assert svc._execute("g", q) == [1]

    # Expired at ship time -> dropped before execution, 504 semantics.
    clock = [0.0]
    d = Deadline(0, clock=lambda: clock[0])
    clock[0] = 1.0
    with pytest.raises(DeadlineExceeded):
        svc._execute("g", q, deadline=d)
    assert svc.stat_expired == 1

    # Saturate: block execution so arrivals stack up behind the
    # shipper, then overflow the bounded queue.
    gate = threading.Event()
    entered = threading.Event()
    real = svc.executor.execute

    def slow(*a, **kw):
        entered.set()
        gate.wait(10)
        return real(*a, **kw)

    svc.executor.execute = slow
    results = []
    threads = [
        threading.Thread(target=lambda: results.append(svc._execute("g", q)))
        for _ in range(3)
    ]
    for t in threads:
        t.start()
        time.sleep(0.05)
    assert entered.wait(10)
    time.sleep(0.1)  # t0 executing, t1 shipped+waiting, t2 queued (depth 1)
    with pytest.raises(ShedError) as e:
        svc._execute("g", q)
    assert e.value.status == 429 and svc.stat_shed == 1
    gate.set()
    for t in threads:
        t.join(timeout=10)
    assert results == [[1]] * 3  # everyone admitted was served
    h.close()


# -- config promotion (satellite) -------------------------------------------


def test_config_qos_and_lockstep_toml_env(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        """
data-dir = "/tmp/x"

[qos]
  default-deadline = "1500ms"
  read-depth = 7
  write-depth = 5
  admin-depth = 3
  queue-wait = "40ms"
  retry-after = "2s"

[lockstep]
  ack-timeout = "45s"
  connect-timeout = "30s"
  queue-depth = 77
"""
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.default_deadline_ms == 1500.0
    assert (cfg.qos_read_depth, cfg.qos_write_depth, cfg.qos_admin_depth) == (7, 5, 3)
    assert cfg.qos_queue_wait_ms == pytest.approx(40.0)
    assert cfg.qos_retry_after_ms == pytest.approx(2000.0)
    assert cfg.lockstep_ack_timeout == 45.0
    assert cfg.lockstep_connect_timeout == 30.0
    assert cfg.lockstep_queue_depth == 77
    # Env overrides TOML (cmd/root.go precedence).
    cfg.apply_env({
        "PILOSA_TPU_DEADLINE_MS": "900",
        "PILOSA_TPU_QOS_READ_DEPTH": "11",
        "PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT": "33",
        "PILOSA_TPU_LOCKSTEP_CONNECT_TIMEOUT": "12",
        "PILOSA_TPU_LOCKSTEP_QUEUE_DEPTH": "13",
    })
    assert cfg.default_deadline_ms == 900.0
    assert cfg.qos_read_depth == 11
    assert cfg.lockstep_ack_timeout == 33.0
    assert cfg.lockstep_connect_timeout == 12.0
    assert cfg.lockstep_queue_depth == 13


def test_lockstep_service_uses_configured_timeouts(tmp_path, monkeypatch):
    """Ctor args (the CLI passes Config values) beat env, env beats the
    built-in defaults — the PR-2 precedence, now for the previously
    hard-coded lockstep timeouts."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    monkeypatch.setenv("PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT", "55")
    monkeypatch.setenv("PILOSA_TPU_LOCKSTEP_CONNECT_TIMEOUT", "44")
    svc = LockstepService(h, control_addr=("127.0.0.1", 0))
    assert svc.ack_timeout == 55.0 and svc.connect_timeout == 44.0
    svc2 = LockstepService(
        h, control_addr=("127.0.0.1", 0), ack_timeout=9.0, connect_timeout=8.0,
        queue_depth=4,
    )
    assert svc2.ack_timeout == 9.0 and svc2.connect_timeout == 8.0
    assert svc2.queue_depth == 4
    h.close()
