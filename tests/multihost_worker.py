"""Worker process for tests/test_multihost.py: joins a 2-process gloo
mesh, builds a global slice stack from process-local shards, runs the
sharded kernels, and prints verifiable results.

Run: python tests/multihost_worker.py <coordinator> <num_procs> <pid>
"""

import json
import sys


def main() -> int:
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import numpy as np

    from pilosa_tpu.parallel.multihost import MultiHostSliceMesh, init_multihost

    init_multihost(coordinator, num_procs, pid, local_device_count=2)

    import jax

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.parallel import sharded_count_and, sharded_union_reduce

    mesh = MultiHostSliceMesh()
    n_slices, W = 8, 256
    rng = np.random.default_rng(42)  # same seed everywhere: shared ground truth
    a_full = rng.integers(0, 1 << 32, size=(n_slices, W), dtype=np.uint32)
    b_full = rng.integers(0, 1 << 32, size=(n_slices, W), dtype=np.uint32)

    owned = mesh.owned_slices(n_slices)
    a = mesh.shard_stack_local({s: a_full[s] for s in owned}, n_slices, (W,))
    b = mesh.shard_stack_local({s: b_full[s] for s in owned}, n_slices, (W,))

    got_count = int(sharded_count_and(mesh, a, b))
    want_count = sum(bw.np_count_and(a_full[i], b_full[i]) for i in range(n_slices))

    union = mesh.fetch_global(sharded_union_reduce(mesh, [a, b]))
    union_ok = bool(np.array_equal(union, a_full | b_full))

    print(
        json.dumps(
            {
                "pid": pid,
                "global_devices": jax.device_count(),
                "local_devices": jax.local_device_count(),
                "owned": owned,
                "count": got_count,
                "count_ok": got_count == want_count,
                "union_ok": union_ok,
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
