"""Worker process for tests/test_multihost.py: joins a 2-process gloo
mesh, builds a global slice stack from process-local shards, runs the
sharded kernels, and prints verifiable results.

Run: python tests/multihost_worker.py <coordinator> <num_procs> <pid>
"""

import json
import sys


def main() -> int:
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import numpy as np

    from pilosa_tpu.parallel.multihost import MultiHostSliceMesh, init_multihost

    init_multihost(coordinator, num_procs, pid, local_device_count=2)

    import jax

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.parallel import sharded_count_and, sharded_union_reduce

    mesh = MultiHostSliceMesh()
    n_slices, W = 8, 256
    rng = np.random.default_rng(42)  # same seed everywhere: shared ground truth
    a_full = rng.integers(0, 1 << 32, size=(n_slices, W), dtype=np.uint32)
    b_full = rng.integers(0, 1 << 32, size=(n_slices, W), dtype=np.uint32)

    owned = mesh.owned_slices(n_slices)
    a = mesh.shard_stack_local({s: a_full[s] for s in owned}, n_slices, (W,))
    b = mesh.shard_stack_local({s: b_full[s] for s in owned}, n_slices, (W,))

    got_count = int(sharded_count_and(mesh, a, b))
    want_count = sum(bw.np_count_and(a_full[i], b_full[i]) for i in range(n_slices))

    union = mesh.fetch_global(sharded_union_reduce(mesh, [a, b]))
    union_ok = bool(np.array_equal(union, a_full | b_full))

    # Full product stack in SPMD lockstep: every process holds the same
    # Holder data and runs the SAME PQL through a MeshEngine whose slice
    # axis spans the GLOBAL device list — host work is replicated, device
    # work is sharded, counts psum across processes.  The multi-host
    # analog of the reference's coordinator+peers, with ICI/DCN
    # collectives instead of protobuf-over-TCP reduces.
    import tempfile

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.engine import MeshEngine
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("g")
        idx.create_frame("f", FrameOptions())
        fr = idx.frame("f")
        for r in range(3):
            for s in range(4):
                fr.set_bit("standard", r, s * SLICE_WIDTH + 7 + r)
                fr.set_bit("standard", r, s * SLICE_WIDTH + 99)
        e_np = Executor(h, engine="numpy")
        e_mesh = Executor(h, engine=MeshEngine(devices=jax.devices()))
        q = (
            'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
            'Count(Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))'
        )
        mesh_res = e_mesh.execute("g", q)
        exec_ok = mesh_res == e_np.execute("g", q)

        # TopN(src): the ENGINE scorer must run on a multi-process mesh
        # (shard_map'd all-slice scoring + allgather), not the host
        # fallback — round-2 verdict item 7.
        qt = 'TopN(Bitmap(rowID=0, frame="f"), frame="f", n=3)'
        topn_parity_ok = e_mesh.execute("g", qt) == e_np.execute("g", qt)
        frags = [h.fragment("g", "f", "standard", s) for s in range(4)]
        src_b = [f.row_dense(0) for f in frags]
        assert e_mesh.engine.row_scorer_all_slices, "expected all-slice scorer"
        scorer_for = e_mesh._topn_scorer_factory("g", "f", list(range(4)), src_b)
        sc = scorer_for(1, src_b[1])
        scorer_engaged = sc is not None
        topn_scorer_ok = False
        if scorer_engaged:
            got = [int(v) for v in sc([0, 1, 2])]
            want = [
                int(bw.np_count_and(frags[1].row_dense(r), src_b[1]))
                for r in range(3)
            ]
            topn_scorer_ok = got == want
        h.close()

    print(
        json.dumps(
            {
                "pid": pid,
                "global_devices": jax.device_count(),
                "local_devices": jax.local_device_count(),
                "owned": owned,
                "count": got_count,
                "count_ok": got_count == want_count,
                "union_ok": union_ok,
                "exec_results": [int(v) for v in mesh_res],
                "exec_ok": bool(exec_ok),
                "topn_parity_ok": bool(topn_parity_ok),
                "topn_scorer_engaged": bool(scorer_engaged),
                "topn_scorer_ok": bool(topn_scorer_ok),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
