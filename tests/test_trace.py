"""Request-tracing tests: span trees, head sampling, the slow-query
log, wire propagation, executor integration, and the client-retry
satellites (one retry = one trace identity; deadline expiry during
backoff aborts the retry).

End-to-end HTTP coverage (/debug/traces, slow log through a real
server, two-node remote sub-spans) lives in test_server.py; the 2-rank
lockstep sampling-determinism test lives in test_multihost.py.
"""

import json
import logging
import time

import pytest

from pilosa_tpu.config import Config
from pilosa_tpu.trace import (
    TRACE_HEADER,
    TRACE_SPANS_HEADER,
    Span,
    Trace,
    Tracer,
    fingerprint,
)


# -- spans --------------------------------------------------------------------


def test_span_tree_offsets_and_tags():
    root = Span("root", trace_id="t1")
    a = root.child("parse")
    a.finish()
    b = root.child("call.Count").annotate(slices=4)
    b.finish()
    root.finish()
    assert a.trace_id == "t1"  # children inherit the trace identity
    js = root.to_json()
    assert js["name"] == "root" and js["ms"] >= 0
    names = [c["name"] for c in js["children"]]
    assert names == ["parse", "call.Count"]
    assert js["children"][1]["tags"] == {"slices": 4}
    # Offsets are relative to the root's own start — no wall clock.
    assert all(c["start_ms"] >= 0 for c in js["children"])


def test_span_finish_idempotent_and_unfinished_serializes():
    sp = Span("x")
    sp.finish()
    ms1 = sp.ms
    time.sleep(0.002)
    sp.finish()
    assert sp.ms == ms1  # idempotent
    live = Span("still-running")
    js = live.to_json()
    assert js["ms"] >= 0  # measured at serialization, not an error


def test_span_graft_keeps_remote_payload_verbatim():
    root = Span("root")
    remote = root.child("remote")
    payload = [{"name": "POST /index/i/query", "start_ms": 0.0, "ms": 3.2,
                "children": [{"name": "parse", "start_ms": 0.1, "ms": 0.2}]}]
    remote.graft(payload)
    remote.finish()
    js = root.to_json()
    grafted = js["children"][0]["children"][0]
    assert grafted["name"] == "POST /index/i/query"
    assert grafted["children"][0]["name"] == "parse"


def test_stage_breakdown_sums_duplicate_names():
    root = Span("root")
    for ms in (1.0, 2.0):
        c = root.child("slice_chunk")
        c.ms = ms
    c = root.child("parse")
    c.ms = 0.5
    bd = root.stage_breakdown()
    assert bd == {"slice_chunk": 3.0, "parse": 0.5}


# -- sampling -----------------------------------------------------------------


def test_head_sampling_rate_zero_only_forced():
    t = Tracer(sample_rate=0.0)
    assert t.begin({}) is None  # never sampled
    tr = t.begin({TRACE_HEADER.lower(): "1"})
    assert tr is not None and tr.forced and tr.propagate
    # A bare override gets a fresh id; a propagated id is adopted.
    assert len(tr.id) == 16
    tr2 = t.begin({TRACE_HEADER.lower(): "abc123def"})
    assert tr2.id == "abc123def"


def test_head_sampling_rate_one_and_decide():
    t = Tracer(sample_rate=1.0)
    tr = t.begin({})
    assert tr is not None and not tr.forced and not tr.propagate
    assert t.decide() is True
    t0 = Tracer(sample_rate=0.0)
    assert t0.decide() is False and t0.decide(force=True) is True


def test_ring_bounded_newest_first_min_ms_filter():
    t = Tracer(sample_rate=1.0, ring=4)
    for i in range(8):
        tr = Trace(f"q{i}")
        tr.root.ms = float(i)
        t.record(tr)
    snap = t.traces_json()
    assert len(snap) == 4  # bounded
    assert [e["name"] for e in snap] == ["q7", "q6", "q5", "q4"]  # newest-first
    assert [e["name"] for e in t.traces_json(min_ms=6.0)] == ["q7", "q6"]
    assert len(t.traces_json(limit=1)) == 1


# -- slow-query log -----------------------------------------------------------


def test_slow_request_bypasses_sampling_and_logs(caplog):
    t = Tracer(sample_rate=0.0, slow_ms=5.0)
    # Fast + unsampled: nothing recorded, nothing logged.
    assert t.finish_request(None, name="POST /q", dt_ms=1.0, body=b"x") is None
    assert len(t) == 0
    with caplog.at_level(logging.WARNING, logger="pilosa_tpu.slowquery"):
        t.finish_request(None, name="POST /q", dt_ms=72.0,
                         body=b'Count(Bitmap(rowID=1, frame="f"))')
    assert len(t) == 1 and t.stat_slow == 1
    entry = t.traces_json()[0]
    assert entry["slow"] and entry["ms"] == 72.0
    assert entry["spans"]["tags"]["unsampled"] is True
    rec = json.loads(caplog.records[-1].message.split("slow-query ", 1)[1])
    assert rec["ms"] == 72.0 and rec["fp"] and "Count(" in rec["snippet"]


def test_slow_sampled_trace_logs_stage_breakdown(caplog):
    t = Tracer(sample_rate=1.0, slow_ms=1.0)
    tr = t.begin({}, name="POST /q")
    tr.root.tags["qcache"] = "miss"
    sp = tr.root.child("parse")
    sp.ms = 0.4
    sp = tr.root.child("call.Count")
    sp.ms = 9.0
    with caplog.at_level(logging.WARNING, logger="pilosa_tpu.slowquery"):
        t.finish_request(tr, name="POST /q", dt_ms=10.0, body=b"Count(...)")
    rec = json.loads(caplog.records[-1].message.split("slow-query ", 1)[1])
    assert rec["stages"] == {"parse": 0.4, "call.Count": 9.0}
    assert rec["tags"]["qcache"] == "miss"  # cache disposition surfaced


def test_propagate_returns_header_and_truncates_oversize():
    t = Tracer(sample_rate=0.0)
    tr = t.begin({TRACE_HEADER.lower(): "deadbeef"}, name="POST /q")
    extra = t.finish_request(tr, name="POST /q", dt_ms=1.0)
    payload = json.loads(extra[TRACE_SPANS_HEADER])
    assert payload[0]["name"] == "POST /q"
    # Oversize trees degrade to the root rather than breaking the header.
    tr2 = t.begin({TRACE_HEADER.lower(): "deadbeef"}, name="POST /q")
    for i in range(3000):
        tr2.root.child(f"span-{i}").finish()
    extra2 = t.finish_request(tr2, name="POST /q", dt_ms=1.0)
    raw = extra2[TRACE_SPANS_HEADER]
    assert len(raw) < 32000
    slim = json.loads(raw)[0]
    assert slim.get("truncated") and "children" not in slim


def test_fingerprint_stable_and_bounded():
    a = fingerprint(b"Count(Bitmap(rowID=1))" * 100)
    b = fingerprint(b"Count(Bitmap(rowID=1))" * 100)
    assert a == b and len(a["snippet"]) <= 120 and len(a["fp"]) == 12
    assert fingerprint(b"") == {"fp": "", "snippet": ""}


# -- config promotion ---------------------------------------------------------


def test_config_trace_toml_and_env(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        """
[trace]
  sample-rate = 0.25
  slow-ms = 150.0
  ring = 64
"""
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.trace_sample_rate == 0.25
    assert cfg.trace_slow_ms == 150.0
    assert cfg.trace_ring == 64
    cfg.apply_env({
        "PILOSA_TPU_TRACE_SAMPLE_RATE": "0.5",
        "PILOSA_TPU_TRACE_SLOW_MS": "75",
        "PILOSA_TPU_TRACE_RING": "32",
    })
    assert cfg.trace_sample_rate == 0.5
    assert cfg.trace_slow_ms == 75.0
    assert cfg.trace_ring == 32
    # Defaults: tracing off (only the force header samples).
    assert Config().trace_sample_rate == 0.0 and Config().trace_slow_ms == 0.0


# -- executor integration -----------------------------------------------------


@pytest.fixture
def holder(tmp_path):
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    fr = idx.frame("f")
    for r in range(3):
        for c in range(r, 30 + r):
            fr.set_bit("standard", r, c)
    yield h
    h.close()


def test_executor_spans_sequential_path(holder):
    from pilosa_tpu.executor import ExecOptions, Executor

    ex = Executor(holder, engine="numpy")
    root = Span("root")
    res = ex.execute("i", 'TopN(frame="f", n=2) Bitmap(rowID=1, frame="f")',
                     opt=ExecOptions(span=root))
    assert len(res) == 2
    names = [c.name for c in root.children]
    assert "parse" in names
    assert "call.TopN" in names and "call.Bitmap" in names
    # Fan-out spans nest under the calls.
    topn = next(c for c in root.children if c.name == "call.TopN")
    assert any(c.name in ("slices", "slice_chunk") for c in topn.children)
    assert all(c.ms is not None for c in root.children)


def test_executor_spans_fused_and_lanes(holder):
    import os

    from pilosa_tpu.executor import ExecOptions, Executor

    os.environ["PILOSA_TPU_NO_FASTLANE"] = "1"  # land in the AST fused lane
    try:
        ex = Executor(holder, engine="numpy")
        root = Span("root")
        q = ('Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f"))) '
             'Count(Union(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))')
        ex.execute("i", q, opt=ExecOptions(span=root))
        assert root.tags.get("lane") == "fused"
        fsp = next(c for c in root.children if c.name == "fused")
        assert fsp.tags["calls"] == 2 and fsp.tags["slices"] >= 1
    finally:
        del os.environ["PILOSA_TPU_NO_FASTLANE"]
    # Fast lanes tag without span children (single-branch sites).
    ex2 = Executor(holder, engine="numpy")
    root2 = Span("root")
    ex2.execute("i", 'SetBit(rowID=9, frame="f", columnID=3)',
                opt=ExecOptions(span=root2))
    assert root2.tags.get("lane") == "write_fast"


def test_executor_qcache_span_outcomes(holder):
    from pilosa_tpu.executor import ExecOptions, Executor
    from pilosa_tpu.qcache import QueryCache

    ex = Executor(holder, engine="numpy", qcache=QueryCache(min_cost_ms=0.0))
    q = 'Count(Bitmap(rowID=1, frame="f"))'
    r1 = Span("r1")
    ex.execute("i", q, opt=ExecOptions(span=r1))
    assert r1.tags["qcache"] == "miss"
    r2 = Span("r2")
    ex.execute("i", q, opt=ExecOptions(span=r2))
    assert r2.tags["qcache"] == "hit"
    assert any(c.name == "qcache.lookup" for c in r2.children)
    r3 = Span("r3")
    ex.execute("i", q, opt=ExecOptions(span=r3, no_cache=True))
    assert r3.tags["qcache"] == "bypass"


def test_executor_untraced_requests_build_no_spans(holder):
    """The off path: no span objects anywhere (opt.span=None and the
    default ExecOptions) — guard against accidental always-on costs."""
    from pilosa_tpu.executor import ExecOptions, Executor

    ex = Executor(holder, engine="numpy")
    opt = ExecOptions()
    assert opt.span is None
    res = ex.execute("i", 'Count(Bitmap(rowID=1, frame="f"))', opt=opt)
    assert res and opt.span is None


# -- client satellites: retry keeps ONE trace/request identity ----------------


class _StubHTTP:
    """Minimal scripted HTTP stub (same shape as test_qos's)."""

    def __init__(self, script):
        import http.server
        import threading

        self.requests = []
        stub = self

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _serve(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                stub.requests.append(
                    {"path": self.path, "headers": dict(self.headers), "body": body}
                )
                status, headers, payload = (
                    script[min(len(stub.requests), len(script)) - 1]
                )
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = _serve

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.host = f"127.0.0.1:{self.httpd.server_address[1]}"
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_client_retry_reuses_trace_identity():
    """One capped Retry-After retry must reuse the SAME trace/request
    identity: the retried attempt carries the identical X-Pilosa-Trace
    id, and the hop span grafts exactly ONE peer payload — never a
    duplicate root span per attempt."""
    from pilosa_tpu import wire
    from pilosa_tpu.server.client import Client

    ok = wire.encode_query_response(results=[1])
    peer_spans = json.dumps([{"name": "POST /index/i/query", "start_ms": 0.0,
                              "ms": 1.5}])
    stub = _StubHTTP([
        (429, {"Retry-After": "0.05", "Content-Type": "application/json"},
         b'{"error": "shed"}'),
        (200, {"Content-Type": "application/x-protobuf",
               TRACE_SPANS_HEADER: peer_spans}, ok),
    ])
    try:
        c = Client(stub.host)
        hop = Span("remote", trace_id="feedface12345678")
        resp = c.execute_query("i", "Count(Bitmap(rowID=1))", trace_span=hop)
        assert resp["results"]
        assert len(stub.requests) == 2  # one retry happened
        ids = [r["headers"].get(TRACE_HEADER) for r in stub.requests]
        assert ids == ["feedface12345678", "feedface12345678"]  # same identity
        # Exactly one grafted peer payload (from the final response).
        assert len(hop.children) == 1
        assert hop.children[0]["name"] == "POST /index/i/query"
    finally:
        stub.close()


def test_client_deadline_expiry_during_backoff_aborts_retry():
    """Deadline expiry during the Retry-After backoff must abort the
    retry: the client returns the shed answer after ONE attempt instead
    of sleeping past the budget."""
    from pilosa_tpu.qos import Deadline
    from pilosa_tpu.server.client import Client, ClientError

    stub = _StubHTTP([
        (429, {"Retry-After": "1.5"}, b'{"error": "shed"}'),
        (200, {}, b"never reached"),
    ])
    try:
        c = Client(stub.host)
        t0 = time.monotonic()
        with pytest.raises(ClientError) as e:
            c.execute_query(
                "i", "Count(Bitmap(rowID=1))", deadline=Deadline(200),
                trace_span=Span("remote", trace_id="aa"),
            )
        assert e.value.status == 429
        assert len(stub.requests) == 1  # the retry was aborted, not slept
        assert time.monotonic() - t0 < 1.0
    finally:
        stub.close()
