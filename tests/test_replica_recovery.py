"""Durable write log + group catch-up: writes survive a dead replica
group, and a restarted group re-converges.

The invariants pinned here (PR 7's upgrade of the replica tier):

- Every accepted write is sequenced into the router WAL (fsync-batched,
  length+checksum framed, crash-recoverable, compactable) BEFORE any
  group sees it; aborted writes (shed before any commit) are
  tombstoned so replay can never deliver a write no live group holds.
- Writes commit on a DEGRADED quorum (majority of groups): with 3
  groups and one dead, ingest keeps flowing — no 503 storm — while the
  dead group's backlog accumulates in the WAL.
- A restarted group reports its persisted last-applied sequence, gets
  the missed WAL suffix replayed in order (epoch-guarded), converges
  to IDENTICAL query results, and only then rejoins the read rotation.
- Partial-failure orderings (crash mid-fan-out, shed-after-commit) are
  reproducible through the seeded fault seam (PILOSA_TPU_FAULT_SPEC).
- Satellites: probe backoff (jittered exponential per down group),
  client retry budget (deadline-aware, decorrelated jitter), replay
  trace tagging, lag/WAL observability, config promotion.
"""

import json
import os
import socket
import tempfile
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from pilosa_tpu.config import Config
from pilosa_tpu.replica import (
    APPLIED_SEQ_HEADER,
    GROUP_HEADER,
    REPLAY_HEADER,
    ReplicaRouter,
    write_not_applied,
)
from pilosa_tpu.replica.catchup import AppliedSeq, note_applied_from_headers
from pilosa_tpu.replica.faults import (
    FaultError,
    FaultInjector,
    InjectedStatus,
)
from pilosa_tpu.replica.wal import WriteAheadLog, _FRAME
from pilosa_tpu.stats import ExpvarStatsClient


# -- WAL unit tests -----------------------------------------------------------


def test_wal_append_records_roundtrip(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "w.wal"))
    s1 = wal.append("POST", "/index/i/query", b"SetBit(...)", "text/plain")
    s2 = wal.append("POST", "/index/i", b"{}")
    assert (s1, s2) == (1, 2)
    assert wal.last_seq == 2 and wal.first_seq == 1
    recs = wal.records(1)
    assert [(r.seq, r.method, r.path, r.body, r.ctype) for r in recs] == [
        (1, "POST", "/index/i/query", b"SetBit(...)", "text/plain"),
        (2, "POST", "/index/i", b"{}", ""),
    ]
    assert wal.records(2)[0].seq == 2 and len(wal.records(3)) == 0
    wal.close()


def test_wal_reopen_recovers_sequence_and_records(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = WriteAheadLog(path)
    for i in range(5):
        wal.append("POST", f"/p{i}", bytes([i]) * i)
    wal.abort(wal.append("POST", "/aborted", b"x"))
    wal.close()
    wal2 = WriteAheadLog(path)
    assert wal2.last_seq == 6
    recs = wal2.records(1)
    assert [r.seq for r in recs] == [1, 2, 3, 4, 5]  # tombstone skipped
    assert wal2.append("POST", "/next", b"") == 7  # sequence space continues
    wal2.close()


def test_wal_torn_tail_truncated(tmp_path):
    """A crash mid-append leaves a torn frame: recovery truncates it,
    keeps every complete record, and appends continue cleanly."""
    path = str(tmp_path / "w.wal")
    stats = ExpvarStatsClient()
    wal = WriteAheadLog(path)
    wal.append("POST", "/a", b"aaaa")
    wal.append("POST", "/b", b"bbbb")
    good_size = wal.size_bytes
    wal.close()
    with open(path, "ab") as f:
        f.write(_FRAME.pack(1 << 20, 0))  # length header with no payload
        f.write(b"torn-garbage")
    wal2 = WriteAheadLog(path, stats=stats)
    assert wal2.last_seq == 2
    assert wal2.size_bytes == good_size  # the tail was truncated away
    assert stats.snapshot().get("wal.torn_tail") == 1
    assert wal2.append("POST", "/c", b"cc") == 3
    wal2.close()
    wal3 = WriteAheadLog(path)  # and the re-append round-trips
    assert [r.seq for r in wal3.records(1)] == [1, 2, 3]
    wal3.close()


def test_wal_corrupt_crc_truncates_from_there(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = WriteAheadLog(path)
    wal.append("POST", "/a", b"aaaa")
    off_b = wal.size_bytes
    wal.append("POST", "/b", b"bbbb")
    wal.close()
    with open(path, "r+b") as f:  # flip a payload byte in record 2
        f.seek(off_b + _FRAME.size + 2)
        f.write(b"\xff")
    wal2 = WriteAheadLog(path)
    assert wal2.last_seq == 1  # the corrupt record and everything after drops
    assert [r.seq for r in wal2.records(1)] == [1]
    wal2.close()


def test_wal_compaction_drops_applied_prefix(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = WriteAheadLog(path)
    for i in range(10):
        wal.append("POST", f"/p{i}", b"x" * 64)
    wal.abort(wal.append("POST", "/ab", b"y"))
    before = wal.size_bytes
    freed = wal.compact(7)
    assert freed > 0 and wal.size_bytes < before
    assert wal.first_seq == 8 and wal.last_seq == 11
    assert [r.seq for r in wal.records(1)] == [8, 9, 10]
    # Still recoverable from disk after the rewrite.
    wal.close()
    wal2 = WriteAheadLog(path)
    assert wal2.last_seq == 11
    assert [r.seq for r in wal2.records(1)] == [8, 9, 10]
    wal2.close()


def test_wal_in_memory_parity():
    """path=None: identical sequence/abort/replay semantics, no disk."""
    wal = WriteAheadLog(None)
    assert wal.append("POST", "/a", b"1") == 1
    assert wal.append("POST", "/b", b"2") == 2
    wal.abort(2)
    assert [r.seq for r in wal.records(1)] == [1]
    wal.compact(1)
    assert wal.records(1) == [] and wal.last_seq == 2
    assert wal.append("POST", "/c", b"3") == 3
    wal.close()


def test_wal_concurrent_appends_group_commit(tmp_path):
    """Concurrent appenders share fsyncs and never collide on sequence
    numbers or frames (the group-commit path)."""
    wal = WriteAheadLog(str(tmp_path / "w.wal"))
    seqs: list[int] = []
    mu = threading.Lock()

    def worker(k):
        for i in range(25):
            s = wal.append("POST", f"/t{k}/{i}", f"{k}:{i}".encode())
            with mu:
                seqs.append(s)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(seqs) == list(range(1, 101))
    recs = wal.records(1)
    assert [r.seq for r in recs] == list(range(1, 101))
    assert {r.body.decode() for r in recs} == {
        f"{k}:{i}" for k in range(4) for i in range(25)
    }
    wal.close()


def test_wal_compact_excludes_inflight_fsync_and_clamps_frontier(
        tmp_path, monkeypatch):
    """compact() swaps the backing file while a group-commit leader may
    be inside os.fsync on the OLD fd: the swap must WAIT for that
    leader (never close a fd under a syscall) and afterwards the
    synced frontier must be the NEW file's end — a stale old-file
    offset (which can exceed the compacted size) would make every
    later append think it is already durable and silently skip its
    fsync."""
    import pilosa_tpu.replica.wal as walmod

    wal = WriteAheadLog(str(tmp_path / "w.wal"))
    for i in range(6):
        wal.append("POST", f"/w{i}", b"x" * 200)
    real_fsync = os.fsync
    main_fd = wal._f.fileno()
    gate = threading.Event()
    entered = threading.Event()

    def parked_fsync(fd):
        if fd == main_fd:
            entered.set()
            gate.wait(10)
        return real_fsync(fd)

    monkeypatch.setattr(walmod.os, "fsync", parked_fsync)
    # A leader enters fsync on the main file and parks there...
    t = threading.Thread(target=lambda: wal.append("POST", "/park", b"p"))
    t.start()
    assert entered.wait(10)
    # ...while compaction tries to drop everything and swap the file.
    done = []
    c = threading.Thread(
        target=lambda: (wal.compact(wal.last_seq), done.append(1))
    )
    c.start()
    time.sleep(0.15)
    assert not done  # the swap waited for the in-flight leader
    gate.set()
    t.join(10)
    c.join(10)
    assert done and not t.is_alive() and not c.is_alive()
    # Frontier clamped to the compacted (empty) file, not stranded at
    # the old file's larger offset.
    assert wal._synced_off == wal._end_off == wal.size_bytes
    calls = []
    monkeypatch.setattr(
        walmod.os, "fsync", lambda fd: (calls.append(fd), real_fsync(fd))[1]
    )
    wal.append("POST", "/tail", b"y")  # still reaches the disk
    assert calls
    assert [r.path for r in wal.records(1)] == ["/tail"]
    wal.close()


def test_wal_concurrent_appends_survive_repeated_compaction(tmp_path):
    """Hammer appends from several threads against back-to-back
    compactions: no appender may ever crash (the old code could fsync
    a closed/stale fd -> ValueError) and the file must stay
    frame-parseable end to end."""
    wal = WriteAheadLog(str(tmp_path / "w.wal"))
    errs = []

    def appender(k):
        try:
            for i in range(40):
                wal.append("POST", f"/t{k}/{i}", b"z" * 128)
        except Exception as e:  # noqa: BLE001 — asserted empty below
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    while any(t.is_alive() for t in ts):
        wal.compact(wal.last_seq)
    for t in ts:
        t.join()
    assert errs == []
    assert wal.last_seq == 160
    wal.close()
    stats = ExpvarStatsClient()
    reopened = WriteAheadLog(wal.path, stats=stats)  # clean recovery scan
    assert stats.snapshot().get("wal.torn_tail", 0) == 0
    reopened.close()


def test_wal_hammer_under_lock_checker(tmp_path):
    """4-thread hammer — appenders (group-commit fsync) vs a dedicated
    compaction thread vs aborts — run UNDER the runtime lock checker
    (conftest enables PILOSA_TPU_LOCK_CHECK for this module): the PR 7
    fsync-generation fix must hold as a checkable discipline, i.e. no
    lock-order cycle among wal._mu / _sync_cv / _compact_mu and no
    fsync under a lock outside the documented allowlist (compaction's
    bulk copy under _compact_mu; the bounded delta fsync is scope-
    allowed in compact()).  Afterwards the log must recover cleanly
    with every non-aborted record intact."""
    from pilosa_tpu.analysis import lockcheck

    assert lockcheck.enabled()  # the conftest gate is active for this file
    lockcheck.reset()
    wal = WriteAheadLog(str(tmp_path / "w.wal"))
    errs: list = []
    aborted: set[int] = set()
    mu = threading.Lock()
    stop = threading.Event()

    def appender(k):
        try:
            for i in range(50):
                s = wal.append("POST", f"/t{k}/{i}", b"h" * 96)
                if i % 10 == 9:  # sprinkle tombstones into the stream
                    wal.abort(s)
                    with mu:
                        aborted.add(s)
        except Exception as e:  # noqa: BLE001 — asserted empty below
            errs.append(e)

    def compactor():
        try:
            while not stop.is_set():
                wal.compact(0)  # keep everything live; exercise the swap
        except Exception as e:  # noqa: BLE001 — asserted empty below
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(k,)) for k in range(3)]
    ts.append(threading.Thread(target=compactor))
    for t in ts:
        t.start()
    for t in ts[:3]:
        t.join()
    stop.set()
    ts[3].join()
    assert errs == []
    vs = lockcheck.take_violations()
    assert vs == [], "\n\n".join(v.describe() for v in vs)
    live = [r.seq for r in wal.records(1)]
    assert sorted(live + sorted(aborted)) == list(range(1, 151))
    wal.close()
    reopened = WriteAheadLog(wal.path)
    assert [r.seq for r in reopened.records(1)] == live  # clean recovery
    reopened.close()


# -- fault-injection seam -----------------------------------------------------


def test_fault_spec_nth_firing_deterministic():
    fi = FaultInjector.from_spec("forward/g1:drop@3")
    # Hits 1 and 2 pass, 3 fires, 4+ pass; other keys never match.
    fi.hit("forward", key="g0")
    fi.hit("forward", key="g1")
    fi.hit("forward", key="g1")
    with pytest.raises(FaultError):
        fi.hit("forward", key="g1")
    fi.hit("forward", key="g1")


def test_fault_spec_error_and_delay_and_multi():
    fi = FaultInjector.from_spec("forward:error=429@1; wal.append:delay=1@1")
    with pytest.raises(InjectedStatus) as e:
        fi.hit("forward", key="anything")
    assert e.value.status == 429
    t0 = time.perf_counter()
    fi.hit("wal.append")
    assert time.perf_counter() - t0 >= 0.001


def test_fault_spec_seeded_probability_is_deterministic():
    decisions = []
    for _ in range(2):
        fi = FaultInjector.from_spec("seed=7; forward:drop~0.3")
        run = []
        for _ in range(50):
            try:
                fi.hit("forward")
                run.append(False)
            except FaultError:
                run.append(True)
        decisions.append(run)
    assert decisions[0] == decisions[1]  # same seed, same spec, same faults
    assert any(decisions[0]) and not all(decisions[0])


def test_fault_spec_from_env_and_bad_specs():
    assert FaultInjector.from_env({}) is None
    fi = FaultInjector.from_env({"PILOSA_TPU_FAULT_SPEC": "forward:drop@1"})
    with pytest.raises(FaultError):
        fi.hit("forward")
    with pytest.raises(ValueError):
        FaultInjector.from_spec("forward")  # no action
    with pytest.raises(ValueError):
        FaultInjector.from_spec("forward:frobnicate")


# -- applied-sequence tracking ------------------------------------------------


def test_applied_seq_persists_and_is_monotonic(tmp_path):
    path = str(tmp_path / "applied_seq")
    a = AppliedSeq(path)
    assert a.value == 0
    a.note(5)
    a.note(3)  # regressions ignored
    assert a.value == 5
    b = AppliedSeq(path)  # a restarted group resumes from disk
    assert b.value == 5


def test_note_applied_header_rules():
    a = AppliedSeq()
    note_applied_from_headers(a, {"x-pilosa-write-seq": "4"}, 200)
    assert a.value == 4
    note_applied_from_headers(a, {"x-pilosa-write-seq": "5"}, 429)  # shed
    note_applied_from_headers(a, {"x-pilosa-write-seq": "6"}, 503)  # fault
    assert a.value == 4  # load-dependent answers stay replayable
    note_applied_from_headers(a, {"x-pilosa-write-seq": "7"}, 409)
    assert a.value == 7  # deterministic 4xx advances (replay would re-answer it)
    note_applied_from_headers(a, {}, 200)  # no header: untouched
    note_applied_from_headers(a, {"x-pilosa-write-seq": "junk"}, 200)
    assert a.value == 7
    # A shed expressed as a <500 status carrying Retry-After must not
    # advance the mark either — same predicate as the router fan-out.
    note_applied_from_headers(a, {"x-pilosa-write-seq": "8"}, 200,
                              retry_after="0.250")
    assert a.value == 7


def test_write_not_applied_shared_predicate():
    """ONE rule for 'did the write land?' across the fan-out, the
    replay, and the group-side bookkeeping: 429, any 5xx, or any
    answer carrying Retry-After is NOT applied; 2xx and deterministic
    4xx are."""
    assert write_not_applied(429, None)
    assert write_not_applied(500, None)
    assert write_not_applied(503, "1.000")
    assert write_not_applied(200, "0.250")  # shed-shaped 2xx
    assert write_not_applied(409, "0.250")  # shed-shaped 4xx
    assert not write_not_applied(200, None)
    assert not write_not_applied(204, "")
    assert not write_not_applied(400, None)
    assert not write_not_applied(404, None)
    assert not write_not_applied(409, None)


# -- three-group rig (real HTTP, restartable groups) --------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Rig3:
    """Three in-process group Servers on FIXED ports (so a restarted
    group keeps its address) + a router in front."""

    def __init__(self, tmp, wal=None, faults=None, probe_interval_s=0.05,
                 **router_kw):
        self.tmp = tmp
        self.ports = [_free_port() for _ in range(3)]
        self.servers = [self._spawn(i, 1) for i in range(3)]
        self.stats = ExpvarStatsClient()
        self.router = ReplicaRouter(
            [f"g{i}=127.0.0.1:{p}" for i, p in enumerate(self.ports)],
            probe_interval_s=probe_interval_s, probe_max_interval_s=0.4,
            wal=wal, faults=faults, stats=self.stats, **router_kw,
        ).serve()
        self.base = f"http://127.0.0.1:{self.router.port}"

    def _spawn(self, i: int, epoch: int):
        from pilosa_tpu.server.server import Server

        cfg = Config(
            data_dir=f"{self.tmp}/g{i}", host=f"127.0.0.1:{self.ports[i]}",
            engine="numpy", stats="expvar", qcache_enabled=False,
            replica_group=f"g{i}@{epoch}",
        )
        srv = Server(cfg)
        srv.open()
        return srv

    def restart(self, i: int, epoch: int):
        """Re-incarnate group i on the same port + data dir (the
        already-closed/killed server is simply replaced)."""
        self.servers[i] = self._spawn(i, epoch)

    def req(self, method, path, body=None, headers=None, timeout=30):
        rq = urllib.request.Request(self.base + path, data=body, method=method)
        for k, v in (headers or {}).items():
            rq.add_header(k, v)
        try:
            with urllib.request.urlopen(rq, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def query(self, q, headers=None):
        return self.req("POST", "/index/i/query", q.encode(), headers)

    def direct_count(self, i, q='Count(Bitmap(rowID=1, frame="f"))'):
        rq = urllib.request.Request(
            f"http://127.0.0.1:{self.ports[i]}/index/i/query",
            data=q.encode(), method="POST",
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            return json.loads(resp.read())["results"][0]

    def status(self) -> dict:
        return json.loads(self.req("GET", "/replica/status")[1])

    def group_status(self, name: str) -> dict:
        return next(g for g in self.status()["groups"] if g["name"] == name)

    def seed(self):
        assert self.req("POST", "/index/i", b"{}")[0] == 200
        assert self.req("POST", "/index/i/frame/f", b"{}")[0] == 200

    def wait_ready(self, name: str, timeout=15.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            g = self.group_status(name)
            if g["healthy"] and g["caughtUp"]:
                return g
            time.sleep(0.05)
        raise AssertionError(f"group {name} never rejoined: {self.group_status(name)}")

    def close(self):
        self.router.close()
        for s in self.servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — already dead
                pass


@pytest.fixture
def rig3():
    with tempfile.TemporaryDirectory() as tmp:
        r = _Rig3(tmp)
        try:
            yield r
        finally:
            r.close()


def test_degraded_quorum_write_survives_dead_group_and_catchup(rig3):
    """THE acceptance scenario, end to end over real HTTP: 3 groups,
    one killed -> writes keep committing (no 503 storm); after restart
    the lagging group replays the WAL suffix, converges to identical
    results, and rejoins reads only once fully caught up."""
    rig3.seed()
    for c in range(5):
        st, _, hdrs = rig3.query(f'SetBit(rowID=1, frame="f", columnID={c})')
        assert st == 200 and hdrs.get(GROUP_HEADER) == "all"

    rig3.servers[2].close()  # the whole group dies
    # Writes KEEP COMMITTING on the degraded quorum (2/3): the very
    # first write discovers the death mid-fan-out and still commits.
    for c in range(5, 15):
        st, body, _ = rig3.query(f'SetBit(rowID=1, frame="f", columnID={c})')
        assert st == 200, (c, body)
    assert rig3.direct_count(0) == rig3.direct_count(1) == 15
    g2 = rig3.group_status("g2")
    assert not g2["healthy"] and g2["lag"] >= 10
    assert rig3.status()["quorate"] is True  # majority rule: still writable
    # Reads keep serving (and never route to the dead group).
    for _ in range(6):
        st, body, hdrs = rig3.query('Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200 and json.loads(body)["results"] == [15]
        assert hdrs.get(GROUP_HEADER, "").startswith(("g0", "g1"))

    routed_g2_before = rig3.stats.snapshot().get("replica.routed.g2", 0)
    rig3.restart(2, epoch=2)
    g2 = rig3.wait_ready("g2")
    # CONVERGENCE: the replayed suffix advanced g2 to the WAL head and
    # its query results are identical to its siblings'.
    assert g2["appliedSeq"] == rig3.status()["wal"]["lastSeq"]
    assert rig3.direct_count(2) == rig3.direct_count(0) == 15
    # Content-level convergence: the fragment block CHECKSUMS agree on
    # every group (generation counters are process-local tokens — the
    # checksums are the cross-process form of "identical state", and
    # identical applied sequences above prove the identical write
    # order that keeps per-group generation vectors in lockstep).
    blocks = []
    for i in range(3):
        rq = urllib.request.Request(
            f"http://127.0.0.1:{rig3.ports[i]}/fragment/blocks"
            "?index=i&frame=f&view=standard&slice=0"
        )
        with urllib.request.urlopen(rq, timeout=10) as resp:
            blocks.append(json.loads(resp.read())["blocks"])
    assert blocks[0] == blocks[1] == blocks[2] and blocks[0]
    snap = rig3.stats.snapshot()
    assert snap.get("replica.replayed", 0) >= 10
    assert snap.get("replica.epoch_bump", 0) >= 1  # g2@1 -> g2@2 observed
    # No read routed to g2 while it was down/lagging; it serves again
    # only now — and correctly.
    assert snap.get("replica.routed.g2", 0) == routed_g2_before
    served = set()
    for _ in range(9):
        st, body, hdrs = rig3.query('Count(Bitmap(rowID=1, frame="f"))')
        assert st == 200 and json.loads(body)["results"] == [15]
        served.add(hdrs.get(GROUP_HEADER, "").split("@")[0])
    assert "g2" in served


def test_crash_mid_fanout_seeded_fault_ordering():
    """The seeded fault spec reproduces a crash-mid-fan-out ordering
    exactly: the Nth forward to g1 drops, the write still commits on
    the majority, and catch-up re-converges g1 — same spec, same
    interleaving, every run."""
    with tempfile.TemporaryDirectory() as tmp:
        # seed()+2 SetBits = 4 forwards per group; the 5th forward to g1
        # is the 3rd SetBit — it fails there and only there.
        faults = FaultInjector.from_spec("forward/g1:drop@5")
        rig = _Rig3(tmp, faults=faults)
        try:
            rig.seed()
            assert rig.query('SetBit(rowID=1, frame="f", columnID=0)')[0] == 200
            assert rig.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
            # The injected crash: g1's forward drops mid-fan-out.  The
            # write COMMITS anyway (g0 + g2 = majority).
            st, body, hdrs = rig.query('SetBit(rowID=1, frame="f", columnID=2)')
            assert st == 200, body
            assert rig.direct_count(0) == rig.direct_count(2) == 3
            assert rig.direct_count(1) == 2  # g1 missed exactly that write
            assert rig.stats.snapshot().get("replica.write_error", 0) == 1
            # Catch-up replays the missed record (the fault was one-shot)
            # and g1 converges.
            rig.wait_ready("g1")
            assert rig.direct_count(1) == 3
            assert rig.stats.snapshot().get("replica.replayed", 0) >= 1
        finally:
            rig.close()


def test_shed_after_commit_commits_on_majority(rig3, monkeypatch):
    """3-group upgrade of the PR-6 shed rule: a group shedding AFTER a
    sibling committed no longer fails the write — the majority commits,
    the shedding group becomes a laggard and is replayed back in."""
    rig3.seed()
    real = rig3.router._forward
    g1 = rig3.router.groups[1]
    shed = (
        429, "application/json",
        json.dumps({"error": "shed"}).encode(), {"Retry-After": "0.250"},
    )

    def shed_g1_writes(g, method, path_qs, body, headers, **kw):
        if g is g1 and b"SetBit" in body:
            return shed
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig3.router, "_forward", shed_g1_writes)
    st, body, hdrs = rig3.query('SetBit(rowID=1, frame="f", columnID=2)')
    assert st == 200 and hdrs.get(GROUP_HEADER) == "all"  # committed: 2/3
    assert rig3.direct_count(0) == rig3.direct_count(2) == 1
    assert rig3.direct_count(1) == 0
    assert not g1.healthy and not g1.caught_up  # demoted to laggard
    monkeypatch.setattr(rig3.router, "_forward", real)
    rig3.wait_ready("g1")
    assert rig3.direct_count(1) == 1  # the shed write arrived by replay


def test_shed_before_any_commit_aborts_the_record(rig3, monkeypatch):
    """A shed at the FIRST group still passes the 429 through verbatim
    — and the WAL record is tombstoned, so no later replay can deliver
    a write no live group holds."""
    rig3.seed()
    real = rig3.router._forward
    g0 = rig3.router.groups[0]
    shed = (
        429, "application/json",
        json.dumps({"error": "shed"}).encode(), {"Retry-After": "0.250"},
    )

    def shed_g0(g, method, path_qs, body, headers, **kw):
        if g is g0 and b"SetBit" in body:
            return shed
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig3.router, "_forward", shed_g0)
    st, _, hdrs = rig3.query('SetBit(rowID=1, frame="f", columnID=2)')
    assert st == 429 and hdrs.get("Retry-After") == "0.250"
    aborted_seq = rig3.router.wal.last_seq
    assert all(r.seq != aborted_seq for r in rig3.router.wal.records(1))
    assert all(g.healthy for g in rig3.router.groups)  # loaded, not broken
    assert rig3.stats.snapshot().get("replica.write_shed", 0) == 1
    monkeypatch.setattr(rig3.router, "_forward", real)
    # A group that now goes down and comes back replays the suffix —
    # which must NOT contain the aborted write.
    rig3.servers[2].close()
    assert rig3.query('SetBit(rowID=1, frame="f", columnID=3)')[0] == 200
    rig3.restart(2, epoch=2)
    rig3.wait_ready("g2")
    assert rig3.direct_count(2) == rig3.direct_count(0) == 1  # columnID=3 only


def test_transport_failure_keeps_record_replayable(rig3, monkeypatch):
    """A transport OSError proves NOTHING about application — the
    socket can die AFTER the group applied the write.  When every
    group fails ambiguously the record must stay LIVE (502, no
    tombstone) so catch-up re-delivers it; a tombstone here could hide
    a write one group actually holds, leaving permanent cross-group
    divergence."""
    rig3.seed()
    real = rig3.router._forward

    def die_on_live_setbit(g, method, path_qs, body, headers, **kw):
        if b"SetBit" in body and REPLAY_HEADER not in headers:
            raise OSError("connection reset mid-exchange")
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig3.router, "_forward", die_on_live_setbit)
    st, body, _ = rig3.query('SetBit(rowID=1, frame="f", columnID=1)')
    assert st == 502 and "partially applied" in json.loads(body)["error"]
    seq = rig3.router.wal.last_seq
    assert [r.seq for r in rig3.router.wal.records(seq)] == [seq]  # LIVE
    # Every group was demoted; with the record live, catch-up delivers
    # the write to ALL of them — at-least-once, never lost.
    monkeypatch.setattr(rig3.router, "_forward", real)
    for i in range(3):
        rig3.wait_ready(f"g{i}")
    assert (rig3.direct_count(0) == rig3.direct_count(1)
            == rig3.direct_count(2) == 1)


def test_shed_after_transport_failure_does_not_abort(rig3, monkeypatch):
    """THE divergence ordering: g0 APPLIES the write but its socket
    dies before the answer; g1/g2 then shed.  Tombstoning on the shed
    (applied==0 from the router's view) would hide the write g0 holds
    — replay could never deliver it to g1/g2.  The record must stay
    live and converge everyone."""
    rig3.seed()
    real = rig3.router._forward
    g0 = rig3.router.groups[0]
    shed = (
        429, "application/json",
        json.dumps({"error": "shed"}).encode(), {"Retry-After": "0.250"},
    )

    def apply_then_die_then_shed(g, method, path_qs, body, headers, **kw):
        if b"SetBit" in body and REPLAY_HEADER not in headers:
            if g is g0:
                real(g, method, path_qs, body, headers, **kw)  # g0 APPLIED
                raise OSError("reset after apply")
            return shed
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig3.router, "_forward", apply_then_die_then_shed)
    st, body, _ = rig3.query('SetBit(rowID=1, frame="f", columnID=1)')
    assert st == 502  # ambiguous — NOT the shed passthrough, NOT an abort
    seq = rig3.router.wal.last_seq
    assert [r.seq for r in rig3.router.wal.records(seq)] == [seq]  # LIVE
    assert rig3.direct_count(0) == 1  # g0 really does hold the write
    monkeypatch.setattr(rig3.router, "_forward", real)
    for i in range(3):
        rig3.wait_ready(f"g{i}")
    # Replay delivered g0's write to the siblings: no divergence.
    assert (rig3.direct_count(0) == rig3.direct_count(1)
            == rig3.direct_count(2) == 1)


def test_wal_error_injection_refuses_write(rig3, monkeypatch):
    """An injected WAL append failure refuses the write 503 BEFORE any
    group is touched (durability-first ordering)."""
    rig3.seed()

    def boom(*a, **kw):
        raise OSError("injected wal failure")

    monkeypatch.setattr(rig3.router.wal, "append", boom)
    before = [rig3.direct_count(i, 'Count(Bitmap(rowID=9, frame="f"))') for i in range(3)]
    st, body, hdrs = rig3.query('SetBit(rowID=9, frame="f", columnID=1)')
    assert st == 503 and "write log" in json.loads(body)["error"]
    assert "Retry-After" in hdrs
    after = [rig3.direct_count(i, 'Count(Bitmap(rowID=9, frame="f"))') for i in range(3)]
    assert before == after  # no group saw the refused write
    assert rig3.stats.snapshot().get("replica.wal_error", 0) == 1


def test_router_restart_recovers_durable_wal(tmp_path):
    """A router restarted over its durable WAL resumes the sequence
    space (no seq reuse = no misattributed applied marks) and keeps
    serving writes to the same groups."""
    wal_path = str(tmp_path / "router.wal")
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig3(tmp, wal=WriteAheadLog(wal_path))
        try:
            rig.seed()
            for c in range(3):
                assert rig.query(f'SetBit(rowID=1, frame="f", columnID={c})')[0] == 200
            seq_before = rig.router.wal.last_seq
            assert seq_before == 5  # 2 schema + 3 data writes
            rig.router.close()
            # New router, same log, same groups (a crashed router's
            # replacement): the sequence space continues.
            rig.router = ReplicaRouter(
                [f"g{i}=127.0.0.1:{p}" for i, p in enumerate(rig.ports)],
                probe_interval_s=0.05, wal=WriteAheadLog(wal_path),
                stats=rig.stats,
            ).serve()
            rig.base = f"http://127.0.0.1:{rig.router.port}"
            assert rig.router.wal.last_seq == seq_before
            # A restarted router TRUSTS NOTHING it cannot verify: every
            # group starts OUT of the rotation until the first probe
            # reads its persisted appliedSeq and replays any missed
            # suffix — only then does it serve again.
            assert all(not g.caught_up for g in rig.router.groups)
            for i in range(3):
                rig.wait_ready(f"g{i}")
            st, _, _ = rig.query('SetBit(rowID=1, frame="f", columnID=7)')
            assert st == 200
            assert rig.router.wal.last_seq == seq_before + 1
            assert rig.direct_count(0) == rig.direct_count(2) == 4
        finally:
            rig.close()


def test_router_restart_replays_missed_suffix_to_laggard(tmp_path):
    """A group that was LAGGING when the router died must not be
    readmitted at face value by the replacement router: the first
    probe reads its persisted appliedSeq authoritatively, replays the
    suffix the dead router never delivered, and only then lets it
    serve reads — otherwise the group silently serves reads that miss
    committed writes forever."""
    wal_path = str(tmp_path / "router.wal")
    with tempfile.TemporaryDirectory() as tmp:
        rig = _Rig3(tmp, wal=WriteAheadLog(wal_path))
        try:
            rig.seed()
            assert rig.query('SetBit(rowID=1, frame="f", columnID=0)')[0] == 200
            rig.servers[2].close()  # g2 dies...
            for c in range(1, 4):  # ...and misses these three commits
                assert rig.query(
                    f'SetBit(rowID=1, frame="f", columnID={c})'
                )[0] == 200
            assert rig.direct_count(0) == 4
            rig.router.close()  # ...and then the ROUTER dies too
            rig.restart(2, epoch=2)  # g2 returns, still 3 writes behind
            assert rig.direct_count(2) == 1
            rig.router = ReplicaRouter(
                [f"g{i}=127.0.0.1:{p}" for i, p in enumerate(rig.ports)],
                probe_interval_s=0.05, wal=WriteAheadLog(wal_path),
                stats=rig.stats,
            ).serve()
            rig.base = f"http://127.0.0.1:{rig.router.port}"
            g2 = rig.wait_ready("g2")
            # The new router REPLAYED the suffix its predecessor never
            # delivered — it did not just take the group's currency on
            # faith.
            assert g2["appliedSeq"] == rig.router.wal.last_seq
            assert rig.direct_count(2) == 4
            assert rig.stats.snapshot().get("replica.replayed", 0) >= 3
            # Every routed read now sees all four committed writes —
            # read-your-writes holds across the router crash.
            for _ in range(6):
                st, body, _ = rig.query('Count(Bitmap(rowID=1, frame="f"))')
                assert st == 200 and json.loads(body)["results"] == [4]
        finally:
            rig.close()


def test_laggard_past_wal_bound_goes_stale(tmp_path):
    """A dead group whose backlog would pin the WAL past wal-max-bytes
    is declared STALE: the log compacts past it (bounded backlog), so
    replay alone can never rescue it — once it comes back alive, the
    AUTOMATED RESYNC (PR 9) streams it the donor's fragments and it
    rejoins converged (PR 7 parked it for an operator here)."""
    with tempfile.TemporaryDirectory() as tmp:
        wal = WriteAheadLog(None, max_bytes=4096)
        rig = _Rig3(tmp, wal=wal)
        try:
            rig.seed()
            rig.servers[2].close()
            # Big committed writes grow the dead group's backlog past
            # the bound (the compaction floor is 64 KiB).
            big = " ".join(
                f'SetBit(rowID=1, frame="f", columnID={c})' for c in range(420)
            )
            for _ in range(40):
                assert rig.query(big)[0] == 200
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if rig.group_status("g2")["stale"]:
                    break
                assert rig.query(big)[0] == 200
            g2 = rig.group_status("g2")
            assert g2["stale"] is True
            assert rig.stats.snapshot().get("replica.stale.g2", 0) == 1
            # The log actually compacted past the laggard (0 = fully
            # drained: every retained record was applied by the
            # remaining groups).
            first = rig.router.wal.first_seq
            assert first == 0 or first > g2["appliedSeq"]
            assert rig.router.wal.last_seq > g2["appliedSeq"]
            assert rig.router.wal.size_bytes <= 4096
            # A stale group cannot rejoin by replay (the records are
            # gone from the log) — the automated resync brings it back:
            # digest diff against a donor, fragment stream, seed,
            # catch-up.  Zero operator action.
            rig.restart(2, epoch=2)
            g2 = rig.wait_ready("g2")
            assert g2["stale"] is False
            snap = rig.stats.snapshot()
            assert snap.get("replica.resync.g2", 0) >= 1
            assert rig.direct_count(2) == rig.direct_count(0)
            # And the majority keeps serving writes throughout.
            assert rig.query('SetBit(rowID=2, frame="f", columnID=1)')[0] == 200
        finally:
            rig.close()


def test_replica_status_reports_lag_and_wal(rig3):
    rig3.seed()
    assert rig3.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
    st = rig3.status()
    assert st["quorum"] == 2 and st["quorate"] is True
    assert st["wal"]["lastSeq"] == 3 and st["wal"]["durable"] is False
    for g in st["groups"]:
        assert g["appliedSeq"] == 3 and g["lag"] == 0 and g["caughtUp"] is True
    snap = rig3.stats.snapshot()
    assert snap.get("replica.wal_bytes", 0) > 0
    assert all(snap.get(f"replica.lag.g{i}") == 0 for i in range(3))


def test_replayed_write_trace_root_tagged(rig3):
    """A catch-up replay carries X-Pilosa-Replay; a (forced) trace on
    the group tags its root replay=true so /debug/traces separates
    replay load from live load."""
    rig3.seed()
    port = rig3.ports[0]

    def direct(headers):
        rq = urllib.request.Request(
            f"http://127.0.0.1:{port}/index/i/query",
            data=b'SetBit(rowID=3, frame="f", columnID=1)', method="POST",
        )
        for k, v in headers.items():
            rq.add_header(k, v)
        with urllib.request.urlopen(rq, timeout=10) as resp:
            return resp.status

    assert direct({"X-Pilosa-Trace": "1", "X-Pilosa-Replay": "1",
                   "X-Pilosa-Write-Seq": "99"}) == 200
    rq = urllib.request.Request(f"http://127.0.0.1:{port}/debug/traces")
    with urllib.request.urlopen(rq, timeout=10) as resp:
        traces = json.loads(resp.read())["traces"]
    root = traces[0]["spans"]
    assert root["tags"].get("replay") is True
    # And the header advanced the group's applied mark (reported back).
    assert rig3.servers[0].applied_seq.value == 99


def test_group_reports_applied_seq_and_persists(rig3):
    """Every group response carries X-Pilosa-Applied-Seq; the mark is
    persisted so a restarted group resumes from it."""
    rig3.seed()
    assert rig3.query('SetBit(rowID=1, frame="f", columnID=1)')[0] == 200
    rq = urllib.request.Request(f"http://127.0.0.1:{rig3.ports[1]}/version")
    with urllib.request.urlopen(rq, timeout=10) as resp:
        assert resp.headers.get(APPLIED_SEQ_HEADER) == "3"
    rq = urllib.request.Request(f"http://127.0.0.1:{rig3.ports[1]}/replica/health")
    with urllib.request.urlopen(rq, timeout=10) as resp:
        assert json.loads(resp.read())["appliedSeq"] == 3
    rig3.servers[1].close()
    rig3.restart(1, epoch=2)
    assert rig3.servers[1].applied_seq.value == 3  # reloaded from disk


def test_catchup_epoch_guard_aborts_on_restart_mid_replay(rig3, monkeypatch):
    """A replay response reporting a DIFFERENT group epoch aborts the
    catch-up round: a restarted incarnation must never absorb a stream
    paced against its predecessor's applied state — the next probe
    reads the fresh incarnation's mark and starts over."""
    rig3.seed()
    g2 = rig3.router.groups[2]
    rec = rig3.router.wal.records(1)[0]

    def bumped_epoch(g, method, path, body, headers, **kw):
        return 200, "application/json", b"{}", {GROUP_HEADER: "g2@99"}

    monkeypatch.setattr(rig3.router, "_forward", bumped_epoch)
    before = g2.applied_seq
    assert rig3.router.catchup._replay_one(g2, rec, start_epoch="g2@1") is False
    assert g2.applied_seq == before  # the stale-stream record never counted
    assert rig3.stats.snapshot().get("replica.catchup_abort", 0) == 1

    def same_epoch(g, method, path, body, headers, **kw):
        return 200, "application/json", b"{}", {GROUP_HEADER: "g2@1"}

    monkeypatch.setattr(rig3.router, "_forward", same_epoch)
    assert rig3.router.catchup._replay_one(g2, rec, start_epoch="g2@1") is True
    assert g2.applied_seq >= rec.seq


def test_catchup_locked_drain_is_deadline_bounded(rig3, monkeypatch):
    """The final drain holds the router's SEQUENCER lock: a group that
    turns slow mid-drain must abort the round quickly (it keeps its
    applied_seq progress; the next probe retries) instead of pinning
    the lock for up to drain_batch x socket-timeout and stalling every
    write cluster-wide."""
    rig3.seed()
    rig3.router.catchup.locked_drain_s = 0.15
    rig3.servers[2].close()
    for c in range(3):
        assert rig3.query(f'SetBit(rowID=1, frame="f", columnID={c})')[0] == 200
    rig3.restart(2, epoch=2)
    real = rig3.router._forward

    def crawling_replay(g, method, path_qs, body, headers, **kw):
        if headers.get(REPLAY_HEADER):
            time.sleep(0.5)  # far slower than the whole locked budget
        return real(g, method, path_qs, body, headers, **kw)

    monkeypatch.setattr(rig3.router, "_forward", crawling_replay)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if rig3.stats.snapshot().get("replica.catchup_stall", 0) >= 1:
            break
        time.sleep(0.02)
    assert rig3.stats.snapshot().get("replica.catchup_stall", 0) >= 1
    assert not rig3.router.groups[2].caught_up  # round aborted, stays out
    # Writes were never starved: the sequencer stays responsive while
    # the laggard crawls.
    t0 = time.monotonic()
    assert rig3.query('SetBit(rowID=1, frame="f", columnID=9)')[0] == 200
    assert time.monotonic() - t0 < 5.0
    # Un-throttle: the next probe round finishes the shorter remainder
    # (progress was kept) and the group rejoins for real.
    monkeypatch.setattr(rig3.router, "_forward", real)
    rig3.wait_ready("g2")
    assert rig3.direct_count(2) == rig3.direct_count(0) == 4


# -- probe backoff (satellite) ------------------------------------------------


def test_probe_backoff_doubles_jittered_and_caps():
    r = ReplicaRouter(["g0=127.0.0.1:1"], probe_interval_s=0.05,
                      probe_max_interval_s=0.4)
    g = r.groups[0]
    r._mark_unhealthy(g, "down")
    assert g.probe_delay == 0.05
    t0 = time.monotonic()
    delays = []
    for _ in range(6):
        r._backoff(g)
        delays.append(g.probe_delay)
        assert g.probe_at >= t0  # pushed into the future
    assert delays == [0.1, 0.2, 0.4, 0.4, 0.4, 0.4]  # doubled, then capped
    # Jitter: next-probe time is within [0.5x, 1.5x] of the delay.
    assert 0.4 * 0.5 - 1e-6 <= g.probe_at - time.monotonic() <= 0.4 * 1.5 + 0.1
    # Recovery resets the backoff to the base interval.
    r._mark_healthy(g)
    assert g.probe_delay == 0.05


def test_probe_once_backs_off_unreachable_group():
    r = ReplicaRouter(["g0=127.0.0.1:1"], probe_interval_s=0.05,
                      probe_max_interval_s=0.4)
    g = r.groups[0]
    r._mark_unhealthy(g, "down")
    g.probe_at = 0.0  # due immediately
    r._probe_once()
    assert not g.healthy and g.probe_delay == 0.1  # failed probe doubled it
    # Not due again until the backoff expires: _probe_once is a no-op.
    before = g.probe_delay
    r._probe_once()
    assert g.probe_delay == before


# -- client retry budget (satellite) ------------------------------------------


class _ShedThen200:
    """Tiny HTTP stub: sheds the first N requests with 429, then 200s."""

    def __init__(self, sheds: int, retry_after: str = "0.01"):
        self.requests = []
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                outer.requests.append(time.monotonic())
                if len(outer.requests) <= sheds:
                    body = b'{"error": "shed"}'
                    self.send_response(429)
                    self.send_header("Retry-After", retry_after)
                else:
                    from pilosa_tpu import wire

                    body = wire.encode_query_response(results=[1])
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-protobuf")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.host = f"127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_client_retry_budget_spends_until_success():
    from pilosa_tpu.server.client import Client

    stats = ExpvarStatsClient()
    stub = _ShedThen200(sheds=2)
    try:
        c = Client(stub.host, retry_budget=3, stats=stats)
        resp = c.execute_query("i", "Count(Bitmap(rowID=1))")
        assert resp["results"] == [{"n": 1}]
        assert len(stub.requests) == 3  # 2 sheds + the success
        assert stats.snapshot()["client.retries"] == 2
    finally:
        stub.close()


def test_client_retry_budget_exhausts_and_surfaces_shed():
    from pilosa_tpu.server.client import Client, ClientError

    stub = _ShedThen200(sheds=10)
    try:
        c = Client(stub.host, retry_budget=2)
        with pytest.raises(ClientError) as e:
            c.execute_query("i", "Count(Bitmap(rowID=1))")
        assert e.value.status == 429
        assert len(stub.requests) == 3  # 1 + budget of 2, never unbounded
    finally:
        stub.close()


def test_client_retry_budget_zero_disables():
    from pilosa_tpu.server.client import Client, ClientError

    stub = _ShedThen200(sheds=1)
    try:
        c = Client(stub.host, retry_budget=0)
        with pytest.raises(ClientError):
            c.execute_query("i", "Count(Bitmap(rowID=1))")
        assert len(stub.requests) == 1
    finally:
        stub.close()


def test_client_retry_deadline_aware():
    """A retry whose backoff cannot finish inside the remaining budget
    surfaces the shed instead of sleeping through the deadline."""
    from pilosa_tpu.qos import Deadline
    from pilosa_tpu.server.client import Client, ClientError

    stub = _ShedThen200(sheds=10, retry_after="1.5")
    try:
        c = Client(stub.host, retry_budget=5)
        t0 = time.monotonic()
        with pytest.raises(ClientError) as e:
            c.execute_query("i", "Count(Bitmap(rowID=1))", deadline=Deadline(200))
        assert e.value.status == 429
        assert time.monotonic() - t0 < 1.0  # never slept the 1.5s hint
        assert len(stub.requests) == 1
    finally:
        stub.close()


def test_client_retry_decorrelated_jitter_bounds():
    """Backoff waits honor the Retry-After floor and the cap."""
    from pilosa_tpu.server.client import Client

    stub = _ShedThen200(sheds=2, retry_after="0.05")
    try:
        c = Client(stub.host, retry_budget=2)
        c.execute_query("i", "Count(Bitmap(rowID=1))")
        gaps = [b - a for a, b in zip(stub.requests, stub.requests[1:])]
        assert all(g >= 0.04 for g in gaps)  # the peer's floor held
        assert all(g <= 2.5 for g in gaps)  # RETRY_AFTER_CAP_S bound
    finally:
        stub.close()


# -- config / CLI promotion ---------------------------------------------------


def test_config_recovery_promotion(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        "[replica]\n"
        'probe-interval = "2s"\n'
        'probe-max-interval = "45s"\n'
        f'wal-dir = "{tmp_path}/wal"\n'
        "wal-max-bytes = 1024\n"
        "\n"
        "[client]\n"
        "retry-budget = 7\n"
    )
    cfg = Config.from_toml(str(toml))
    assert cfg.replica_probe_interval == 2.0
    assert cfg.replica_probe_max_interval == 45.0
    assert cfg.replica_wal_dir == f"{tmp_path}/wal"
    assert cfg.replica_wal_max_bytes == 1024
    assert cfg.client_retry_budget == 7
    cfg.apply_env({
        "PILOSA_TPU_REPLICA_PROBE_INTERVAL": "0.5",
        "PILOSA_TPU_REPLICA_PROBE_MAX_INTERVAL": "9",
        "PILOSA_TPU_REPLICA_WAL_DIR": "/elsewhere",
        "PILOSA_TPU_REPLICA_WAL_MAX_BYTES": "2048",
        "PILOSA_TPU_CLIENT_RETRY_BUDGET": "1",
    })
    assert cfg.replica_probe_interval == 0.5
    assert cfg.replica_probe_max_interval == 9.0
    assert cfg.replica_wal_dir == "/elsewhere"
    assert cfg.replica_wal_max_bytes == 2048
    assert cfg.client_retry_budget == 1


def test_router_from_config_builds_durable_wal(tmp_path):
    from pilosa_tpu.replica import router_from_config

    cfg = Config(host="127.0.0.1:10101")
    cfg.replica_groups = ["127.0.0.1:1"]
    cfg.replica_router_port = 0
    cfg.replica_wal_dir = str(tmp_path / "wal")
    cfg.replica_wal_max_bytes = 12345
    cfg.replica_probe_interval = 0.25
    r = router_from_config(cfg)
    try:
        assert r.wal.path == os.path.join(str(tmp_path / "wal"), "router.wal")
        assert r.wal.max_bytes == 12345
        assert r.probe_interval_s == 0.25
        assert r.wal.append("POST", "/x", b"") == 1
        r.wal.close()
        r2 = router_from_config(cfg)
        assert r2.wal.last_seq == 1  # durable across router builds
        r2.wal.close()
    finally:
        pass


# -- lockstep applied-seq reporting (satellite of the tentpole) ---------------


def test_lockstep_front_end_reports_applied_seq(tmp_path):
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("g")
    idx.create_frame("f", FrameOptions())
    svc = LockstepService(
        h, control_addr=("127.0.0.1", 0), http_addr=("127.0.0.1", 0),
        group="g0", group_epoch=1,
    )
    threading.Thread(target=svc.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 10
    while svc._httpd is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc._httpd is not None
    base = f"http://{svc.http_addr[0]}:{svc.http_addr[1]}"
    try:
        rq = urllib.request.Request(
            base + "/index/g/query",
            data=b'SetBit(rowID=1, frame="f", columnID=1)', method="POST",
        )
        rq.add_header("X-Pilosa-Write-Seq", "11")
        with urllib.request.urlopen(rq, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers.get(APPLIED_SEQ_HEADER) == "11"
        with urllib.request.urlopen(base + "/replica/health", timeout=10) as resp:
            health = json.loads(resp.read())
        assert health["appliedSeq"] == 11
        # Persisted beside the holder: a restarted incarnation resumes.
        assert AppliedSeq(os.path.join(h.path, "applied_seq")).value == 11
        # A deterministic 400 (unknown frame — identical on every
        # group) advances the mark too: replaying it would only
        # re-answer the same error.
        rq = urllib.request.Request(
            base + "/index/g/query",
            data=b'SetBit(rowID=1, frame="nope", columnID=1)', method="POST",
        )
        rq.add_header("X-Pilosa-Write-Seq", "12")
        try:
            urllib.request.urlopen(rq, timeout=10)
            raise AssertionError("unknown frame should 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        assert svc.applied_seq.value == 12
    finally:
        svc.shutdown()
        h.close()
