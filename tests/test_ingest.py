"""Streaming columnar bulk-ingest front door (POST .../ingest).

Pins the wire contract (packed-uint64 framing, per-chunk CRC, resumable
offsets, idempotent re-sends), the apply semantics (batched set_bits,
inverse-view parity, executor dirty notes), the import-parity rule
(rank caches fresh IMMEDIATELY at completion — TopN right after a
streamed ingest must not be ranking-debounce stale), QoS classification,
and the lockstep front end's replicated translation of the same wire.
"""

import json
import tempfile
import threading
import time
import urllib.error
import urllib.request
import zlib

import numpy as np
import pytest

from pilosa_tpu import ingest
from pilosa_tpu.config import Config
from pilosa_tpu.qos import CLASS_WRITE, classify_request
from pilosa_tpu.server.client import Client, ClientError
from pilosa_tpu.server.server import Server


# -- wire format units -------------------------------------------------------

def test_packed_roundtrip():
    rows = np.array([1, 2, 3], dtype=np.uint64)
    cols = np.array([10, 20, 1 << 40], dtype=np.uint64)
    body = ingest.encode_packed(rows, cols)
    r2, c2 = ingest.decode_packed(body)
    assert r2.tolist() == rows.tolist() and c2.tolist() == cols.tolist()


@pytest.mark.parametrize(
    "body",
    [b"", b"PI64", b"XXXX" + b"\x00" * 20,
     ingest.encode_packed([1], [2])[:-1],  # truncated payload
     ingest.PACKED_MAGIC + (99).to_bytes(4, "little") + b"\x00" * 8],
)
def test_packed_malformed_rejected(body):
    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_packed(body)
    assert ei.value.status == 400


def test_arrow_unavailable_is_415():
    if ingest.arrow_available():
        pytest.skip("pyarrow importable: the 415 path is for hosts without it")
    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_arrow(b"whatever")
    assert ei.value.status == 415


@pytest.mark.skipif(not ingest.arrow_available(), reason="pyarrow unavailable")
def test_arrow_roundtrip():
    import pyarrow as pa

    rows = np.arange(5, dtype=np.uint64)
    cols = rows * 7
    table = pa.table({"row": rows, "col": cols})
    import io as _io

    sink = _io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    r2, c2 = ingest.decode_arrow(sink.getvalue())
    assert r2.tolist() == rows.tolist() and c2.tolist() == cols.tolist()


def _arrow_body(table):
    import io as _io

    import pyarrow as pa

    sink = _io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


@pytest.mark.skipif(not ingest.arrow_available(), reason="pyarrow unavailable")
def test_arrow_decode_producer_variety():
    """Real producers ship their whole table: extra columns are ignored,
    dictionary-encoded ids decode, multi-chunk columns concatenate, and
    any integer type casts to uint64."""
    import pyarrow as pa

    rows = np.array([1, 2, 3], dtype=np.uint64)
    cols = np.array([7, 8, 9], dtype=np.uint64)
    t = pa.table({
        "row": pa.array(rows.tolist(), type=pa.int16()).dictionary_encode(),
        "col": pa.chunked_array([cols[:2], cols[2:]]),
        "label": ["a", "b", "c"],  # extra column: ignored
    })
    r2, c2 = ingest.decode_arrow(_arrow_body(t))
    assert r2.tolist() == rows.tolist() and c2.tolist() == cols.tolist()
    assert r2.dtype == np.uint64 and c2.dtype == np.uint64


@pytest.mark.skipif(not ingest.arrow_available(), reason="pyarrow unavailable")
def test_arrow_decode_pointed_400s():
    """Schema mistakes answer pointed 400s naming the column — not a
    bare 'bad arrow chunk: KeyError' at 100M rows."""
    import pyarrow as pa

    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_arrow(_arrow_body(pa.table({"row": [1, 2]})))
    assert ei.value.status == 400 and "'col'" in str(ei.value)
    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_arrow(_arrow_body(
            pa.table({"row": [1.5, 2.5], "col": [1, 2]})
        ))
    assert ei.value.status == 400 and "'row'" in str(ei.value)
    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_arrow(_arrow_body(
            pa.table({"row": [-1], "col": [2]})
        ))
    assert ei.value.status == 400
    with pytest.raises(ingest.IngestError) as ei:
        ingest.decode_arrow(b"\x00not arrow\x00")
    assert ei.value.status == 400


def test_ingest_route_classifies_as_write():
    assert classify_request("POST", "/index/i/frame/f/ingest", b"") == CLASS_WRITE


# -- StreamIngestor units ----------------------------------------------------

class _Sink:
    def __init__(self):
        self.chunks = []
        self.completed = []

    def apply(self, key, rows, cols, deadline):
        self.chunks.append((key, rows.tolist(), cols.tolist()))
        return len(rows)

    def complete(self, key):
        self.completed.append(key)


def _frames(rows, cols, per=4):
    return [
        ingest.encode_packed(rows[i : i + per], cols[i : i + per])
        for i in range(0, len(rows), per)
    ]


def _transfer(frames):
    total = sum(len(f) for f in frames)
    crc = 0
    for f in frames:
        crc = zlib.crc32(f, crc)
    return total, crc


def test_stream_resume_dup_gap_and_completion():
    sink = _Sink()
    ing = ingest.StreamIngestor(sink.apply, complete=sink.complete)
    rows = list(range(10))
    cols = [c * 3 for c in rows]
    frames = _frames(rows, cols)
    total, crc = _transfer(frames)
    key = ("i", "f")
    # probe before anything: staged 0
    assert ing.probe(key, total, crc) == {"staged": 0, "done": False}
    off = 0
    out = None
    for fb in frames[:-1]:
        out = ing.chunk(key, off, total, crc, fb, chunk_crc=zlib.crc32(fb))
        off += len(fb)
        assert out["staged"] == off and not out["done"]
    # duplicate re-send of the first chunk: idempotent ack, no re-apply
    n_applied = len(sink.chunks)
    dup = ing.chunk(key, 0, total, crc, frames[0])
    assert dup["staged"] == off and len(sink.chunks) == n_applied
    # gap: skipping past the frontier answers 409 with the frontier
    with pytest.raises(ingest.IngestError) as ei:
        ing.chunk(key, off + len(frames[-1]) + 4, total, crc, frames[-1])
    assert ei.value.status == 409 and ei.value.staged == off
    # resume probe mid-transfer
    assert ing.probe(key, total, crc)["staged"] == off
    # final chunk completes; completion hook fired once
    out = ing.chunk(key, off, total, crc, frames[-1], chunk_crc=zlib.crc32(frames[-1]))
    assert out["done"] and sink.completed == [key]
    # all pairs applied exactly once, in order
    seen = [p for _, rs, cs in sink.chunks for p in zip(rs, cs)]
    assert seen == list(zip(rows, cols))


def test_chunk_crc_mismatch_rejected_before_apply():
    sink = _Sink()
    ing = ingest.StreamIngestor(sink.apply)
    fb = ingest.encode_packed([1], [2])
    with pytest.raises(ingest.IngestError) as ei:
        ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb), fb,
                  chunk_crc=zlib.crc32(fb) ^ 1)
    assert ei.value.status == 400 and not sink.chunks
    # the offset did not advance: the SAME chunk retries cleanly
    out = ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb), fb,
                    chunk_crc=zlib.crc32(fb))
    assert out["done"] and len(sink.chunks) == 1


def test_payload_crc_mismatch_at_completion_surfaces():
    sink = _Sink()
    ing = ingest.StreamIngestor(sink.apply)
    fb = ingest.encode_packed([1], [2])
    with pytest.raises(ingest.IngestError) as ei:
        ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb) ^ 5, fb)
    assert ei.value.status == 409
    # transfer state dropped: a clean re-stream starts at 0
    assert ing.probe(("i", "f"), len(fb), zlib.crc32(fb))["staged"] == 0


def test_oversized_chunk_answers_413():
    ing = ingest.StreamIngestor(_Sink().apply, max_chunk_bytes=64)
    fb = ingest.encode_packed(list(range(32)), list(range(32)))
    with pytest.raises(ingest.IngestError) as ei:
        ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb), fb)
    assert ei.value.status == 413


def test_new_payload_restarts_transfer():
    sink = _Sink()
    ing = ingest.StreamIngestor(sink.apply)
    frames = _frames(list(range(8)), list(range(8)))
    total, crc = _transfer(frames)
    ing.chunk(("i", "f"), 0, total, crc, frames[0])
    # different (total, crc): old transfer dies, off must restart at 0
    frames2 = _frames([9], [9])
    t2, c2 = _transfer(frames2)
    out = ing.chunk(("i", "f"), 0, t2, c2, frames2[0])
    assert out["done"]


def test_failed_apply_keeps_chunk_retryable():
    calls = {"n": 0}

    def flaky(key, rows, cols, deadline):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient")
        return len(rows)

    ing = ingest.StreamIngestor(flaky)
    fb = ingest.encode_packed([1, 2], [3, 4])
    with pytest.raises(OSError):
        ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb), fb)
    out = ing.chunk(("i", "f"), 0, len(fb), zlib.crc32(fb), fb)
    assert out["done"] and calls["n"] == 2


# -- end to end over a real server ------------------------------------------

@pytest.fixture
def srv():
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(data_dir=d, host="127.0.0.1:0", engine="numpy",
                     stats="expvar", qcache_enabled=False)
        s = Server(cfg)
        s.open()
        try:
            c = Client(s.host)
            c.create_index("i")
            c.create_frame("i", "f")
            yield s, c
        finally:
            s.close()


def test_ingest_end_to_end(srv):
    s, c = srv
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 50, size=20000).astype(np.uint64)
    cols = rng.integers(0, 1 << 20, size=20000).astype(np.uint64)
    out = c.ingest_stream("i", "f", rows, cols, chunk_pairs=4096)
    assert out["done"] and out["ops"] == 20000
    r = c.execute_query("i", 'Count(Bitmap(rowID=7, frame="f"))')
    assert r["results"][0]["n"] == len(np.unique(cols[rows == 7]))
    # idempotent re-stream converges (router WAL replay shape)
    out2 = c.ingest_stream("i", "f", rows, cols, chunk_pairs=4096)
    assert out2["done"]
    assert c.execute_query("i", 'Count(Bitmap(rowID=7, frame="f"))')[
        "results"
    ][0]["n"] == len(np.unique(cols[rows == 7]))
    v = json.loads(
        urllib.request.urlopen(f"http://{s.host}/debug/vars").read()
    )
    assert v["ingest.completed"] >= 2 and v["ingest.ops"] >= 40000


def test_topn_fresh_immediately_after_ingest(srv):
    """Import-parity regression: the rank cache recalculates AT
    completion — a TopN on the very next request reflects the streamed
    rows, not the 10 s-debounced pre-ingest ranking."""
    s, c = srv
    # Pre-ingest state: row 1 leads.
    c.execute_query("i", "".join(
        f'SetBit(rowID=1, frame="f", columnID={k})' for k in range(5)
    ))
    r = c.execute_query("i", 'TopN(frame="f", n=1)')
    assert r["results"][0]["pairs"][0]["id"] == 1
    # Stream a NEW dominant row; TopN immediately after must lead with it.
    rows = np.full(500, 9, dtype=np.uint64)
    cols = np.arange(500, dtype=np.uint64)
    assert c.ingest_stream("i", "f", rows, cols)["done"]
    r = c.execute_query("i", 'TopN(frame="f", n=2)')
    pairs = r["results"][0]["pairs"]
    assert pairs[0] == {"id": 9, "count": 500}, pairs


def test_ingest_inverse_view_parity(srv):
    """Inverse-enabled frames get the transposed pairs, like import."""
    s, c = srv
    c.create_frame("i", "inv", {"inverseEnabled": True})
    assert c.ingest_stream("i", "inv", [3], [44])["done"]
    frag = s.holder.fragment("i", "inv", "inverse", 0)
    assert frag is not None and frag.row_count(44) == 1


def test_ingest_unknown_frame_404(srv):
    s, c = srv
    fb = ingest.encode_packed([1], [2])
    with pytest.raises(ClientError) as ei:
        c.ingest_chunk("i", "nope", 0, len(fb), zlib.crc32(fb), fb)
    assert ei.value.status == 404


def test_ingest_resume_after_interrupt(srv):
    """A sender killed mid-transfer probes and resumes from the staged
    frontier; only the missing suffix streams."""
    s, c = srv
    rows = np.arange(1000, dtype=np.uint64) % 10
    cols = np.arange(1000, dtype=np.uint64)
    frames = _frames(rows, cols, per=256)
    total, crc = _transfer(frames)
    st, out = c.ingest_chunk("i", "f", 0, total, crc, frames[0],
                             ccrc=zlib.crc32(frames[0]))
    assert st == 200 and out["staged"] == len(frames[0])
    # "restart": ingest_stream probes, skips chunk 0, streams the rest
    out = c.ingest_stream("i", "f", rows, cols, chunk_pairs=256)
    assert out["done"]
    r = c.execute_query("i", 'Count(Bitmap(rowID=3, frame="f"))')
    assert r["results"][0]["n"] == 100


def test_cli_ingest_streams_csv(srv, tmp_path, capsys):
    from pilosa_tpu.cli.main import main

    s, c = srv
    csv = tmp_path / "bits.csv"
    csv.write_text("".join(f"{r},{r * 7}\n" for r in range(200)))
    assert main([
        "ingest", "--host", s.host, "--index", "i", "--frame", "f",
        "--chunk-pairs", "64", str(csv),
    ]) == 0
    assert "streamed 200 bits" in capsys.readouterr().out
    r = c.execute_query("i", 'Count(Bitmap(rowID=5, frame="f"))')
    assert r["results"][0]["n"] == 1


def test_ingest_backpressure_never_sheds_reads():
    """Chunks classify as writes: a saturating ingest stream queues at
    the WRITE door while reads keep their own door — no read sheds."""
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(data_dir=d, host="127.0.0.1:0", engine="numpy",
                     stats="expvar", qcache_enabled=False)
        cfg.qos_write_depth = 1
        cfg.qos_read_depth = 8
        s = Server(cfg)
        s.open()
        try:
            c = Client(s.host)
            c.create_index("i")
            c.create_frame("i", "f")
            c.ingest_stream("i", "f", [1], [1])
            stop = [False]
            served = [0]

            def reader():
                while not stop[0]:
                    rq = urllib.request.Request(
                        f"http://{s.host}/index/i/query",
                        data=b'Count(Bitmap(rowID=1, frame="f"))',
                        method="POST",
                    )
                    with urllib.request.urlopen(rq, timeout=30) as resp:
                        resp.read()
                    served[0] += 1

            t = threading.Thread(target=reader, daemon=True)
            t.start()
            rng = np.random.default_rng(3)
            rows = rng.integers(0, 20, size=60000).astype(np.uint64)
            cols = rng.integers(0, 1 << 20, size=60000).astype(np.uint64)
            assert c.ingest_stream("i", "f", rows, cols, chunk_pairs=8192)["done"]
            stop[0] = True
            t.join(timeout=30)
            v = json.loads(
                urllib.request.urlopen(f"http://{s.host}/debug/vars").read()
            )
            assert int(v.get("qos.shed.read", 0)) == 0
            assert served[0] > 0
        finally:
            s.close()


# -- lockstep front end ------------------------------------------------------

def test_lockstep_front_end_ingest(tmp_path):
    """The lockstep front end serves the SAME ingest wire: chunks
    replay as batched SetBit bodies through the replicated total order
    and the completion recalc rides a reserved entry — TopN right after
    is fresh on the serving rank."""
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.service import LockstepService

    h = Holder(str(tmp_path / "d"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f", FrameOptions())
    svc = LockstepService(
        h, control_addr=("127.0.0.1", 0), http_addr=("127.0.0.1", 0)
    )
    threading.Thread(target=svc.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 10
    while svc._httpd is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc._httpd is not None
    base = f"http://{svc.http_addr[0]}:{svc.http_addr[1]}"
    try:
        rng = np.random.default_rng(4)
        rows = rng.integers(0, 10, size=3000).astype(np.uint64)
        cols = rng.integers(0, 1 << 20, size=3000).astype(np.uint64)
        frames = _frames(rows, cols, per=1024)
        total, crc = _transfer(frames)
        off = 0
        for fb in frames:
            rq = urllib.request.Request(
                f"{base}/index/i/frame/f/ingest?off={off}&total={total}"
                f"&crc={crc}&ccrc={zlib.crc32(fb)}",
                data=fb, method="POST",
            )
            with urllib.request.urlopen(rq, timeout=30) as resp:
                out = json.loads(resp.read())
            off += len(fb)
            assert out["staged"] == off
        assert out["done"]
        # served through the replicated executor: counts + fresh TopN
        rq = urllib.request.Request(
            f"{base}/index/i/query",
            data=b'Count(Bitmap(rowID=3, frame="f"))', method="POST",
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            got = json.loads(resp.read())["results"][0]
        assert got == len(np.unique(cols[rows == 3]))
        rq = urllib.request.Request(
            f"{base}/index/i/query", data=b'TopN(frame="f", n=1)', method="POST",
        )
        with urllib.request.urlopen(rq, timeout=30) as resp:
            pairs = json.loads(resp.read())["results"][0]
        uniq = {int(x): len(np.unique(cols[rows == x])) for x in np.unique(rows)}
        assert pairs[0]["count"] == max(uniq.values())
    finally:
        svc.shutdown()
        h.close()
