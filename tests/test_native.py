"""C++ native kernel tests: native results must equal the Python fallbacks
(the asm-vs-Go equivalence idiom, roaring/assembly_test.go analog)."""

import os

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.cluster import fnv1a64 as py_fnv64
from pilosa_tpu.roaring import OP_ADD, OP_REMOVE, _popcount_words, encode_op
from pilosa_tpu.wire import encode_varint

pytestmark = pytest.mark.skipif(not native.available(), reason="native lib unavailable")


def test_fnv1a64_matches_python():
    for data in (b"", b"a", b"foobar", bytes(range(256))):
        assert native.fnv1a64(data) == py_fnv64(data)


def test_varint_roundtrip_matches_python(rng):
    vals = np.concatenate(
        [
            rng.integers(0, 1 << 7, 100, dtype=np.uint64),
            rng.integers(0, 1 << 32, 100, dtype=np.uint64),
            rng.integers(0, 1 << 63, 100, dtype=np.uint64),
            np.array([0, 1, (1 << 64) - 1], dtype=np.uint64),
        ]
    )
    raw = native.varint_encode(vals)
    want = b"".join(encode_varint(int(v)) for v in vals.tolist())
    assert raw == want
    back = native.varint_decode(raw)
    np.testing.assert_array_equal(back, vals)


def test_varint_decode_rejects_truncation():
    raw = native.varint_encode(np.array([300], dtype=np.uint64))
    with pytest.raises(ValueError):
        native.varint_decode(raw[:-1])


def test_oplog_roundtrip_and_corruption(rng):
    types = rng.integers(0, 2, 50).astype(np.uint8)
    vals = rng.integers(0, 1 << 40, 50, dtype=np.uint64)
    raw = native.oplog_encode(types, vals)
    want = b"".join(encode_op(int(t), int(v)) for t, v in zip(types.tolist(), vals.tolist()))
    assert raw == want
    t2, v2 = native.oplog_decode(raw)
    np.testing.assert_array_equal(t2, types)
    np.testing.assert_array_equal(v2, vals)
    bad = bytearray(raw)
    bad[13 * 7 + 2] ^= 0xFF
    with pytest.raises(ValueError, match="op 7"):
        native.oplog_decode(bytes(bad))


def test_parse_csv():
    data = b"1,100\n2,200,1500000000\n\n3,5\n"
    rows, cols, ts = native.parse_csv(data)
    assert rows.tolist() == [1, 2, 3]
    assert cols.tolist() == [100, 200, 5]
    assert ts.tolist() == [0, 1500000000, 0]
    with pytest.raises(ValueError, match="line 2"):
        native.parse_csv(b"1,2\nnope\n")
    with pytest.raises(ValueError, match="line 1"):
        native.parse_csv(b"5\n")


def test_popcount_matches_lut(rng):
    words = rng.integers(0, 1 << 32, 10000, dtype=np.uint32)
    assert native.popcount_words(words) == _popcount_words(words)


def test_wire_large_packed_uses_native(rng):
    # encode via wire.Writer.packed (native path for >=64 values), decode both ways
    from pilosa_tpu import wire

    vals = rng.integers(0, 1 << 50, 1000, dtype=np.uint64).tolist()
    raw = wire.Writer().packed(1, vals).finish()
    fields = list(wire.iter_fields(raw))
    decoded = wire.decode_packed_uint64(fields[0][2])
    assert decoded == vals


@pytest.fixture
def force_fallback():
    """Temporarily disable the native lib so the pure-Python path runs."""
    lib, tried = native._lib, native._tried
    native._lib, native._tried = None, True
    yield
    native._lib, native._tried = lib, tried


@pytest.mark.parametrize(
    "data",
    [
        b"5,",          # empty column field
        b",7",          # empty row field
        b"1 2,3",       # interior space concatenating digits
        b"5,2,",        # empty timestamp -> 0
        b"5,2, ",       # blank timestamp -> 0
        b" 5 , 2 ",     # surrounding spaces ok
        b"5,2,9\r\n",   # CRLF
        b"5,2,x",       # junk timestamp
        b"-1,2",        # negative id
        b"3,4,  7 ",    # padded timestamp
        b"1,100\n2,200,1500000000\n\n3,5\n",
        b"1,2\n   \n3,4",                     # whitespace-only line skipped
        b"18446744073709551616,1",            # row overflows uint64
        b"1,18446744073709551616",            # col overflows uint64
        b"1,2,9223372036854775808",           # ts overflows int64
        b"18446744073709551615,2",            # max uint64 row ok
        b"1,2,3,4",                           # too many fields
        b"+1,2",                              # explicit sign rejected
        b"1_0,2",                             # underscore grouping rejected
        b"1,2,+3",                            # signed timestamp rejected
    ],
)
def test_parse_csv_native_matches_fallback(data, force_fallback):
    """Native and fallback must agree on accept/reject AND values —
    otherwise import behavior depends on whether the .so built."""
    def run():
        try:
            r, c, t = native.parse_csv(data)
            return ("ok", r.tolist(), c.tolist(), t.tolist())
        except ValueError:
            return ("err",)

    fallback = run()
    native._lib, native._tried = None, False  # re-enable native
    if not native.available():
        pytest.skip("native lib unavailable")
    assert run() == fallback


def test_varint_decode_rejects_overlong_both_paths(force_fallback):
    """A 10-byte varint encoding >= 2^64 must raise ValueError on both
    paths (not OverflowError, not silent truncation)."""
    overlong = bytes([0x80] * 9 + [0x7F]) * 7  # > native threshold
    with pytest.raises(ValueError):
        native.varint_decode(overlong)  # fallback path
    native._lib, native._tried = None, False
    if not native.available():
        pytest.skip("native lib unavailable")
    with pytest.raises(ValueError):
        native.varint_decode(overlong)  # native path


def test_varint_decode_max_uint64_both_paths(force_fallback):
    m = np.array([2**64 - 1] * 100, dtype=np.uint64)
    np.testing.assert_array_equal(native.varint_decode(native.varint_encode(m)), m)
    native._lib, native._tried = None, False
    if not native.available():
        pytest.skip("native lib unavailable")
    np.testing.assert_array_equal(native.varint_decode(native.varint_encode(m)), m)


def test_gram_counts_native():
    """pn_gram_counts answers all four pair ops via count identities and
    returns None (Python fallback) when a row id is absent."""
    from pilosa_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(8)
    R = 7
    # Symmetric PSD-ish gram with plausible count structure.
    bits = rng.integers(0, 2, size=(R, 64))
    gram = (bits @ bits.T).astype(np.int64)
    rows_sorted = np.array([2, 5, 9, 11, 20, 31, 40], dtype=np.int64)
    pos = np.array([3, 0, 6, 1, 4, 2, 5], dtype=np.int32)
    n = 40
    r1 = rows_sorted[rng.integers(0, R, size=n)].astype(np.int64)
    r2 = rows_sorted[rng.integers(0, R, size=n)].astype(np.int64)
    op_ids = rng.integers(0, 4, size=n).astype(np.uint8)
    got = native.gram_counts(op_ids, r1, r2, rows_sorted, pos, gram)
    assert got is not None
    id_pos = dict(zip(rows_sorted.tolist(), pos.tolist()))
    for i in range(n):
        p1, p2 = id_pos[int(r1[i])], id_pos[int(r2[i])]
        g, d1, d2 = gram[p1, p2], gram[p1, p1], gram[p2, p2]
        want = [g, d1 + d2 - g, d1 + d2 - 2 * g, d1 - g][op_ids[i]]
        assert got[i] == want, i
    # Unknown row id -> None (caller takes the Python path).
    r1_bad = r1.copy()
    r1_bad[5] = 999
    assert native.gram_counts(op_ids, r1_bad, r2, rows_sorted, pos, gram) is None


def test_array_add_logged(tmp_path):
    """Fused singleton add: insert + WAL record + write(2) in one call;
    the record bytes must match encode_op exactly (replay compatible)."""
    lib = native.load()
    wal = tmp_path / "wal"
    fd = os.open(str(wal), os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    buf = np.zeros(8, dtype=np.uint32)
    addr = buf.ctypes.data
    # Insert 3 values (one duplicate) with WAL.
    assert lib.pn_array_add_logged(addr, 0, 7, (5 << 16) | 7, fd) == 1
    assert lib.pn_array_add_logged(addr, 1, 3, (5 << 16) | 3, fd) == 2
    assert lib.pn_array_add_logged(addr, 2, 7, (5 << 16) | 7, fd) == -2  # dup
    assert buf[:2].tolist() == [3, 7]
    os.close(fd)
    want = encode_op(OP_ADD, (5 << 16) | 7) + encode_op(OP_ADD, (5 << 16) | 3)
    assert wal.read_bytes() == want
    # fd = -1: mutation without WAL (unlogged callers).
    assert lib.pn_array_add_logged(addr, 2, 1, 1, -1) == 3
    assert buf[:3].tolist() == [1, 3, 7]
    # Bad fd: declined atomically — no insert, no partial record.
    assert lib.pn_array_add_logged(addr, 3, 9, 9, 12345) == -3
    assert buf[:3].tolist() == [1, 3, 7]


def test_bitmap_add_fused_lane_matches_slow_path(tmp_path):
    """Bitmap.add through the fused lane equals the PILOSA_TPU_NO_NATIVE
    slow path: same container contents, same WAL bytes, same op_n."""
    from pilosa_tpu import roaring

    rng = np.random.default_rng(11)
    vals = rng.integers(0, 1 << 22, size=400).tolist()

    def run(native_on: bool):
        bm = roaring.Bitmap()
        path = tmp_path / ("fast" if native_on else "slow")
        w = open(path, "ab", buffering=0)
        bm.op_writer = w
        if not native_on:
            bm._op_fd = -2  # force the python slow path
        changed = [bm.add(v) for v in vals]
        w.close()
        return changed, sorted(bm.to_array().tolist()), bm.op_n, path.read_bytes()

    c1, v1, n1, wal1 = run(True)
    c2, v2, n2, wal2 = run(False)
    assert c1 == c2
    assert v1 == v2
    assert n1 == n2
    assert wal1 == wal2


def test_fused_lane_declines_buffered_writers(tmp_path):
    """A BUFFERED op_writer must keep every record in the Python write
    path: mixing the fused lane's raw write(2) with unflushed buffered
    records would reorder the WAL (replay corruption)."""
    from pilosa_tpu import roaring

    bm = roaring.Bitmap()
    path = tmp_path / "wal"
    w = open(path, "wb")  # buffered
    bm.op_writer = w
    assert bm.add(5)
    assert bm.remove(5)
    assert bm.add(5)
    w.close()
    recs = path.read_bytes()
    assert len(recs) == 39  # 3 records, in operation order
    assert [recs[i] for i in (0, 13, 26)] == [roaring.OP_ADD, roaring.OP_REMOVE, roaring.OP_ADD]


def test_match_pairs_accepts_count_bitmap_singles():
    """Count(Bitmap(...)) matches as the (r, r) AND pair — the C matcher
    and serve lane cover plain row counts in batched requests."""
    q = ('Count(Bitmap(rowID=3, frame="f")) '
         'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))')
    m = native.pql_match_pairs(q.encode())
    assert m is not None
    op_ids, frame_ids, key_ids, r1, r2 = m[0], m[1], m[2], m[3], m[4]
    assert op_ids.tolist() == [0, 0]
    assert list(zip(r1.tolist(), r2.tolist())) == [(3, 3), (1, 2)]
    # malformed single-leaf shapes still fall back
    assert native.pql_match_pairs(b'Count(Bitmap(rowID=3, frame="f") ') is None
    assert native.pql_match_pairs(b'Count(Bitmap(frame="f"))  Count(Bitmap(rowID=1))') is None
