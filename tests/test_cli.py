"""CLI tests (reference analog: cmd/*_test.go, ctl/*_test.go)."""

import json
import socket
import sys

import numpy as np
import pytest

from pilosa_tpu.cli.main import main
from pilosa_tpu.config import Config
from pilosa_tpu.server.client import Client
from pilosa_tpu.server.server import Server
from pilosa_tpu.pilosa import SLICE_WIDTH


@pytest.fixture
def srv(tmp_path):
    s = Server(Config(data_dir=str(tmp_path / "data"), host="127.0.0.1:0", engine="numpy"))
    s.open()
    c = Client(s.host)
    c.create_index("i")
    c.create_frame("i", "f")
    yield s
    s.close()


def test_config_command(capsys):
    assert main(["config"]) == 0
    out = capsys.readouterr().out
    assert "data-dir" in out and "[cluster]" in out


def test_config_env_precedence(capsys, monkeypatch):
    monkeypatch.setenv("PILOSA_HOST", "envhost:123")
    main(["config"])
    assert 'host = "envhost:123"' in capsys.readouterr().out


def test_server_command(tmp_path, capsys):
    assert main(["server", "--data-dir", str(tmp_path / "d"), "--host", "127.0.0.1:0", "--test-exit"]) == 0
    assert "serving on" in capsys.readouterr().out


def test_server_profile_cpu(tmp_path, capsys):
    """--profile.cpu writes a loadable pstats file (cmd/server.go:100)."""
    import pstats

    prof = tmp_path / "cpu.prof"
    assert main([
        "server", "--data-dir", str(tmp_path / "d"), "--host", "127.0.0.1:0",
        "--profile.cpu", str(prof), "--test-exit",
    ]) == 0
    assert "cpu profile written" in capsys.readouterr().out
    assert pstats.Stats(str(prof)).total_calls > 0


@pytest.mark.skipif(sys.version_info < (3, 12),
                    reason="process-wide cProfile needs 3.12 sys.monitoring")
def test_profile_captures_handler_threads():
    """The flag's pprof parity rests on 3.12 cProfile being process-wide
    (sys.monitoring): work on OTHER threads must land in the profile."""
    import cProfile
    import io
    import pstats
    import threading

    def handler_work():
        return sum(i * i for i in range(10_000))

    p = cProfile.Profile()
    p.enable()
    t = threading.Thread(target=handler_work)
    t.start()
    t.join()
    p.disable()
    buf = io.StringIO()
    pstats.Stats(p, stream=buf).print_stats("handler_work")
    assert "handler_work" in buf.getvalue()


def test_import_export_sort(tmp_path, srv, capsys):
    csv = tmp_path / "bits.csv"
    csv.write_text(f"2,{SLICE_WIDTH+5}\n1,10\n1,3\n")
    # sort pre-pass orders by slice then row
    assert main(["sort", str(csv)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert out == ["1,3", "1,10", f"2,{SLICE_WIDTH+5}"]

    assert main(["import", "--host", srv.host, "--index", "i", "--frame", "f", str(csv)]) == 0
    assert "imported 3 bits" in capsys.readouterr().out

    assert main(["export", "--host", srv.host, "--index", "i", "--frame", "f"]) == 0
    out = capsys.readouterr().out
    assert "1,3" in out and f"2,{SLICE_WIDTH+5}" in out


def test_backup_restore_roundtrip(tmp_path, srv, capsys):
    c = Client(srv.host)
    c.execute_query("i", 'SetBit(rowID=4, frame="f", columnID=9)')
    tar = tmp_path / "f.tar"
    assert main(["backup", "--host", srv.host, "--index", "i", "--frame", "f", "-o", str(tar)]) == 0
    c.create_frame("i", "g")
    assert main(["restore", "--host", srv.host, "--index", "i", "--frame", "g", "-i", str(tar)]) == 0
    resp = c.execute_query("i", 'Bitmap(rowID=4, frame="g")')
    assert resp["results"][0]["bitmap"]["bits"] == [9]


def test_bench_command(srv, capsys):
    assert main(["bench", "--host", srv.host, "--index", "i", "--frame", "f", "-n", "50"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["n"] == 50 and out["ops_per_sec"] > 0
    assert main(["bench", "--host", srv.host, "--index", "i", "--frame", "f", "-o", "bogus"]) == 1


def test_check_inspect(tmp_path, srv, capsys):
    c = Client(srv.host)
    c.execute_query("i", 'SetBit(rowID=1, frame="f", columnID=5)')
    frag_path = srv.data_dir + "/i/f/views/standard/fragments/0"
    assert main(["check", frag_path]) == 0
    assert "ok" in capsys.readouterr().out
    assert main(["inspect", "-v", frag_path]) == 0
    out = capsys.readouterr().out
    assert "containers" in out and "type=array" in out
    # corrupted file fails check
    bad = tmp_path / "bad"
    bad.write_bytes(b"\x00" * 32)
    assert main(["check", str(bad)]) == 1


def test_lockstep_command(tmp_path):
    """`pilosa-tpu lockstep` on two ranks: rank 0 serves HTTP, writes
    replicate through the control plane; SIGINT shuts both down."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    dirs = [str(tmp_path / f"d{i}") for i in range(2)]
    for d in dirs:  # identical replicated holder data per rank
        h = Holder(d)
        h.open()
        idx = h.create_index("g")
        idx.create_frame("f", FrameOptions())
        for s in range(2):
            idx.frame("f").set_bit("standard", 1, s * (1 << 20) + 3)
        h.close()

    coord, ctrl, http = free_port(), free_port(), free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = repo
    env["XLA_FLAGS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "lockstep",
             "--data-dir", dirs[pid], "--host", f"127.0.0.1:{http}",
             "--control", f"127.0.0.1:{ctrl}",
             "--coordinator", f"127.0.0.1:{coord}",
             "--num-processes", "2", "--process-id", str(pid),
             "--local-devices", "2"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            cwd=repo,
            env=env,
        )
        for pid in range(2)
    ]
    try:
        deadline = time.monotonic() + 120
        out = None
        while time.monotonic() < deadline:
            if any(p.poll() is not None for p in procs):
                pytest.fail("lockstep rank died at startup")
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{http}/index/g/query",
                    data=b'Count(Bitmap(rowID=1, frame="f"))',
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=10) as r:
                    out = json.loads(r.read())
                break
            except OSError:
                time.sleep(0.5)
        assert out == {"results": [2]}, out
        req = urllib.request.Request(
            f"http://127.0.0.1:{http}/index/g/query",
            data=b'SetBit(rowID=1, frame="f", columnID=9)',
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read()) == {"results": [True]}
        procs[0].send_signal(signal.SIGINT)
        for p in procs:
            assert p.wait(timeout=60) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
