"""Iterator tests (reference analog: iterator_test.go)."""

import numpy as np

from pilosa_tpu.iterator import (
    BufIterator,
    LimitIterator,
    RoaringIterator,
    SliceIterator,
    merge_iterators,
)
from pilosa_tpu.pilosa import SLICE_WIDTH
from pilosa_tpu.roaring import Bitmap


def drain(it):
    out = []
    while (p := it.next()) is not None:
        out.append(p)
    return out


def test_slice_iterator_orders_pairs():
    it = SliceIterator([2, 1, 1], [5, 9, 3])
    assert drain(it) == [(1, 3), (1, 9), (2, 5)]


def test_slice_iterator_seek():
    it = SliceIterator([0, 1, 2], [7, 7, 7])
    it.seek(1, 0)
    assert it.next() == (1, 7)
    it.seek(1, 8)  # past (1,7) -> lands on (2,7)
    assert it.next() == (2, 7)
    it.seek(5, 0)
    assert it.next() is None


def test_roaring_iterator_maps_positions():
    bm = Bitmap([3, SLICE_WIDTH + 4, 2 * SLICE_WIDTH])
    it = RoaringIterator(bm)
    assert drain(it) == [(0, 3), (1, 4), (2, 0)]
    it.seek(1, 0)
    assert it.next() == (1, 4)


def test_buf_iterator_unread_peek():
    it = BufIterator(SliceIterator([0, 0], [1, 2]))
    assert it.peek() == (0, 1)
    assert it.next() == (0, 1)
    it.unread((9, 9))
    assert it.next() == (9, 9)
    assert it.next() == (0, 2)
    assert it.next() is None


def test_limit_iterator_stops_past_max_row():
    it = LimitIterator(SliceIterator([0, 1, 2, 3], [0, 0, 0, 0]), max_row=1)
    assert drain(it) == [(0, 0), (1, 0)]


def test_merge_iterators_dedups():
    a = SliceIterator([0, 1], [1, 2])
    b = SliceIterator([0, 2], [1, 3])
    merged = merge_iterators([a, b])
    assert drain(merged) == [(0, 1), (1, 2), (2, 3)]


def test_buf_iterator_double_unread_raises():
    """Double unread without an intervening read is a programming error
    (iterator_test.go TestBufIterator_DoubleFillPanic analog)."""
    import pytest

    from pilosa_tpu.iterator import BufIterator, SliceIterator

    it = BufIterator(SliceIterator([1], [2]))
    p = it.next()
    it.unread(p)
    with pytest.raises(RuntimeError):
        it.unread(p)
