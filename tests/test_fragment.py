"""Fragment + cache tests (reference analog: fragment_test.go, cache tests)."""

import os

import numpy as np
import pytest

from pilosa_tpu import roaring
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.cache import LRUCache, Pair, RankCache, pairs_add, pairs_sorted
from pilosa_tpu.core.fragment import DEFAULT_MAX_OPN, Fragment, TopOptions
from pilosa_tpu.pilosa import SLICE_WIDTH


@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, cache_type="ranked")
    f.open()
    yield f
    f.close()


def reopen(f: Fragment) -> Fragment:
    f.close()
    g = Fragment(f.path, f.index, f.frame, f.view, f.slice, cache_type=f.cache_type)
    g.open()
    return g


def test_set_clear_contains(frag):
    assert frag.set_bit(120, 1)
    assert not frag.set_bit(120, 1)
    assert frag.contains(120, 1)
    assert frag.clear_bit(120, 1)
    assert not frag.contains(120, 1)
    assert not frag.clear_bit(120, 1)


def test_wal_persistence(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(3, 100)
    f.set_bit(3, 200)
    f.set_bit(4, 50)
    f.clear_bit(3, 200)
    g = reopen(f)
    assert g.contains(3, 100)
    assert not g.contains(3, 200)
    assert g.contains(4, 50)
    assert g.row_count(3) == 1
    g.close()


def test_snapshot_at_max_opn(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_opn=5)
    f.open()
    for i in range(7):
        f.set_bit(0, i)
    # After crossing max_opn the WAL was folded into a snapshot.
    assert f.storage.op_n < 5
    g = reopen(f)
    assert g.row_count(0) == 7
    g.close()


def test_row_dense_and_row(frag):
    cols = [0, 31, 32, 1000, SLICE_WIDTH - 1]
    for c in cols:
        frag.set_bit(7, c)
    words = frag.row_dense(7)
    from pilosa_tpu.ops import bitwise as bw

    assert bw.np_count(words) == len(cols)
    np.testing.assert_array_equal(bw.unpack_positions(words), np.array(cols, dtype=np.uint64))
    # row() returns global columns for this slice (slice 0 → same values).
    assert frag.row(7).to_array().tolist() == cols
    # mutation invalidates the dense row cache
    frag.set_bit(7, 5)
    assert bw.np_count(frag.row_dense(7)) == len(cols) + 1


def test_row_for_nonzero_slice(tmp_path):
    f = Fragment(str(tmp_path / "2"), "i", "f", "standard", 2)
    f.open()
    f.set_bit(1, 2 * SLICE_WIDTH + 5)  # global column in slice 2
    assert f.row(1).to_array().tolist() == [2 * SLICE_WIDTH + 5]
    f.close()


def test_import_bits_and_count(frag):
    rows = np.repeat(np.arange(10, dtype=np.uint64), 100)
    cols = np.tile(np.arange(100, dtype=np.uint64) * 7, 10)
    frag.import_bits(rows, cols)
    assert frag.count() == 1000
    for r in range(10):
        assert frag.row_count(r) == 100
    assert frag.max_row() == 9


def test_top_basic(frag):
    # row 0: 3 bits, row 1: 2 bits, row 2: 1 bit
    for r, n in [(0, 3), (1, 2), (2, 1)]:
        for c in range(n):
            frag.set_bit(r, c)
    frag.recalculate_cache()
    top = frag.top(TopOptions(n=2))
    assert [(p.id, p.count) for p in top] == [(0, 3), (1, 2)]


def test_top_with_src_intersection(frag):
    for c in range(10):
        frag.set_bit(0, c)  # 0..9
    for c in range(5, 20):
        frag.set_bit(1, c)  # 5..19
    for c in range(100, 103):
        frag.set_bit(2, c)
    frag.recalculate_cache()
    src = roaring.Bitmap(range(0, 8))  # intersects row0 by 8, row1 by 3
    top = frag.top(TopOptions(n=5, src=src))
    assert [(p.id, p.count) for p in top] == [(0, 8), (1, 3)]


def test_top_row_ids_no_truncate(frag):
    for r in range(5):
        for c in range(r + 1):
            frag.set_bit(r, c)
    frag.recalculate_cache()
    top = frag.top(TopOptions(n=1, row_ids=[0, 3]))
    assert {p.id for p in top} == {0, 3}


def test_top_min_threshold(frag):
    for r, n in [(0, 10), (1, 2)]:
        for c in range(n):
            frag.set_bit(r, c)
    frag.recalculate_cache()
    top = frag.top(TopOptions(n=10, min_threshold=5))
    assert [p.id for p in top] == [0]


def test_top_tanimoto(frag):
    # Reference fragment_test.go Tanimoto case: rows with known overlaps.
    for c in [1, 2, 3]:
        frag.set_bit(100, c)
    for c in [1, 2]:
        frag.set_bit(101, c)
    for c in [1, 2, 3, 4]:
        frag.set_bit(102, c)
    frag.recalculate_cache()
    src = roaring.Bitmap([1, 2, 3])
    top = frag.top(TopOptions(tanimoto_threshold=70, src=src))
    got = {p.id: p.count for p in top}
    # row100: count 3/ union 3 → 100%; row102: 3/4 → 75%; row101: 2/3 → 67% (excluded)
    assert got == {100: 3, 102: 3}


def test_blocks_and_checksum_invalidation(frag):
    frag.set_bit(0, 1)
    frag.set_bit(150, 1)  # second block (rows 100-199)
    blocks = dict(frag.blocks())
    assert set(blocks.keys()) == {0, 1}
    chk_all = frag.checksum()
    frag.set_bit(0, 2)
    blocks2 = dict(frag.blocks())
    assert blocks2[1] == blocks[1]  # untouched block unchanged
    assert blocks2[0] != blocks[0]
    assert frag.checksum() != chk_all


def test_block_data(frag):
    frag.set_bit(105, 3)
    frag.set_bit(105, 9)
    rows, cols = frag.block_data(1)
    assert rows.tolist() == [105, 105]
    assert cols.tolist() == [3, 9]


def test_merge_block_majority(frag):
    # Local has {a}, two remotes have {a,b} and {b}: majority(2 of 3) → {a?, b}
    # a on 2 nodes → keep; b on 2 nodes → set locally.
    frag.set_bit(0, 1)  # a
    local = frag.block_data(0)
    remote1 = (np.array([0, 0], np.uint64), np.array([1, 2], np.uint64))  # a, b
    remote2 = (np.array([0], np.uint64), np.array([2], np.uint64))  # b
    diffs = frag.merge_block(0, [local, remote1, remote2])
    assert frag.contains(0, 1) and frag.contains(0, 2)
    # remote2's diff should say: set a, clear nothing
    (set_r, set_c), (clr_r, clr_c) = diffs[2]
    assert set_c.tolist() == [1] and clr_c.tolist() == []
    # remote1 already canonical
    (s1r, s1c), (c1r, c1c) = diffs[1]
    assert s1c.tolist() == [] and c1c.tolist() == []


def test_write_read_roundtrip(tmp_path, frag):
    for r in range(3):
        for c in range(10 * (r + 1)):
            frag.set_bit(r, c)
    import io

    buf = io.BytesIO()
    frag.write_to(buf)
    g = Fragment(str(tmp_path / "restored"), "i", "f", "standard", 0, cache_type="ranked")
    g.open()
    g.read_from(buf.getvalue())
    assert g.count() == frag.count()
    assert g.row_count(2) == 30
    assert [p.id for p in g.top(TopOptions(n=1))] == [2]
    g.close()


def test_cache_sidecar_persistence(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, cache_type="ranked")
    f.open()
    for c in range(50):
        f.set_bit(9, c)
    f.close()
    assert os.path.exists(f.cache_path)
    g = Fragment(f.path, "i", "f", "standard", 0, cache_type="ranked")
    g.open()
    g.recalculate_cache()
    assert g.cache.get(9) == 50
    g.close()


# -- cache unit tests -------------------------------------------------------


def test_rank_cache_threshold_and_trim():
    now = [0.0]
    c = RankCache(3, _now=lambda: now[0])
    for i, n in enumerate([10, 20, 30, 40, 50]):
        c.bulk_add(i, n)
    c.recalculate()
    assert [p.id for p in c.top()] == [4, 3, 2]
    assert c.threshold_value == 20  # count of first evicted rank
    # Adds below threshold ignored.
    c.add(99, 5)
    assert c.get(99) == 0


def test_rank_cache_debounce():
    now = [0.0]
    c = RankCache(10, _now=lambda: now[0])
    c.add(1, 5)
    assert [p.id for p in c.top()] == [1]
    c.bulk_add(2, 50)
    c.invalidate()  # within 10s — debounced
    assert [p.id for p in c.top()] == [1]
    now[0] += 11
    c.invalidate()
    assert [p.id for p in c.top()] == [2, 1]


def test_lru_cache_eviction():
    c = LRUCache(2)
    c.add(1, 10)
    c.add(2, 20)
    c.get(1)
    c.add(3, 30)  # evicts 2 (least recently used)
    assert c.get(2) == 0
    assert c.get(1) == 10 and c.get(3) == 30


def test_pairs_add_merge():
    a = [Pair(1, 10), Pair(2, 5)]
    b = [Pair(2, 7), Pair(3, 1)]
    merged = {p.id: p.count for p in pairs_add(a, b)}
    assert merged == {1: 10, 2: 12, 3: 1}


def test_new_cache_types():
    assert isinstance(cache_mod.new_cache("ranked", 10), RankCache)
    assert isinstance(cache_mod.new_cache("lru", 10), LRUCache)
    from pilosa_tpu.pilosa import ErrInvalidCacheType

    with pytest.raises(ErrInvalidCacheType):
        cache_mod.new_cache("bogus", 10)


def test_set_bits_matches_sequential(tmp_path):
    """Batched set_bits == sequential set_bit: same changed mask, same data,
    duplicates first-wins (fragment.go:371-413 semantics, batched)."""
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 50, size=300, dtype=np.uint64)
    cols = rng.integers(0, SLICE_WIDTH, size=300, dtype=np.uint64)
    rows[10], cols[10] = rows[0], cols[0]  # in-batch duplicate

    a = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    a.open()
    want = np.array([a.set_bit(int(r), int(c)) for r, c in zip(rows, cols)])
    b = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
    b.open()
    got = b.set_bits(rows, cols)
    assert np.array_equal(got, want)
    assert not got[10]  # duplicate of index 0
    assert np.array_equal(b.storage.to_array(), a.storage.to_array())
    # A second identical batch changes nothing.
    assert not b.set_bits(rows, cols).any()
    a.close()
    b.close()


def test_set_bits_wal_durable(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bits([1, 2, 3], [10, 20, 30])
    f = reopen(f)  # WAL replay, no snapshot happened (batch < max_opn)
    assert f.contains(1, 10) and f.contains(2, 20) and f.contains(3, 30)
    f.close()


def test_set_bits_length_mismatch(frag):
    with pytest.raises(ValueError):
        frag.set_bits([1, 2, 3], [10])


def test_set_bits_bulk_batch_snapshots(tmp_path):
    """A batch >= max_opn skips the WAL and snapshots once (import shape)."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_opn=10)
    f.open()
    rows = np.arange(20, dtype=np.uint64)
    cols = np.arange(20, dtype=np.uint64) * 7
    assert f.set_bits(rows, cols).all()
    assert f.storage.op_n == 0  # snapshotted, WAL empty
    f = reopen(f)
    assert f.contains(5, 35)
    assert f.row_count(5) == 1
    f.close()


def test_set_bits_mostly_duplicate_batch_uses_wal(tmp_path):
    """A big batch whose NEW bits are few appends WAL records instead of
    rewriting the fragment file (snapshot decision is on added count)."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_opn=100)
    f.open()
    rows = np.zeros(500, dtype=np.uint64)
    cols = np.arange(500, dtype=np.uint64)
    f.set_bits(rows, cols)  # >= max_opn -> snapshot, op_n == 0
    assert f.storage.op_n == 0
    cols2 = np.concatenate([cols, [1000, 1001, 1002]])
    ch = f.set_bits(np.zeros(len(cols2), dtype=np.uint64), cols2)
    assert ch.sum() == 3
    assert f.storage.op_n == 3  # 3 WAL records, no snapshot
    f = reopen(f)  # replayed from snapshot + WAL
    assert f.contains(0, 1002) and f.row_count(0) == 503
    f.close()


def test_mmap_open_bounded_rss(tmp_path):
    """mmap attach: opening a large fragment costs O(container headers) of
    heap, not O(file) — payloads stay in the page cache until touched
    (fragment.go:179-234).  Measured in a subprocess so interpreter noise
    can't mask the difference between the mmap and read-everything paths."""
    import subprocess
    import sys

    import numpy as np

    from pilosa_tpu import roaring

    # Build a ~256 MB snapshot fast: 32k full dense containers written
    # straight into the container map (an import loop would dominate the
    # test's runtime for no extra coverage).
    bm = roaring.Bitmap()
    full = np.full(roaring.BITMAP_N, 0xFFFFFFFFFFFFFFFF, dtype=np.uint64)
    for key in range(32768):
        c = roaring.Container(bitmap=full)
        c._n = 1 << 16
        bm.containers[key] = c
    path = tmp_path / "frag"
    with open(path, "wb") as f:
        bm.write_to(f)
    assert path.stat().st_size > 250 << 20

    child = """
import os, resource, sys
from pilosa_tpu.core.fragment import Fragment
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # post-import
f = Fragment(sys.argv[1], "i", "f", "standard", 0)
f.open()
assert f.storage.count() == 32768 * 65536
row = f.row_dense(0)          # touch ONE row's containers
assert row.any()
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(base, peak)             # KiB on linux
f.close()
"""
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    def deltas(mmap_on: str) -> int:
        env2 = dict(env, PILOSA_TPU_MMAP=mmap_on)
        out = subprocess.run(
            [sys.executable, "-c", child, str(path)],
            capture_output=True, text=True, env=env2, timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        base, peak = (int(x) * 1024 for x in out.stdout.split())
        return peak - base

    # Deltas over each child's own post-import baseline, so the ~200 MB
    # interpreter+numpy footprint (environment-dependent) cancels out.
    delta_mmap = deltas("1")
    # The guaranteed property: the mmap path opens the same file for
    # headers + one touched row only.
    assert delta_mmap < 64 << 20, f"mmap open delta {delta_mmap >> 20} MB"
    # Comparison half: the read path holds file bytes + parsed copies
    # (> the 256 MB file).  Under host memory pressure peak-RSS
    # accounting can under-report the read child (pages swapped before
    # the peak), so only assert the contrast when the read child
    # measured sanely — the bound above already proved the mmap claim.
    delta_read = deltas("0")
    if delta_read > 150 << 20:
        assert delta_read > delta_mmap + (100 << 20), (
            f"read {delta_read >> 20} MB vs mmap {delta_mmap >> 20} MB"
        )


def test_snapshot_reattaches_mmap(tmp_path):
    """After a snapshot the storage re-attaches zero-copy to the NEW file
    (fragment.go:1017-1057 re-mmap): heap containers become views again
    and the replaced inode's mapping is released."""
    import numpy as np

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    f.open()
    rng = np.random.default_rng(5)
    rows = rng.integers(0, 8, size=9000).astype(np.uint64)
    cols = rng.integers(0, 1 << 20, size=9000).astype(np.uint64)
    f.import_bits(rows, cols)  # import snapshots at the end
    assert f._storage_map is not None, "expected re-attached mmap"
    want = f.storage.count()
    dense = [c for c in f.storage.containers.values() if c.bitmap is not None]
    arrays = [c for c in f.storage.containers.values() if c.array is not None]
    # payloads are views into the new map, not heap copies
    assert all(not c.bitmap.flags.writeable for c in dense)
    assert all(not c.array.flags.writeable or len(c.array) == 0 for c in arrays)
    # the re-attached storage serves reads and writes (COW on top)
    mm_before = f._storage_map
    assert f.row_dense(int(rows[0])).any()
    assert f.set_bit(3, 777) or True
    assert f.contains(3, 777)
    # force another snapshot cycle: map swaps again, data stays intact
    f.snapshot()
    assert f._storage_map is not None and f._storage_map is not mm_before
    assert f.storage.count() in (want, want + 1)
    assert f.contains(3, 777)
    f.storage.check()
    f.close()


def test_post_close_reads_fail_loudly(tmp_path):
    """close() swaps storage for an empty bitmap to release the mmap; a
    late reader must get ErrFragmentClosed, not silently-empty rows."""
    from pilosa_tpu.pilosa import ErrFragmentClosed

    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 10)
    assert f.row_count(1) == 1
    f.close()
    for access in (
        lambda: f.row_dense(1),
        lambda: f.row(1),
        lambda: f.row_count(1),
        lambda: f.contains(1, 10),
        lambda: f.set_bit(1, 11),
        lambda: f.clear_bit(1, 10),
        lambda: f.import_bits([1], [12]),
        lambda: f.set_bits([1], [13]),
        lambda: f.set_bits([1] * 9, list(range(9))),  # vectorized branch
        lambda: f.count(),
        lambda: f.blocks(),
        lambda: f.block_data(0),
        lambda: f.snapshot(),  # would overwrite the file from empty storage
    ):
        with pytest.raises(ErrFragmentClosed):
            access()


def test_snapshot_skips_storage_reread_without_mmap(tmp_path, monkeypatch):
    """With PILOSA_TPU_MMAP=0 a snapshot must not re-read the file it just
    wrote (there is no map to re-attach)."""
    monkeypatch.setenv("PILOSA_TPU_MMAP", "0")
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(2, 20)
    calls = []
    orig = Fragment._map_storage
    monkeypatch.setattr(
        Fragment, "_map_storage", lambda self: calls.append(1) or orig(self)
    )
    f.snapshot()
    assert calls == []
    assert f.contains(2, 20)
    f.close()


class TestDirtyRowJournal:
    """The dirty-row journal behind warm-state repair: exact deltas for
    small writes, None (unenumerable) for bulk changes, eviction, and
    recreated fragments."""

    def test_exact_delta_set_clear(self, frag):
        g0 = frag.generation
        assert frag.rows_dirty_since(g0) == set()
        frag.set_bit(1, 10)
        frag.set_bit(2, 20)
        frag.clear_bit(1, 10)
        assert frag.rows_dirty_since(g0) == {1, 2}
        g1 = frag.generation
        frag.set_bits([5, 6, 5], [1, 2, 3])
        assert frag.rows_dirty_since(g1) == {5, 6}
        assert frag.rows_dirty_since(g0) == {1, 2, 5, 6}

    def test_batched_set_bits_large_path(self, frag):
        # >8 positions takes the vectorized branch; same journal contract.
        g0 = frag.generation
        rows = list(range(12))
        frag.set_bits(rows, [100 + r for r in rows])
        assert frag.rows_dirty_since(g0) == set(rows)

    def test_noop_writes_do_not_log(self, frag):
        frag.set_bit(3, 30)
        g = frag.generation
        frag.set_bit(3, 30)  # duplicate: no change, no generation bump
        frag.clear_bit(9, 90)  # absent: no change
        assert frag.generation == g
        assert frag.rows_dirty_since(g) == set()

    def test_bulk_import_unenumerable(self, frag):
        g0 = frag.generation
        frag.import_bits([7], [3])
        assert frag.rows_dirty_since(g0) is None
        # After the import, new small writes are enumerable again.
        g1 = frag.generation
        frag.set_bit(8, 80)
        assert frag.rows_dirty_since(g1) == {8}

    def test_journal_eviction_floors(self, frag, monkeypatch):
        from pilosa_tpu.core import fragment as frag_mod

        monkeypatch.setattr(frag_mod, "_DIRTY_LOG_MAX", 8)
        g0 = frag.generation
        for i in range(12):  # 12 distinct bits > log max 8
            frag.set_bit(i, 1000 + i)
        assert frag.rows_dirty_since(g0) is None  # evicted past g0
        g1 = frag.generation
        frag.set_bit(50, 5000)
        assert frag.rows_dirty_since(g1) == {50}  # recent span still exact

    def test_recreated_fragment_floor(self, tmp_path):
        from pilosa_tpu.core.fragment import Fragment

        f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
        f1.open()
        f1.set_bit(1, 1)
        g_old = f1.generation
        f1.close()
        f2 = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
        f2.open()
        # A consumer anchored on the OLD fragment's generation can never
        # enumerate a delta against the new one.
        assert f2.rows_dirty_since(g_old) is None
        f2.close()
