"""Kernel-layer property tests: JAX ops vs numpy ground truth.

The analog of the reference's asm-vs-Go equivalence tests
(roaring/assembly_test.go): every fused count kernel must agree with a
straightforward numpy popcount reference on random inputs.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from pilosa_tpu.ops import (
    WORDS_PER_SLICE,
    bit_and,
    bit_or,
    bit_xor,
    bit_andnot,
    count,
    count_and,
    count_or,
    count_xor,
    count_andnot,
    batch_intersection_count,
    make_range_mask,
    pack_positions,
    unpack_positions,
)
from pilosa_tpu.ops import bitwise as bw
from pilosa_tpu.ops import dispatch
from pilosa_tpu.pilosa import SLICE_WIDTH

W = 1024  # small word count for speed; tileable (1024 = 8*128)


def rand_words(rng, shape):
    return rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)


@pytest.mark.parametrize("seed", range(5))
def test_counts_match_numpy(seed):
    rng = np.random.default_rng(seed)
    a = rand_words(rng, (W,))
    b = rand_words(rng, (W,))
    assert int(count(jnp.asarray(a))) == bw.np_count(a)
    assert int(count_and(jnp.asarray(a), jnp.asarray(b))) == bw.np_count_and(a, b)
    assert int(count_or(jnp.asarray(a), jnp.asarray(b))) == bw.np_count_or(a, b)
    assert int(count_xor(jnp.asarray(a), jnp.asarray(b))) == bw.np_count_xor(a, b)
    assert int(count_andnot(jnp.asarray(a), jnp.asarray(b))) == bw.np_count_andnot(a, b)


def test_elementwise_ops(rng):
    a = rand_words(rng, (W,))
    b = rand_words(rng, (W,))
    np.testing.assert_array_equal(np.asarray(bit_and(jnp.asarray(a), jnp.asarray(b))), a & b)
    np.testing.assert_array_equal(np.asarray(bit_or(jnp.asarray(a), jnp.asarray(b))), a | b)
    np.testing.assert_array_equal(np.asarray(bit_xor(jnp.asarray(a), jnp.asarray(b))), a ^ b)
    np.testing.assert_array_equal(np.asarray(bit_andnot(jnp.asarray(a), jnp.asarray(b))), a & ~b)


def test_batched_counts(rng):
    a = rand_words(rng, (7, W))
    b = rand_words(rng, (7, W))
    got = np.asarray(count_and(jnp.asarray(a), jnp.asarray(b)))
    want = np.array([bw.np_count_and(a[i], b[i]) for i in range(7)])
    np.testing.assert_array_equal(got, want)


def test_batch_intersection_count(rng):
    rows = rand_words(rng, (5, W))
    src = rand_words(rng, (W,))
    got = np.asarray(batch_intersection_count(jnp.asarray(rows), jnp.asarray(src)))
    want = np.array([bw.np_count_and(rows[i], src) for i in range(5)])
    np.testing.assert_array_equal(got, want)


def test_dispatch_layer(rng):
    # On CPU CI this exercises the jnp fallback path of the dispatcher.
    a = rand_words(rng, (W,))
    b = rand_words(rng, (W,))
    assert int(dispatch.count(jnp.asarray(a))) == bw.np_count(a)
    assert int(dispatch.count_and(jnp.asarray(a), jnp.asarray(b))) == bw.np_count_and(a, b)


@pytest.mark.parametrize(
    "start,end",
    [(0, 0), (0, 32), (5, 9), (0, SLICE_WIDTH), (31, 33), (64, 64), (100, 1000), (SLICE_WIDTH - 1, SLICE_WIDTH)],
)
def test_make_range_mask(start, end):
    mask = make_range_mask(start, end)
    got = set(unpack_positions(mask).tolist())
    want = set(range(start, end))
    assert got == want


@pytest.mark.parametrize("seed", range(3))
def test_pack_unpack_roundtrip(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(0, 5000))
    pos = np.unique(rng.integers(0, SLICE_WIDTH, size=n, dtype=np.uint64))
    words = pack_positions(pos)
    back = unpack_positions(words)
    np.testing.assert_array_equal(back, pos)
    assert bw.np_count(words) == len(pos)


def test_gather_count_and_matches_numpy(rng):
    # Batched Count(Intersect(r1, r2)) over a row matrix — the headline
    # query path (executor.go:576-605 analog), jnp/XLA form.
    n_slices, n_rows, batch = 3, 7, 11
    rm = rand_words(rng, (n_slices, n_rows, W))
    pairs = rng.integers(0, n_rows, size=(batch, 2)).astype(np.int32)
    got = np.asarray(dispatch.gather_count_and(jnp.asarray(rm), jnp.asarray(pairs)))
    want = np.array(
        [
            sum(bw.np_count_and(rm[s, p0], rm[s, p1]) for s in range(n_slices))
            for p0, p1 in pairs
        ]
    )
    np.testing.assert_array_equal(got, want)


def test_pallas_partial_tile_math(rng):
    # The kernel body's reduction (`_partial_tile`) is pure jnp — verify it on
    # CPU against numpy.  (Pallas interpret mode hangs under the axon platform
    # plugin, so full-kernel runs are covered by the on-TPU test below and the
    # project verify drives, not interpret mode.)
    import jax
    from pilosa_tpu.ops import pallas_kernels as pk

    a = rand_words(rng, (1, W // 128, 128))
    tile = np.asarray(pk._partial_tile(jnp.asarray(a)))
    assert tile.shape == (8, 128)
    assert int(tile.sum()) == bw.np_count(a)


@pytest.mark.skipif(
    "not config.getoption('--run-tpu', default=False)",
    reason="full Pallas kernels only lower on real TPU (run with --run-tpu)",
)
def test_pallas_kernels_on_tpu(rng):
    import jax
    from pilosa_tpu.ops import pallas_kernels as pk

    assert jax.default_backend() == "tpu"
    a = rand_words(rng, (3, W))
    b = rand_words(rng, (3, W))
    src = rand_words(rng, (W,))
    got2 = np.asarray(pk.fused_count2("and", jnp.asarray(a), jnp.asarray(b)))
    got1 = np.asarray(pk.fused_count1(jnp.asarray(a)))
    got_shared = np.asarray(pk.fused_count2("and", jnp.asarray(a), jnp.asarray(src)))
    np.testing.assert_array_equal(got2, np.array([bw.np_count_and(a[i], b[i]) for i in range(3)]))
    np.testing.assert_array_equal(got1, np.array([bw.np_count(a[i]) for i in range(3)]))
    np.testing.assert_array_equal(got_shared, np.array([bw.np_count_and(a[i], src) for i in range(3)]))
    rm = rand_words(rng, (2, 5, W))
    pairs = rng.integers(0, 5, size=(4, 2)).astype(np.int32)
    got_g = np.asarray(pk.fused_gather_count2("and", jnp.asarray(rm), jnp.asarray(pairs)))
    want_g = np.array(
        [sum(bw.np_count_and(rm[s, p0], rm[s, p1]) for s in range(2)) for p0, p1 in pairs]
    )
    np.testing.assert_array_equal(got_g, want_g)
    got_r = np.asarray(pk.fused_resident_count2("and", jnp.asarray(rm), jnp.asarray(pairs)))
    np.testing.assert_array_equal(got_r, want_g)
    idx = rng.integers(0, 5, size=(4, 3)).astype(np.int32)
    idx[0, 1:] = idx[0, 0]  # padded short cover (OR-idempotent)
    got_or = np.asarray(pk.fused_gather_count_or(jnp.asarray(rm), jnp.asarray(idx)))
    np.testing.assert_array_equal(got_or, bw.np_gather_count_or_multi(rm, idx))
    for op in ("and", "andnot"):
        got_m = np.asarray(
            pk.fused_gather_count_multi(op, jnp.asarray(rm), jnp.asarray(idx))
        )
        np.testing.assert_array_equal(got_m, bw.np_gather_count_multi(op, rm, idx))


def test_validate_names():
    from pilosa_tpu.pilosa import validate_name, validate_label, ErrName, ErrLabel

    validate_name("a" * 65)
    validate_name("my-index_0")
    for bad in ("myindex\n", "A", "9x", "a" * 66, ""):
        with pytest.raises(ErrName):
            validate_name(bad)
    validate_label("ColumnID")
    with pytest.raises(ErrLabel):
        validate_label("col\n")


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_pair_gram_identities(rng, op):
    """The AND-Gram + count identities reproduce every pair op's counts
    (the MXU all-pairs strategy; exact int8->int32 accumulation)."""
    rm = rand_words(rng, (3, 6, W))
    pairs = rng.integers(0, 6, size=(9, 2)).astype(np.int32)
    G = np.asarray(bw.pair_gram(jnp.asarray(rm)))
    got = np.asarray(bw.gram_pair_counts(op, G, pairs))
    f = {"and": lambda a, b: a & b, "or": lambda a, b: a | b,
         "xor": lambda a, b: a ^ b, "andnot": lambda a, b: a & ~b}[op]
    want = np.array(
        [sum(bw.np_count(f(rm[s, p0], rm[s, p1])) for s in range(3)) for p0, p1 in pairs]
    )
    np.testing.assert_array_equal(got, want)


def test_gather_count_chunks_large_batches(rng, monkeypatch):
    """Batches beyond the SMEM prefetch budget are evaluated in chunks
    with identical results (observed hard failure at B=4096 on v5e).
    The Pallas gate is forced on and the kernels stubbed with the jnp
    forms so CI actually executes the chunk/concatenate logic."""
    from pilosa_tpu.ops.dispatch import _GATHER_BATCH_MAX

    chunk_sizes = []

    def fake_kernel(op, rm_, prs, interpret=False):
        chunk_sizes.append(int(prs.shape[0]))
        return bw.gather_count(op, rm_, prs)

    monkeypatch.setattr(dispatch, "use_pallas", lambda: True)
    monkeypatch.setattr(dispatch, "fused_gather_count2", fake_kernel)
    monkeypatch.setattr(dispatch, "fused_resident_count2", fake_kernel)

    n_slices, n_rows = 2, 5
    rm = rand_words(rng, (n_slices, n_rows, W))
    b = _GATHER_BATCH_MAX + 37
    pairs = rng.integers(0, n_rows, size=(b, 2)).astype(np.int32)
    got = np.asarray(
        dispatch.gather_count("and", jnp.asarray(rm), jnp.asarray(pairs), allow_gram=False)
    )
    assert got.shape == (b,)
    assert chunk_sizes == [_GATHER_BATCH_MAX, 37]  # chunking really ran
    for k in (0, _GATHER_BATCH_MAX - 1, _GATHER_BATCH_MAX, b - 1):
        p0, p1 = pairs[k]
        want = sum(bw.np_count_and(rm[s, p0], rm[s, p1]) for s in range(n_slices))
        assert got[k] == want


def test_gather_count_or_multi_matches_numpy(rng):
    # Fused time-quantum Range count: OR a per-query view cover, popcount,
    # sum over slices (time.go:95-167 + executor.go:498-554 analog).
    n_slices, n_rows, batch, vmax = 2, 9, 7, 4
    rm = rand_words(rng, (n_slices, n_rows, W))
    idx = rng.integers(0, n_rows, size=(batch, vmax)).astype(np.int32)
    # Short covers pad by repeating the first id (OR-idempotent).
    idx[0, 1:] = idx[0, 0]
    idx[1, 2:] = idx[1, 0]
    got = np.asarray(
        dispatch.gather_count_or_multi(jnp.asarray(rm), jnp.asarray(idx))
    )
    want = bw.np_gather_count_or_multi(rm, idx)
    np.testing.assert_array_equal(got, want)
    # Degenerate single-view cover equals a plain row count.
    one = np.asarray(
        dispatch.gather_count_or_multi(jnp.asarray(rm), jnp.asarray(idx[:, :1]))
    )
    want_one = np.array(
        [sum(bw.np_count(rm[s, idx[q, 0]]) for s in range(n_slices)) for q in range(batch)]
    )
    np.testing.assert_array_equal(one, want_one)


@pytest.mark.parametrize("op", ["and", "or", "andnot"])
def test_gather_count_multi_matches_numpy(rng, op):
    # N-operand fold counts (Count over 3+-operand Intersect/Union/
    # Difference trees) — jnp/XLA form vs numpy ground truth.
    n_slices, n_rows, batch, k = 2, 9, 6, 5
    rm = rand_words(rng, (n_slices, n_rows, W))
    idx = rng.integers(0, n_rows, size=(batch, k)).astype(np.int32)
    # Fold-idempotent padding: and/or repeat the first id, andnot a
    # non-first id.
    idx[0, 3:] = idx[0, 0] if op != "andnot" else idx[0, 1]
    got = np.asarray(
        dispatch.gather_count_multi(op, jnp.asarray(rm), jnp.asarray(idx))
    )
    want = bw.np_gather_count_multi(op, rm, idx)
    np.testing.assert_array_equal(got, want)


def test_fused_gather_count2_rowmajor_interpret(rng):
    """Row-major pipelined gather kernel (manual DMA double buffering) vs
    numpy ground truth, all four pair ops, interpret mode."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count2_rowmajor

    S, R, W, B = 3, 40, 2048, 17
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
    rm_t = np.ascontiguousarray(rm.transpose(1, 0, 2)).reshape(R, S, W // 128, 128)
    for op in ("and", "or", "xor", "andnot"):
        got = np.asarray(
            fused_gather_count2_rowmajor(
                op, jnp.asarray(rm_t), jnp.asarray(pairs), interpret=True
            )
        )
        a = rm[:, pairs[:, 0], :]
        b = rm[:, pairs[:, 1], :]
        r = {"and": a & b, "or": a | b, "xor": a ^ b, "andnot": a & ~b}[op]
        want = bw.np_popcount(r).reshape(S, B, -1).sum(axis=(0, 2))
        assert np.array_equal(got, want), op


def test_gather_count_tiled_4d_matches_3d(rng):
    """4D tiled row matrices give identical results to 3D logical ones
    through the public dispatch entry points."""
    from pilosa_tpu.ops import dispatch

    S, R, W, B = 2, 12, 1024, 9
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    rm4 = rm.reshape(S, R, W // 128, 128)
    pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
    idx = rng.integers(0, R, size=(B, 3), dtype=np.int32)
    for op in ("and", "or", "xor", "andnot"):
        a = np.asarray(dispatch.gather_count(op, jnp.asarray(rm), jnp.asarray(pairs)))
        b = np.asarray(dispatch.gather_count(op, jnp.asarray(rm4), jnp.asarray(pairs)))
        assert np.array_equal(a, b), op
    for op in ("and", "or", "andnot"):
        a = np.asarray(dispatch.gather_count_multi(op, jnp.asarray(rm), jnp.asarray(idx)))
        b = np.asarray(dispatch.gather_count_multi(op, jnp.asarray(rm4), jnp.asarray(idx)))
        assert np.array_equal(a, b), op


def test_pair_gram_chunked_matches_oneshot(rng):
    """The slice-streaming Gram builder (large matrices) must equal the
    one-shot unpack+matmul and the numpy ground truth, in both layouts."""
    S, R, W = 5, 9, 1024
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    g1 = np.asarray(bw.pair_gram(jnp.asarray(rm)))
    orig = bw.GRAM_ONESHOT_BYTES
    orig_step = bw.GRAM_STEP_BYTES
    bw.GRAM_ONESHOT_BYTES = 1  # force the scan path
    try:
        g2 = np.asarray(bw.pair_gram(jnp.asarray(rm)))
        g3 = np.asarray(bw.pair_gram(jnp.asarray(rm.reshape(S, R, W // 128, 128))))
        # Force word-axis subdivision too (tall-row-set regime): a tiny
        # step budget splits each slice into power-of-two chunks.
        bw.GRAM_STEP_BYTES = R * (W // 4) * 32
        g4 = np.asarray(bw.pair_gram(jnp.asarray(rm)))
        g5 = np.asarray(bw.pair_gram(jnp.asarray(rm.reshape(S, R, W // 128, 128))))
    finally:
        bw.GRAM_ONESHOT_BYTES = orig
        bw.GRAM_STEP_BYTES = orig_step
    want = np.zeros((R, R), dtype=np.int64)
    for i in range(R):
        for j in range(R):
            want[i, j] = sum(bw.np_count_and(rm[s, i], rm[s, j]) for s in range(S))
    assert np.array_equal(g1, want)
    assert np.array_equal(g2, want)
    assert np.array_equal(g3, want)
    assert np.array_equal(g4, want)
    assert np.array_equal(g5, want)


def test_gather_count_rowmajor_wrapper_parity(rng):
    """dispatch.gather_count_rowmajor (3D and tiled 4D inputs, including
    a batch larger than the chunk cap) must match slice-major
    dispatch.gather_count on the same data."""
    S, R, W = 3, 48, 1024
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    rm_t = np.ascontiguousarray(rm.transpose(1, 0, 2))
    rm_t4 = rm_t.reshape(R, S, W // 128, 128)
    import pilosa_tpu.ops.dispatch as dispatch_mod

    old = dispatch_mod._GATHER_BATCH_MAX
    dispatch_mod._GATHER_BATCH_MAX = 8  # force the concat path
    try:
        pairs = rng.integers(0, R, size=(21, 2), dtype=np.int32)
        for op in ("and", "or", "xor", "andnot"):
            want = np.asarray(
                dispatch.gather_count(op, jnp.asarray(rm), jnp.asarray(pairs),
                                      allow_gram=False)
            )
            for rmj in (rm_t, rm_t4):
                got = np.asarray(
                    dispatch.gather_count_rowmajor(op, jnp.asarray(rmj), jnp.asarray(pairs))
                )
                assert np.array_equal(got, want), (op, rmj.ndim)
    finally:
        dispatch_mod._GATHER_BATCH_MAX = old


def test_fused_gather_count_multi_rowmajor_interpret(rng):
    """Row-major K-operand fold kernel vs numpy ground truth."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_multi_rowmajor

    S, R, W, B, K = 3, 40, 2048, 11, 4
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    idx = rng.integers(0, R, size=(B, K), dtype=np.int32)
    rm_t = np.ascontiguousarray(rm.transpose(1, 0, 2)).reshape(R, S, W // 128, 128)
    for op in ("and", "or", "andnot"):
        got = np.asarray(
            fused_gather_count_multi_rowmajor(
                op, jnp.asarray(rm_t), jnp.asarray(idx), interpret=True
            )
        )
        want = bw.np_gather_count_multi(op, rm, idx)
        assert np.array_equal(got, want), op


def test_gather_count_multi_rowmajor_wrapper_parity(rng):
    """dispatch.gather_count_multi_rowmajor matches the slice-major
    dispatch on the same data (3D + tiled 4D row-major inputs)."""
    S, R, W, B, K = 2, 24, 1024, 9, 3
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    rm_t = np.ascontiguousarray(rm.transpose(1, 0, 2))
    idx = rng.integers(0, R, size=(B, K), dtype=np.int32)
    for op in ("and", "or", "andnot"):
        want = np.asarray(dispatch.gather_count_multi(op, jnp.asarray(rm), jnp.asarray(idx)))
        for rmj in (rm_t, rm_t.reshape(R, S, W // 128, 128)):
            got = np.asarray(
                dispatch.gather_count_multi_rowmajor(op, jnp.asarray(rmj), jnp.asarray(idx))
            )
            assert np.array_equal(got, want), (op, rmj.ndim)


# --- fused tree lane (arbitrary nested Count trees; executor.go:261-276) ---


def _rand_tree_arrays(rng, R, B, D):
    """Random perfect-tree programs: leaves int32[B, 2^D], opcodes
    int32[B, 2^D - 1] drawn over all five opcodes (incl. TREE_PASS)."""
    K = 1 << D
    leaves = rng.integers(0, R, size=(B, K), dtype=np.int32)
    opc = rng.integers(0, 5, size=(B, K - 1), dtype=np.int32)
    return leaves, opc


@pytest.mark.parametrize("seed", range(3))
def test_gather_count_tree_matches_numpy(seed):
    """jnp tree fold vs numpy ground truth on random programs, every
    depth bucket the executor emits (D=1..4), 3D and tiled 4D inputs."""
    rng = np.random.default_rng(seed)
    S, R, B = 3, 12, 7
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    rm4 = rm.reshape(S, R, W // 128, 128)
    for D in (1, 2, 3, 4):
        leaves, opc = _rand_tree_arrays(rng, R, B, D)
        want = bw.np_gather_count_tree(rm, leaves, opc)
        got = np.asarray(
            bw.gather_count_tree(jnp.asarray(rm), jnp.asarray(leaves), jnp.asarray(opc))
        )
        assert np.array_equal(got, want), D
        got4 = np.asarray(
            dispatch.gather_count_tree(
                jnp.asarray(rm4), jnp.asarray(leaves), jnp.asarray(opc)
            )
        )
        assert np.array_equal(got4, want), D


def test_fused_gather_count_tree_interpret(rng):
    """Pallas tree kernel vs numpy ground truth (interpret mode)."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_tree

    S, R, B, D = 2, 10, 5, 3
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    leaves, opc = _rand_tree_arrays(rng, R, B, D)
    got = np.asarray(
        fused_gather_count_tree(
            jnp.asarray(rm), jnp.asarray(leaves), jnp.asarray(opc), interpret=True
        )
    )
    assert np.array_equal(got, bw.np_gather_count_tree(rm, leaves, opc))


def test_gather_count_tree_chunks_large_batches(rng, monkeypatch):
    """The dispatch chunking for tree batches preserves results (same
    contract as the pair/multi chunk tests)."""
    from pilosa_tpu.ops import dispatch as dispatch_mod
    from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE

    S, R, B, D = 2, 8, 9, 2
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    leaves, opc = _rand_tree_arrays(rng, R, B, D)
    want = bw.np_gather_count_tree(rm, leaves, opc)
    # Shrink the fallback budget so the jnp path chunks (CPU suite).
    monkeypatch.setattr(
        "pilosa_tpu.pilosa.OR_MULTI_BUDGET_DEVICE", S * (1 << D) * W * 4 * 2
    )
    got = np.asarray(
        dispatch_mod.gather_count_tree(
            jnp.asarray(rm), jnp.asarray(leaves), jnp.asarray(opc)
        )
    )
    assert np.array_equal(got, want)


def test_numpy_engine_tree_matches_ground_truth(rng):
    """NumpyEngine's inline per-opcode tree fold (jax-free path) must
    equal the bitwise ground truth on random programs."""
    from pilosa_tpu.engine import NumpyEngine

    S, R, B, D = 2, 9, 11, 3
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    leaves, opc = _rand_tree_arrays(rng, R, B, D)
    got = NumpyEngine().gather_count_tree(rm, leaves, opc)
    assert got.tolist() == bw.np_gather_count_tree(rm, leaves, opc).tolist()


def test_fused_gather_src_counts_interpret(rng):
    """All-slice TopN scorer kernel vs numpy ground truth."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_src_counts

    S, R, K = 3, 10, 7
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    src = rng.integers(0, 1 << 32, size=(S, W), dtype=np.uint32)
    pos = rng.integers(0, R, size=(K,), dtype=np.int32)
    got = np.asarray(
        fused_gather_src_counts(
            jnp.asarray(rm), jnp.asarray(pos), jnp.asarray(src), interpret=True
        )
    )
    want = np.stack([
        np.array([bw.np_count(rm[s, p] & src[s]) for p in pos]) for s in range(S)
    ])
    assert np.array_equal(got, want)
    # dispatch fallback parity (jnp path on CPU)
    got_d = np.asarray(
        dispatch.topn_scorer_counts(jnp.asarray(rm), jnp.asarray(pos), jnp.asarray(src))
    )
    assert np.array_equal(got_d, want)
