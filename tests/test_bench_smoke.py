"""Smoke tests for the benchmark harness and examples.

The driver runs ``python bench.py`` at round end — a broken bench records
nothing, so every config must at least produce its JSON line on tiny
shapes (CPU backend).  Same for the getting-started example.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(env_extra, script="bench.py", timeout=240):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # keep TPU plugin site dirs out
    env["JAX_PLATFORMS"] = "cpu"
    # Skip the gcc-compiled reference-loop measurement (several seconds
    # of DRAM streaming per bench process); smoke shapes only check the
    # JSON contract, not the denominator's accuracy.
    env.setdefault("BENCH_REF_BYTES_PER_S", "2.38e10")
    env.update(env_extra)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, script)],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )
    assert out.returncode == 0, f"stderr:\n{out.stderr[-2000:]}"
    return out.stdout


@pytest.mark.parametrize(
    "cfg,extra",
    [
        ("intersect_count", {"BENCH_ITERS": "2", "BENCH_SLICES": "2", "BENCH_ROWS": "4", "BENCH_BATCH": "4"}),
        # Tier scoreboard forced on (shape env normally disables it so
        # big-shape runs can't leak into the 4k-row tier shapes).
        ("intersect_count", {"BENCH_ITERS": "2", "BENCH_SLICES": "2", "BENCH_ROWS": "4",
                             "BENCH_BATCH": "4", "BENCH_TIERS": "1"}),
        ("setbit", {"BENCH_OPS": "300"}),
        ("topn", {"BENCH_ITERS": "2", "BENCH_TOPN_ROWS": "8"}),
        ("union64", {"BENCH_ITERS": "3", "BENCH_SLICES": "2"}),
        ("timerange", {"BENCH_ITERS": "4", "BENCH_BATCH": "2"}),
        ("executor", {"BENCH_ITERS": "3", "BENCH_SLICES": "2", "BENCH_ROWS": "4",
                      "BENCH_BATCH": "4", "BENCH_BITS_PER_ROW": "50", "BENCH_THREADS": "2"}),
        ("range_executor", {"BENCH_ITERS": "3", "BENCH_SLICES": "2",
                            "BENCH_BATCH": "4", "BENCH_BITS": "200"}),
        # Mixed read/write tier: BENCH_SMOKE exercises the warm-state
        # REPAIR lane end-to-end (patch + rebuild A/B) on CPU.
        ("mixed", {"BENCH_SMOKE": "1"}),
        # Planner convergence tier: adaptive (door-loop plan_for) vs
        # pinned-lane baselines; asserts post-warmup lane agreement.
        ("planner", {"BENCH_SMOKE": "1"}),
        ("intersect_count_stream", {"BENCH_ITERS": "2", "BENCH_SLICES": "4",
                                    "BENCH_ROWS": "4", "BENCH_BATCH": "4",
                                    "BENCH_CHUNK_SLICES": "2"}),
        ("intersect_count_4krows", {"BENCH_ITERS": "2", "BENCH_SLICES": "2",
                                    "BENCH_ROWS": "64", "BENCH_BATCH": "4"}),
        ("topn_p50", {"BENCH_ITERS": "4", "BENCH_SLICES": "2", "BENCH_ROWS": "4"}),
    ],
)
def test_bench_config_emits_json(cfg, extra):
    stdout = _run({"BENCH_CONFIG": cfg, **extra})
    line = stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
    assert result["value"] > 0
    if extra.get("BENCH_TIERS") == "1":
        names = [t["tier"] for t in result["tiers"]]
        assert len(names) >= 4 and len(set(names)) == len(names)
        assert all("qps" in t and "bandwidth_util" in t for t in result["tiers"])
    if cfg == "mixed":
        names = [t["tier"] for t in result["tiers"]]
        assert names == [
            "mixed_95_5", "mixed_50_50", "mixed_50_50_b8", "mixed_50_50_b64"
        ]
        assert all(
            t["qps"] > 0 and t["rebuild_qps"] > 0 and "speedup" in t
            for t in result["tiers"]
        )
        # The smoke path must actually exercise the patch lane, and the
        # burst tiers must COALESCE: one deferred repair per write burst,
        # so repairs never grow with burst size.
        by = {t["tier"]: t for t in result["tiers"]}
        assert by["mixed_50_50"]["repairs"] > 0
        assert 0 < by["mixed_50_50_b8"]["repairs"] <= by["mixed_50_50"]["repairs"]
        assert 0 < by["mixed_50_50_b64"]["repairs"] <= by["mixed_50_50_b8"]["repairs"]
        # Per-(row, slice) granularity is live: the patch lane fetched
        # planes, bounded by rows x slices per repair.
        assert by["mixed_50_50"]["patch_planes"] > 0


def test_bench_writelane_emits_json():
    """The native write lane + streaming ingest bench: the in-run A/B
    contract (native beats the Python general lane on singletons, the
    parse+vectorized path on batches; the streaming tier sustains
    ingest with zero read-class sheds) is asserted INSIDE the bench —
    a nonzero exit would fail _run — so this smoke checks the JSON
    shape and re-states the headline invariants."""
    stdout = _run({"BENCH_CONFIG": "writelane", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "writelane_batched_native_vs_python"
    assert result["value"] > 1.0
    t = result["tiers"]
    assert t["singleton_native_vs_general"] > 1.0
    assert t["batched_native_vs_python"] > 1.0
    assert t["differential_ok"] is True
    assert t["stream_read_sheds"] == 0 and t["stream_reads_served"] > 0
    assert t["stream_pairs_per_s"] > 0


def test_bench_qcache_emits_json():
    """The query-result-cache bench must keep working: a Zipf-skewed
    repeated read mix with interleaved writes, cache on vs off on the
    same schedule.  The Zipf tier must actually HIT (skewed repeats are
    the whole point) and read-your-writes must hold in both tiers (a
    write to a touched fragment forces a miss; the next answer reflects
    it)."""
    stdout = _run({"BENCH_CONFIG": "qcache", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "qcache_read_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["qcache_on", "qcache_off"]
    by = {t["tier"]: t for t in result["tiers"]}
    assert by["qcache_on"]["hit_rate"] > 0.5
    assert by["qcache_on"]["hits"] > 0 and by["qcache_on"]["misses"] > 0
    # Cache off = no cache at all: nothing can hit.
    assert by["qcache_off"]["hit_rate"] == 0 and by["qcache_off"]["hits"] == 0
    # Read-your-writes + the numpy ground-truth gate held in BOTH tiers
    # (the bench itself asserts them; the fields record it).
    assert all(t["rw_ok"] and t["gate_ok"] for t in result["tiers"])
    assert all(t["ms_per_request"] > 0 for t in result["tiers"])
    # Tracing overhead guard ran in-run: head sampling at 0.01 must
    # cost <= 5% vs tracing disabled (the bench asserts; the fields
    # record the measured ratio).
    assert by["qcache_on"]["trace_ok"] is True
    assert "trace_overhead" in by["qcache_on"]


def test_bench_overload_emits_json():
    """The request-lifecycle QoS bench must keep working: a real HTTP
    server past saturation, QoS on (bounded admission + deadlines —
    shed rate > 0, goodput holds) vs off (unbounded, p99 degrades)."""
    stdout = _run({"BENCH_CONFIG": "overload", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "overload_goodput_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["presat", "overload_qos_on", "overload_qos_off"]
    by = {t["tier"]: t for t in result["tiers"]}
    # Overload really overloads AND the door really sheds.
    assert by["overload_qos_on"]["shed_rate"] > 0
    assert by["overload_qos_on"]["served"] > 0
    # QoS off admits everything: nothing is shed, everything is served.
    assert by["overload_qos_off"]["shed_rate"] == 0
    assert all(t["goodput_qps"] > 0 for t in result["tiers"])


def test_bench_tenancy_emits_json():
    """The multi-tenant hostile-neighbor bench must keep working: a
    polite tenant's isolated p99 baseline, then a hostile flood at 2x
    the door's depth with fair-share isolation ON (polite p99 within
    1.5x baseline, zero polite sheds, hostile really sheds — all
    asserted in-run) and OFF (the A/B degradation is recorded)."""
    stdout = _run({"BENCH_CONFIG": "tenancy", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "tenancy_polite_p99_ms" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["polite_baseline", "hostile_flood_on", "hostile_flood_off"]
    by = {t["tier"]: t for t in result["tiers"]}
    assert by["polite_baseline"]["served"] > 0
    # The bench asserted these in-run; the fields record it.
    on = by["hostile_flood_on"]
    assert on["polite"]["shed"] == 0 and on["polite"]["served"] > 0
    assert on["hostile"]["shed"] > 0
    # The /debug/tenants scrape rode along: the door saw both tenants.
    assert on["door"]["polite"]["admitted"] > 0
    assert on["door"]["hostile"]["shed"] > 0
    assert result["vs_baseline"] <= 1.5


def test_bench_replica_emits_json():
    """The replicated-serving-groups bench must keep working: group
    subprocesses behind out-of-process routers, read QPS at 1 vs N
    groups + a router-off direct baseline, with cross-group
    read-your-writes and failover (reads survive a killed group, writes
    503 until quorate) asserted in-run.  The scaling RATIO is recorded,
    not asserted — it needs physical cores the CI box may not have
    (the ``cpus`` field disambiguates)."""
    stdout = _run({"BENCH_CONFIG": "replica", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "replica_read_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["direct_1g", "router_1g", "router_2g"]
    by = {t["tier"]: t for t in result["tiers"]}
    assert all(t["read_qps"] > 0 and t["served"] > 0 for t in result["tiers"])
    # The bench asserted these in-run; the fields record it.
    assert by["router_2g"]["rw_ok"] is True
    assert by["router_2g"]["failover_ok"] is True
    assert by["router_2g"]["failovers"] >= 1
    assert by["router_2g"]["write_fanout"] >= 1  # schema + import + probe write
    assert result["scaling_1_to_2"] > 0 and result["cpus"] >= 1


def test_bench_multicore_emits_json():
    """The multi-core host-serving bench must keep working: a real CLI
    server at 1 vs 2 workers (in-process pool threads on free-threaded
    builds, SO_REUSEPORT processes on GIL builds) driven from 1/2/4
    client threads, plus the serve-lane-breadth A/B (native multi-frame
    / tree / Range one-crossing lanes vs the Python general lane,
    byte-parity + speedup > 1 asserted in-run).  The worker-scaling
    RATIO is asserted in-run only on a multi-core host; a 1-cpu box
    records the ratio and the skip reason (``cpus`` disambiguates)."""
    stdout = _run({"BENCH_CONFIG": "multicore", "BENCH_SMOKE": "1"}, timeout=600)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "multicore_read_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["serve_1w", "clients_1", "clients_2", "clients_4",
                     "breadth_multiframe", "breadth_tree", "breadth_range"]
    by = {t["tier"]: t for t in result["tiers"]}
    for t in ("serve_1w", "clients_1", "clients_2", "clients_4"):
        assert by[t]["read_qps"] > 0 and by[t]["served"] > 0
    # The breadth A/B asserted parity + win in-run; the fields record it.
    for t in ("breadth_multiframe", "breadth_tree", "breadth_range"):
        assert by[t]["speedup"] > 1.0
        assert by[t]["native_ms"] > 0 and by[t]["python_ms"] > 0
    assert result["scaling_1_to_2"] > 0 and result["cpus"] >= 1
    assert result["worker_mode"] in ("threads", "processes")
    if result["cpus"] == 1:
        assert result["scaling_skip"]  # ratio assert skipped WITH a reason


def test_bench_recovery_emits_json():
    """The durable-write-log recovery bench must keep working: 3 group
    subprocesses behind a durable-WAL CLI router, a group SIGKILLed
    mid-stream with writes still committing on the degraded quorum
    (zero failed writes asserted in-run), then a restart whose WAL
    suffix replay converges and rejoins reads."""
    stdout = _run({"BENCH_CONFIG": "recovery", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "recovery_write_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["writes_3g", "writes_2g", "catchup"]
    by = {t["tier"]: t for t in result["tiers"]}
    # The headline: NO failed writes with a group down (the old
    # full-set quorum rule 503'd every one of these).
    assert by["writes_2g"]["failed_batches"] == 0
    assert by["writes_2g"]["write_qps"] > 0
    assert by["writes_3g"]["failed_batches"] == 0
    # Catch-up really replayed the missed suffix and converged.
    assert by["catchup"]["converged"] is True
    assert by["catchup"]["rejoined_reads"] is True
    assert by["catchup"]["replayed"] >= by["catchup"]["lag_at_restart"]
    assert by["catchup"]["catchup_s"] > 0
    assert by["catchup"]["wal"]["durable"] is True
    assert result["catchup_s"] > 0 and result["cpus"] >= 1


def test_bench_resync_emits_json():
    """The automated-resync bench: a BLANK group joins a loaded
    2-group cluster behind a durable-WAL CLI router, self-heals via
    the digest-diff fragment stream, and rejoins with zero failed
    writes during the resync and digest convergence asserted in-run."""
    stdout = _run({"BENCH_CONFIG": "resync", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "resync_rejoin_s" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["load", "rejoin"]
    by = {t["tier"]: t for t in result["tiers"]}
    assert by["rejoin"]["failed_writes_during_resync"] == 0
    assert by["rejoin"]["writes_during_resync"] > 0
    assert by["rejoin"]["converged"] is True
    assert by["rejoin"]["bytes_streamed"] > 0
    assert by["rejoin"]["resync_fragments"] >= 1
    assert result["cpus"] >= 1


def test_bench_shard_emits_json():
    """The partitioned-replica-groups bench: write throughput through
    one shard vs two (separate subprocess groups, separate sequencer
    spaces), then a LIVE RESHARD splitting the slice space under
    concurrent write load — zero failed writes and digest convergence
    (moved range only on the new group) asserted in-run.  The write
    scaling RATIO is recorded under BENCH_SMOKE, asserted only on a
    real multi-core run (``scaling_asserted``/``skip_reason`` say
    which)."""
    stdout = _run({"BENCH_CONFIG": "shard", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "shard_write_qps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["router_1s", "router_2s", "reshard"]
    by = {t["tier"]: t for t in result["tiers"]}
    assert by["router_1s"]["write_qps"] > 0 and by["router_1s"]["served"] > 0
    assert by["router_2s"]["write_qps"] > 0 and by["router_2s"]["served"] > 0
    # The bench asserted these in-run; the fields record it.
    assert by["reshard"]["failed_writes"] == 0
    assert by["reshard"]["writes_during_reshard"] > 0
    assert by["reshard"]["moved_fragments"] >= 1
    assert by["reshard"]["map_epoch"] == 1
    assert by["reshard"]["fence_ms"] >= 0
    assert result["scaling_1s_to_2s"] > 0 and result["cpus"] >= 1
    if not result["scaling_asserted"]:
        assert result["skip_reason"]  # skipped WITH a reason, never silently


def test_star_trace_example_runs():
    stdout = _run({}, script=os.path.join("examples", "star_trace.py"))
    assert "top stargazers:" in stdout and "user 1 attrs:" in stdout


def test_graft_entry_dryrun_smoke():
    """The driver's multichip dryrun must keep working (4 virtual devices
    keeps it quick; the driver runs 8)."""
    import subprocess

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("JAX_PLATFORMS", None)  # the script pins its own CPU mesh
    # The suite's conftest exports XLA_FLAGS for the in-process tests; if
    # it leaks into the subprocess the script skips its own CPU pin
    # (device count pre-set) and a remote-TPU sitecustomize hook can hang
    # the run looking for an accelerator.
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"), "4"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=280,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dryrun_multichip OK" in out.stdout


def test_graft_entry_compiles_single_chip():
    """entry() must stay jittable (driver compile-check analog)."""
    import subprocess

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import __graft_entry__ as g, jax;"
        "fn, args = g.entry();"
        "out = jax.jit(fn)(*args);"
        "print('entry OK', [getattr(o, 'shape', None) for o in out])"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "entry OK" in out.stdout


def test_bench_lockstep_emits_json():
    stdout = _run(
        {"BENCH_CONFIG": "lockstep", "BENCH_ITERS": "6", "BENCH_BATCH": "4",
         "BENCH_THREADS": "2"},
        timeout=300,
    )
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "lockstep_service_qps" and result["value"] > 0


def test_bench_lockstep_coalesce_emits_json():
    """The request-coalescing bench path must keep working: both tiers
    (coalesced batch replay vs one entry per request) run a real 2-rank
    job and emit per-request overhead."""
    stdout = _run({"BENCH_CONFIG": "lockstep_coalesce", "BENCH_SMOKE": "1",
                   "BENCH_ITERS": "8", "BENCH_THREADS": "2"},
                  timeout=360)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "lockstep_coalesce_rps" and result["value"] > 0
    names = [t["tier"] for t in result["tiers"]]
    assert names == ["coalesce_on", "coalesce_off"]
    assert all(t["rps"] > 0 and t["per_request_ms"] > 0 for t in result["tiers"])


def test_bench_bulk_smoke():
    """The device-build bulk door vs streamed ingest A/B: the digest
    parity and Arrow round-trip contracts are asserted INSIDE the bench
    (a nonzero exit fails _run); BENCH_SMOKE relaxes only the 5x
    throughput gate, which tiny shapes can't meaningfully hold."""
    stdout = _run({"BENCH_CONFIG": "bulk", "BENCH_SMOKE": "1"}, timeout=300)
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["metric"] == "bulk_build_vs_streamed_ingest"
    t = result["tiers"]
    assert t["bulk_pairs_per_s"] > 0 and t["stream_pairs_per_s"] > 0
    assert t["digest_equal"] is True
    assert t["arrow_roundtrip_bytes"] > 0


def test_bench_executor_gather_smoke():
    stdout = _run({
        "BENCH_CONFIG": "executor_gather", "BENCH_ROWS": "32",
        "BENCH_SLICES": "2", "BENCH_BATCH": "8", "BENCH_ITERS": "2",
        "BENCH_BITS_PER_ROW": "5",
    })
    result = json.loads(stdout.strip().splitlines()[-1])
    assert result["value"] > 0


def test_refloop_bench_compiles_and_runs(tmp_path):
    """The measured CPU stand-in for the reference's hot loop
    (native/refloop_bench.c = popcntAndSliceAsm semantics) must build
    with the baked toolchain and emit its JSON line."""
    import shutil
    import subprocess as sp

    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    exe = tmp_path / "refloop"
    built = sp.run(
        [cc, "-O2", "-mpopcnt", "-o", str(exe),
         os.path.join(REPO, "native", "refloop_bench.c")],
        capture_output=True, text=True,
    )
    if built.returncode != 0:
        if "mpopcnt" in built.stderr:  # non-x86 host: capability gap
            pytest.skip("-mpopcnt unsupported on this arch")
        raise AssertionError(built.stderr[-1000:])
    out = sp.run([str(exe)], capture_output=True, text=True, timeout=120, check=True)
    d = json.loads(out.stdout.strip())
    assert d["bytes_per_s"] > 1e8 and d["pair_qps_1slice"] > 0
