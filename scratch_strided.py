"""Strided-DMA gather: one descriptor per operand from a SLICE-MAJOR matrix."""
import functools, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def make_strided(depth=2):
    def _kernel(op, pairs_ref, rm_ref, out_ref, buf, sems):
        q = pl.program_id(0)
        n_q = pl.num_programs(0)

        def dma(i, o):
            return pltpu.make_async_copy(
                rm_ref.at[:, pairs_ref[i, o]],  # [S, sub, 128] strided
                buf.at[i % depth, o],
                sems.at[i % depth, o],
            )

        @pl.when(q == 0)
        def _():
            for d in range(depth - 1):
                for o in range(2):
                    dma(d, o).start()

        @pl.when(q + depth - 1 < n_q)
        def _():
            for o in range(2):
                dma(q + depth - 1, o).start()

        for o in range(2):
            dma(q, o).wait()
        a = buf[q % depth, 0]
        b = buf[q % depth, 1]
        pc = lax.population_count(a & b).astype(jnp.int32)
        s_, sub_, _ = pc.shape
        out_ref[0] = pc.reshape(s_ * sub_ // 8, 8, _LANES).sum(axis=0)

    @functools.partial(jax.jit, static_argnames=("op",))
    def gather(op, rm4, pairs):
        n_slices, n_rows, sub = rm4.shape[:3]
        b = pairs.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, pr: (q, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((depth, 2, n_slices, sub, _LANES), jnp.uint32),
                pltpu.SemaphoreType.DMA((depth, 2)),
            ],
        )
        out = pl.pallas_call(
            functools.partial(_kernel, op),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        )(pairs, rm4)
        return out.sum(axis=(1, 2))

    return gather


from pilosa_tpu.roaring import _POPCNT8

# correctness small
S, R, W, B = 4, 256, 32768, 64
rng = np.random.default_rng(7)
rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
drm = jax.device_put(rm.reshape(S, R, W // 128, 128))
fn = make_strided(2)
got = np.asarray(fn("and", drm, jax.device_put(pairs)))
want = _POPCNT8[(rm[:, pairs[:, 0], :] & rm[:, pairs[:, 1], :]).view(np.uint8)].reshape(S, B, -1).sum(axis=(0, 2))
assert np.array_equal(got, want), "mismatch"
print("strided correct")

for S2 in (4, 16):
    R2 = 4096
    @functools.partial(jax.jit, static_argnames=())
    def gen(key):
        return jax.random.bits(key, (S2, R2, W // 128, 128), jnp.uint32)
    drm2 = gen(jax.random.PRNGKey(0))
    ITERS = 64 if S2 == 4 else 16
    prs = rng.integers(0, R2, size=(ITERS, 256, 2), dtype=np.int32)
    dp = jax.device_put(prs)
    for d in (2, 4):
        fn2 = make_strided(d)
        @jax.jit
        def stream(rm_, ps):
            def step(c, p):
                return c, fn2("and", rm_, p)
            out = lax.scan(step, 0, ps)[1]
            return out, out.sum()
        _, dg = stream(drm2, dp); np.asarray(dg)
        dts = []
        for _ in range(3):
            t0 = time.perf_counter(); _, dg = stream(drm2, dp); np.asarray(dg)
            dts.append(time.perf_counter() - t0)
        dt = min(dts)
        qps = ITERS * 256 / dt
        bw = ITERS * 256 * 2 * S2 * W * 4 / dt / 819e9
        print(f"strided S={S2} d={d}: {qps:,.0f} q/s, util={bw:.3f}")
