"""Benchmark: PQL Intersect+Count throughput (the north-star metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md config 1/4 shape): a Star-Trace style index — a
device-resident row matrix of ``n_slices`` slices × ``n_rows`` rows of
packed SLICE_WIDTH-bit bitmaps — served a stream of
``Count(Intersect(Bitmap(r1), Bitmap(r2)))`` queries.  Queries run in
batches through ONE fused computation per batch: on TPU a Pallas kernel
that scalar-prefetches the row-id pairs and streams each operand row
HBM→VMEM exactly once (gather → AND → popcount → reduce with no
materialized intermediates — the TPU-native form of the reference's
per-slice goroutine fan-out + SIMD loop, executor.go:1115-1244 +
roaring/assembly_amd64.s:60-77).

Timing methodology: all ``iters`` batches are chained inside one jitted
``lax.scan`` and the timer stops only when the results have been fetched
to host memory.  This is deliberate: the TPU here sits behind a remote
tunnel with ~70 ms round-trip latency and unreliable
``block_until_ready`` semantics, so per-batch host dispatch would
measure the tunnel, not the device, and blocking on the last output
alone under-measures.  One dispatch + explicit host fetch amortizes the
round trip across the whole query stream and cannot finish early.

vs_baseline: ratio against a single-threaded numpy popcount loop on the
same data on this host's CPU — the stand-in for the reference's Go+SIMD
single-node path (the reference publishes no numbers in-tree; see
BASELINE.md).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def bench_setbit() -> dict:
    """Config 2: SetBit op/sec through the fragment write path (the
    `pilosa bench --operation set-bit` analog, ctl/bench.go:71-102)."""
    n = int(os.environ.get("BENCH_OPS", "20000"))
    import tempfile

    from pilosa_tpu.core.fragment import Fragment

    rng = np.random.default_rng(7)
    rows = rng.integers(0, 1000, size=n)
    cols = rng.integers(0, 1 << 20, size=n)
    with tempfile.TemporaryDirectory() as d:
        f = Fragment(os.path.join(d, "frag"), "i", "f", "standard", 0)
        f.open()
        t0 = time.perf_counter()
        for r, c in zip(rows.tolist(), cols.tolist()):
            f.set_bit(r, c)
        dt = time.perf_counter() - t0
        f.close()
    return {
        "metric": "setbit_ops_per_sec",
        "value": round(n / dt, 1),
        "unit": "SetBit/sec (single fragment, WAL on)",
        "vs_baseline": 1.0,  # host-side path; no device analog
    }


def bench_topn() -> dict:
    """Config 3: TopN over a ranked frame — candidate scoring via the
    batched intersection-count kernel (fragment.go:493-625 analog)."""
    n_rows = int(os.environ.get("BENCH_TOPN_ROWS", "2048"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    import jax

    from pilosa_tpu.ops import dispatch
    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(3)
    rows = rng.integers(0, 1 << 32, size=(n_rows, WORDS_PER_SLICE), dtype=np.uint32)
    src = rng.integers(0, 1 << 32, size=(WORDS_PER_SLICE,), dtype=np.uint32)
    drows, dsrc = jax.device_put(rows), jax.device_put(src)
    np.asarray(dispatch.batch_intersection_count(drows, dsrc))  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = np.asarray(dispatch.batch_intersection_count(drows, dsrc))
    dt = (time.perf_counter() - t0) / iters
    from pilosa_tpu.roaring import _POPCNT8

    t0 = time.perf_counter()
    base = _POPCNT8[(rows & src).view(np.uint8)].reshape(n_rows, -1).sum(axis=1)
    base_dt = time.perf_counter() - t0
    assert np.array_equal(out, base)
    return {
        "metric": "topn_candidate_scan_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": f"rows/sec scored vs src ({n_rows} rows x 2^20 cols, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def bench_union64() -> dict:
    """Config 4: multi-slice Union+Count mapReduce over 64 slices."""
    n_slices = int(os.environ.get("BENCH_SLICES", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(4)
    a = rng.integers(0, 1 << 32, size=(n_slices, WORDS_PER_SLICE), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(n_slices, WORDS_PER_SLICE), dtype=np.uint32)

    @jax.jit
    def union_count(x, y):
        return jnp.sum(lax.population_count(jnp.bitwise_or(x, y)).astype(jnp.int64))

    da, db = jax.device_put(a), jax.device_put(b)
    int(union_count(da, db))  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        got = int(union_count(da, db))
    dt = (time.perf_counter() - t0) / iters
    from pilosa_tpu.roaring import _POPCNT8

    t0 = time.perf_counter()
    want = int(_POPCNT8[(a | b).view(np.uint8)].sum())
    base_dt = time.perf_counter() - t0
    assert got == want
    cols_per_sec = n_slices * (1 << 20) / dt
    return {
        "metric": "union_count_cols_per_sec",
        "value": round(cols_per_sec, 1),
        "unit": f"columns/sec unioned+counted ({n_slices} slices, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def bench_timerange() -> dict:
    """Config 5: time-quantum Range — OR-reduce the YMDH view cover of a
    1-year range (time.go:95-167 analog; ~15 views) then popcount."""
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    n_views = 15  # typical cover size for a 1-year [start, end) at YMDH
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(5)
    views = rng.integers(0, 1 << 32, size=(n_views, WORDS_PER_SLICE), dtype=np.uint32)

    @jax.jit
    def range_union_count(v):
        acc = lax.reduce(v, np.uint32(0), lax.bitwise_or, (0,))
        return jnp.sum(lax.population_count(acc).astype(jnp.int64))

    dv = jax.device_put(views)
    int(range_union_count(dv))
    t0 = time.perf_counter()
    for _ in range(iters):
        got = int(range_union_count(dv))
    dt = (time.perf_counter() - t0) / iters
    from pilosa_tpu.roaring import _POPCNT8

    t0 = time.perf_counter()
    acc = views[0].copy()
    for i in range(1, n_views):
        acc |= views[i]
    want = int(_POPCNT8[acc.view(np.uint8)].sum())
    base_dt = time.perf_counter() - t0
    assert got == want
    return {
        "metric": "timerange_union_views_per_sec",
        "value": round(n_views / dt, 1),
        "unit": f"views/sec OR-reduced+counted ({n_views}-view YMDH cover, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def main() -> None:
    cfg = os.environ.get("BENCH_CONFIG", "intersect_count")
    if cfg != "intersect_count":
        result = {
            "setbit": bench_setbit,
            "topn": bench_topn,
            "union64": bench_union64,
            "timerange": bench_timerange,
        }[cfg]()
        print(json.dumps(result))
        return
    n_slices = int(os.environ.get("BENCH_SLICES", "16"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "40"))
    # Bit density ~2^-k via AND of k random words (throughput over packed
    # words is density-independent; this just keeps counts realistic).
    density_k = int(os.environ.get("BENCH_DENSITY_K", "4"))

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    W = WORDS_PER_SLICE  # 32768 words = 2^20 bits per slice-row
    rng = np.random.default_rng(42)
    row_matrix = rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)
    for _ in range(density_k - 1):
        row_matrix &= rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)

    all_pairs = rng.integers(0, n_rows, size=(iters, batch, 2), dtype=np.int32)

    # ---- TPU path -------------------------------------------------------
    import jax
    from jax import lax

    from pilosa_tpu.ops import dispatch

    @jax.jit
    def run_stream(rm, pairs_stream):
        def step(carry, prs):
            return carry, dispatch.gather_count_and(rm, prs)

        return lax.scan(step, 0, pairs_stream)[1]

    drm = jax.device_put(row_matrix)
    dpairs = jax.device_put(all_pairs)
    # Warmup compiles and runs the full stream once; fetching to host is
    # the only reliable synchronization on this backend.
    out = np.asarray(run_stream(drm, dpairs))

    t0 = time.perf_counter()
    out = np.asarray(run_stream(drm, dpairs))
    dt = time.perf_counter() - t0
    qps = iters * batch / dt

    # ---- CPU numpy baseline (single-threaded popcount loop) -------------
    from pilosa_tpu.roaring import _POPCNT8

    base_iters = max(1, min(3, iters))
    t0 = time.perf_counter()
    base_out = None
    for i in range(base_iters):
        p = all_pairs[i]
        a = row_matrix[:, p[:, 0], :]
        b = row_matrix[:, p[:, 1], :]
        inter = a & b
        base_out = _POPCNT8[inter.view(np.uint8)].reshape(n_slices, batch, -1).sum(axis=(0, 2))
    base_dt = time.perf_counter() - t0
    base_qps = base_iters * batch / base_dt
    assert np.array_equal(out[base_iters - 1], base_out), "TPU/CPU result mismatch"

    result = {
        "metric": "intersect_count_qps",
        "value": round(qps, 1),
        "unit": f"queries/sec ({n_slices} slices x 2^20 cols, batch {batch}, backend {jax.default_backend()})",
        "vs_baseline": round(qps / base_qps, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
