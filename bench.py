"""Benchmark: PQL Intersect+Count throughput (the north-star metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md config 1/4 shape): a Star-Trace style index — a
device-resident row matrix of ``n_slices`` slices × ``n_rows`` rows of
packed SLICE_WIDTH-bit bitmaps — served a stream of
``Count(Intersect(Bitmap(r1), Bitmap(r2)))`` queries.  Queries run in
batches through ONE fused jit computation (gather rows → AND → popcount →
reduce over slices+words), which is the TPU-native form of the
reference's per-slice goroutine fan-out + SIMD loop.

vs_baseline: ratio against a single-threaded numpy popcount loop on the
same data on this host's CPU — the stand-in for the reference's Go+SIMD
single-node path (the reference publishes no numbers in-tree; see
BASELINE.md).  The numpy baseline uses the same vectorized
AND+LUT-popcount per query, which is competitive with the reference's
per-container loops.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    n_slices = int(os.environ.get("BENCH_SLICES", "16"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    # Bit density ~2^-k via AND of k random words (throughput over packed
    # words is density-independent; this just keeps counts realistic).
    density_k = int(os.environ.get("BENCH_DENSITY_K", "4"))

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    W = WORDS_PER_SLICE  # 32768 words = 2^20 bits per slice-row
    rng = np.random.default_rng(42)
    row_matrix = rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)
    for _ in range(density_k - 1):
        row_matrix &= rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)

    pairs = rng.integers(0, n_rows, size=(iters, batch, 2), dtype=np.int32)

    # ---- TPU path -------------------------------------------------------
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def query_batch(rm, prs):
        a = jnp.take(rm, prs[:, 0], axis=1)
        b = jnp.take(rm, prs[:, 1], axis=1)
        return jnp.sum(lax.population_count(jnp.bitwise_and(a, b)).astype(jnp.int32), axis=(0, 2))

    drm = jax.device_put(row_matrix)
    dpairs = [jax.device_put(pairs[i]) for i in range(iters)]
    # warmup/compile
    query_batch(drm, dpairs[0]).block_until_ready()

    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        out = query_batch(drm, dpairs[i])
    out.block_until_ready()
    dt = time.perf_counter() - t0
    qps = iters * batch / dt

    # ---- CPU numpy baseline (single-threaded popcount loop) -------------
    from pilosa_tpu.roaring import _POPCNT8

    base_iters = max(1, min(3, iters))
    t0 = time.perf_counter()
    for i in range(base_iters):
        p = pairs[i]
        a = row_matrix[:, p[:, 0], :]
        b = row_matrix[:, p[:, 1], :]
        inter = a & b
        _ = _POPCNT8[inter.view(np.uint8)].reshape(n_slices, batch, -1).sum(axis=(0, 2))
    base_dt = time.perf_counter() - t0
    base_qps = base_iters * batch / base_dt

    result = {
        "metric": "intersect_count_qps",
        "value": round(qps, 1),
        "unit": f"queries/sec ({n_slices} slices x 2^20 cols, batch {batch}, backend {jax.default_backend()})",
        "vs_baseline": round(qps / base_qps, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
